"""SLO engine: declarative per-QoS targets, multi-window burn rates.

The phi-accrual insight (Hayashibara, PAPERS.md) applied to service
health: a binary pass/fail gate answers "did the run break" after the
fact, but a control loop (ROADMAP item 4's demand-elastic serving)
needs a *continuous, threshold-per-consumer* signal while the run is
still going. This module turns the serving front-end's delivery and
shed streams into exactly that:

- an :class:`SloSpec` per QoS class declares the **latency target**
  (admission-to-delivery ticks a delivered stream must beat) and the
  **error budget** (the fraction of requests allowed to miss — shed
  for a service-caused reason, or delivered late);
- the engine folds every delivery/shed into per-tick good/error
  counts and evaluates **burn rates** over two rolling windows on the
  deterministic step clock (:data:`SLO_WINDOWS` — a short window that
  reacts, a long window that refuses to flap; the SRE multi-window
  discipline). ``burn = (error fraction in window) / budget``: burn 1
  means the class is consuming its budget exactly as fast as the spec
  allows;
- transitions are events, not logs: ``slo.burn`` when the short
  window first crosses burn 1 (the early warning), ``slo.breach``
  when BOTH windows burn at ≥ 1 (sustained — the autoscaler's regrow
  trigger), ``slo.recover`` when both fall back under 1. All three
  are emission-validated kinds in the one obs schema.

Policy lines, stated where they bind:

- ``tenant-rate`` sheds are **not** SLO errors: the per-tenant token
  bucket refusing a tenant that exceeds its own contract is the
  service *working*, not failing. Every other shed reason
  (``brownout:*``, ``admission-timeout``, ``backpressure:*``) counts.
- A breach is a *health observation*, never a campaign gate: the
  seeded overload cell is SUPPOSED to brown out best_effort — the
  breach firing there deterministically is the signal working, and
  the fair-weather cells firing zero alarms is the noise floor
  holding (both pinned by ``tests/test_slo.py``).

Everything is deterministic: integer tick counts, integer window
sums, burn rates rendered as rounded floats — same seed, byte-
identical ``health()`` snapshot.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Tuple

#: Multi-window burn-rate evaluation windows (ticks): (short, long).
#: The short window catches a fast burn within one admission-wait cap;
#: the long window must agree before a breach is declared, so a
#: one-burst blip can warn but never page. docs/observability.md
#: quotes these (drift-guarded).
SLO_WINDOWS: Tuple[int, int] = (32, 128)

#: Burn rate at/above which a window is considered burning: 1.0 means
#: errors consume the budget exactly as fast as the spec allows.
BREACH_BURN = 1.0

#: Minimum events (good + error) a window must hold before its burn
#: rate means anything: below this, burn reads 0 — one unlucky shed
#: among a handful of requests (or during the first few ticks before
#: the windows fill) must not page. Honestly stated: a class too
#: sparse to clear the floor can never breach; the floor is the
#: noise gate, not a loophole — sheds count as events, so a total
#: outage keeps the window full and burns at rate 1/budget.
MIN_WINDOW_EVENTS = 16


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One QoS class's service-level objective.

    ``latency_target_ticks``: a delivered stream whose admission-to-
    delivery latency exceeds this is an SLO error even though it
    delivered (late is wrong, per class). ``error_budget``: the
    fraction of the class's requests allowed to error inside a burn
    window before the class is breaching.
    """

    qos: str
    latency_target_ticks: int
    error_budget: float

    def __post_init__(self):
        if self.latency_target_ticks < 1:
            raise ValueError(
                f"latency_target_ticks must be >= 1, got "
                f"{self.latency_target_ticks}"
            )
        if not 0.0 < self.error_budget < 1.0:
            raise ValueError(
                f"error_budget must be in (0, 1), got "
                f"{self.error_budget}"
            )

    def to_json(self) -> dict:
        return {
            "latency_target_ticks": self.latency_target_ticks,
            "error_budget": self.error_budget,
        }


#: The shipped per-class SLOs. Latency targets sit well above the
#: fair-weather tails (interactive delivers in a handful of ticks at
#: 1x load) and well below the deadline budgets (400/1200/2400 — the
#: watchdog's hard wall): a stream can be an SLO error long before it
#: is a watchdog failure, which is the point — the burn signal leads
#: the failure. Budgets order strictest-class-strictest.
#: docs/observability.md quotes this table (drift-guarded).
DEFAULT_SLOS: Dict[str, SloSpec] = {
    "interactive": SloSpec("interactive", latency_target_ticks=64,
                           error_budget=0.02),
    "batch": SloSpec("batch", latency_target_ticks=160,
                     error_budget=0.10),
    "best_effort": SloSpec("best_effort", latency_target_ticks=320,
                           error_budget=0.25),
}

#: Shed reasons excluded from the error count: the service refusing a
#: client that broke its own contract is not a service error.
NON_SLO_SHED_REASONS = ("tenant-rate",)


class _ClassState:
    """Rolling burn-window state for one QoS class (all integers)."""

    def __init__(self, spec: SloSpec, windows: Tuple[int, ...]):
        self.spec = spec
        # per window: deque of (good, error) per closed tick + running
        # sums (bounded state — the windows are the only history)
        self.ticks = [deque(maxlen=w) for w in windows]
        self.good_sum = [0] * len(windows)
        self.err_sum = [0] * len(windows)
        # the CURRENT tick's accumulation (closed by evaluate())
        self.pending_good = 0
        self.pending_err = 0
        # full-run accounting
        self.good = 0
        self.errors = 0
        self.errors_by_reason: Dict[str, int] = {}
        self.burns = [0.0] * len(windows)
        self.worst_burn = 0.0
        self.breached = False
        self.breach_started: Optional[int] = None
        self.breaches = 0
        self.recoveries = 0
        self.burn_warnings = 0
        self.breached_ticks = 0
        self._warned = False

    def close_tick(self) -> None:
        for i, window in enumerate(self.ticks):
            if len(window) == window.maxlen:
                g, e = window[0]
                self.good_sum[i] -= g
                self.err_sum[i] -= e
            window.append((self.pending_good, self.pending_err))
            self.good_sum[i] += self.pending_good
            self.err_sum[i] += self.pending_err
            total = self.good_sum[i] + self.err_sum[i]
            if total < MIN_WINDOW_EVENTS:
                self.burns[i] = 0.0  # insufficient evidence
            else:
                rate = self.err_sum[i] / total
                self.burns[i] = rate / self.spec.error_budget
        self.pending_good = 0
        self.pending_err = 0
        if max(self.burns) > self.worst_burn:
            self.worst_burn = max(self.burns)


class SloEngine:
    """Per-QoS-class burn-rate evaluation on the step clock.

    Feed it ``observe_delivery`` / ``observe_shed`` as they happen and
    ``evaluate(now)`` once per tick (the serving front-end wires all
    three). ``recorder``/``metrics`` are the optional obs hooks — one
    event per *transition* (warn/breach/recover, never per tick) and
    the ``slo_*`` counters at the same sites.
    """

    def __init__(
        self,
        specs: Optional[Dict[str, SloSpec]] = None,
        windows: Tuple[int, int] = SLO_WINDOWS,
        recorder=None,
        metrics=None,
    ):
        from smi_tpu.serving.qos import QOS_CLASSES  # leaf; lazy for
        # import-order safety (obs loads before serving finishes init)

        if len(windows) != 2 or windows[0] >= windows[1]:
            raise ValueError(
                f"windows must be (short, long) with short < long, "
                f"got {windows}"
            )
        if any(w < 1 for w in windows):
            raise ValueError(f"windows must be >= 1 tick, got {windows}")
        self.specs = dict(specs if specs is not None else DEFAULT_SLOS)
        missing = [c for c in QOS_CLASSES if c not in self.specs]
        if missing:
            raise ValueError(
                f"SLO specs missing QoS class(es) {missing}; every "
                f"class needs a declared target"
            )
        unknown = [c for c in self.specs if c not in QOS_CLASSES]
        if unknown:
            # a misspelled class key would otherwise be silently
            # dropped — the exact outcome loud validation exists for
            raise ValueError(
                f"SLO specs name unknown QoS class(es) {unknown}; "
                f"known: {QOS_CLASSES}"
            )
        self.windows = tuple(int(w) for w in windows)
        self.recorder = recorder
        self.metrics = metrics
        self._classes: Dict[str, _ClassState] = {
            qos: _ClassState(self.specs[qos], self.windows)
            for qos in QOS_CLASSES
        }

    # -- observation ----------------------------------------------------

    def observe_delivery(self, qos: str, latency_ticks: int,
                         now: int) -> None:
        """One delivered stream: good if within the class's latency
        target, an SLO error (reason ``latency``) otherwise."""
        state = self._classes[qos]
        if latency_ticks <= state.spec.latency_target_ticks:
            state.pending_good += 1
            state.good += 1
        else:
            self._error(state, "latency")

    def observe_shed(self, qos: str, reason: str, now: int) -> None:
        """One named shed. ``tenant-rate`` is excluded (client-caused,
        see :data:`NON_SLO_SHED_REASONS`); every service-caused reason
        burns the budget under its leading token (``brownout``,
        ``admission-timeout``, ``backpressure``)."""
        if reason in NON_SLO_SHED_REASONS:
            return
        self._error(self._classes[qos], reason.split(":")[0])

    def _error(self, state: _ClassState, reason: str) -> None:
        state.pending_err += 1
        state.errors += 1
        state.errors_by_reason[reason] = (
            state.errors_by_reason.get(reason, 0) + 1
        )

    # -- evaluation -----------------------------------------------------

    def evaluate(self, now: int) -> None:
        """Close the tick: fold the pending counts into both windows,
        recompute burn rates, and emit warn/breach/recover transitions
        (events + counters at the transition, never per tick)."""
        short_w, long_w = self.windows
        for qos in sorted(self._classes):
            state = self._classes[qos]
            state.close_tick()
            if state.breached:
                state.breached_ticks += 1
            burn_short, burn_long = state.burns
            if not state.breached:
                if (burn_short >= BREACH_BURN
                        and burn_long >= BREACH_BURN):
                    state.breached = True
                    state.breach_started = now
                    state.breaches += 1
                    state._warned = False
                    self._emit("slo.breach", now, qos=qos, window=long_w,
                               rate=round(burn_long, 4),
                               budget=state.spec.error_budget)
                    self._count("slo_breaches_total", qos=qos)
                elif burn_short >= BREACH_BURN and not state._warned:
                    # the early warning: the short window is burning
                    # but the long window has not (yet) agreed
                    state._warned = True
                    state.burn_warnings += 1
                    self._emit("slo.burn", now, qos=qos, window=short_w,
                               rate=round(burn_short, 4))
                    self._count("slo_burn_warnings_total", qos=qos)
                elif burn_short < BREACH_BURN:
                    state._warned = False
            elif (burn_short < BREACH_BURN
                    and burn_long < BREACH_BURN):
                state.breached = False
                state.recoveries += 1
                state._warned = False
                self._emit(
                    "slo.recover", now, qos=qos,
                    breached_ticks=now - state.breach_started,
                )
                self._count("slo_recoveries_total", qos=qos)

    def _emit(self, kind: str, now: int, **fields) -> None:
        if self.recorder is not None:
            self.recorder.emit(kind, now, **fields)

    def _count(self, name: str, **labels) -> None:
        if self.metrics is not None:
            self.metrics.counter(name, **labels).inc()

    # -- the health snapshot --------------------------------------------

    @property
    def breached(self) -> bool:
        """Any class currently breaching."""
        return any(s.breached for s in self._classes.values())

    def health(self) -> dict:
        """The deterministic health snapshot riding every campaign
        report and ``serve --selftest`` (sorted keys, rounded burns —
        byte-identical per seed)."""
        classes = {}
        for qos in sorted(self._classes):
            s = self._classes[qos]
            classes[qos] = {
                "slo": s.spec.to_json(),
                "good": s.good,
                "errors": s.errors,
                "errors_by_reason": dict(
                    sorted(s.errors_by_reason.items())
                ),
                "burn": {
                    "short": round(s.burns[0], 4),
                    "long": round(s.burns[1], 4),
                },
                "worst_burn": round(s.worst_burn, 4),
                "breached": s.breached,
                "breaches": s.breaches,
                "recoveries": s.recoveries,
                "burn_warnings": s.burn_warnings,
                "breached_ticks": s.breached_ticks,
            }
        return {
            "windows": list(self.windows),
            "breach_burn": BREACH_BURN,
            "min_window_events": MIN_WINDOW_EVENTS,
            "breached": self.breached,
            "breaches_total": sum(
                s.breaches for s in self._classes.values()
            ),
            "classes": classes,
        }


def format_health(health: dict) -> List[str]:
    """Render a :meth:`SloEngine.health` snapshot as text lines (the
    ``smi-tpu health`` / ``serve --selftest`` surface)."""
    lines = [
        f"SLO health (windows {health['windows'][0]}/"
        f"{health['windows'][1]} ticks): "
        + ("BREACHED" if health["breached"] else "ok")
        + f", {health['breaches_total']} breach(es) over the run"
    ]
    for qos, c in health["classes"].items():
        slo = c["slo"]
        state = "BREACHED" if c["breached"] else (
            "burning" if c["burn"]["short"] >= BREACH_BURN else "ok"
        )
        reasons = ", ".join(
            f"{k}={v}" for k, v in c["errors_by_reason"].items()
        ) or "none"
        lines.append(
            f"  {qos:<12} {state:<8} burn {c['burn']['short']:g}/"
            f"{c['burn']['long']:g} (worst {c['worst_burn']:g}) "
            f"target<={slo['latency_target_ticks']} budget "
            f"{slo['error_budget']:g}  good {c['good']} errors "
            f"{c['errors']} [{reasons}]"
        )
    return lines
