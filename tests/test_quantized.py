"""Compressed collectives (r19): the beta-term attack, end to end.

The contract under test, layer by layer:

- ONE precision vocabulary: the transport simulator, the cost model,
  and the JAX lowering declare identical wire ratios — drift-guarded
  here, so a wire-fraction edit in any one tier fails loudly.
- The quantized/sparse protocol state machines deliver exactly under
  schedule fuzz, and the fault matrix holds: in-flight damage to a
  quantized or sparse frame is a named IntegrityError on framed
  transport and provable SilentCorruption on bare transport.
- The accuracy contract: every lossy width has a bounded relative
  error, the error-feedback residual drives the accumulated bias of a
  repeated compensated quantize toward zero (eager-only — inside a
  traced region the residual store is bypassed by design), and the
  degenerate shapes (top-k >= size, empty, scalar) fall back dense.
- Precedence is explicit pin > env > measured cache > (inert) model >
  dense heuristic; the pin and the env knob error LOUDLY on an
  ineligible op/dtype or a malformed value — exactness is never
  silently traded — while a cache entry written for another call site
  falls through silently.
- The untuned program is byte-for-byte the pre-knob lowering:
  ``precision=None`` with no cache compiles to the identical HLO as an
  explicit dense pin.
- The acceptance vectors: on the deterministic credits simulator the
  int8 two-tier allreduce at 4 MiB on a 2x2 pod prices at most 0.55x
  the f32 makespan, and the quoted pins in ``ANALYTIC_EXPECTED_US``
  equal the recomputation.

Everything runs on the 8-device CPU fake mesh / pure Python — no TPU.
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import smi_tpu as smi
from smi_tpu.parallel import collectives as coll
from smi_tpu.parallel import credits as C
from smi_tpu.parallel import faults as F
from smi_tpu.tuning import cost_model as cm
from smi_tpu.tuning import engine as eng
from smi_tpu.tuning.cache import CacheEntry, PlanCache
from smi_tpu.tuning.engine import PlanEngine, _collective_topology
from smi_tpu.tuning.online import (OnlineTuner, op_candidates,
                                   priced_sample_us)
from smi_tpu.tuning.plan import PlanKey, payload_bucket

pytestmark = pytest.mark.quantized

TOPO8 = cm.TopologySpec(n=8)
POD22 = cm.TopologySpec(n=4, inner=2, outer=2)


@pytest.fixture(autouse=True)
def _clean_precision_state(monkeypatch):
    """Every cell starts with no env pin, a fresh residual store, and
    no process-global engine left over from another test module."""
    monkeypatch.delenv(coll.ALLREDUCE_PRECISION_ENV, raising=False)
    monkeypatch.delenv(cm.DCN_BETA_ENV, raising=False)
    coll.error_feedback_reset()
    eng.set_engine(None)
    yield
    coll.error_feedback_reset()
    eng.set_engine(None)


# ---------------------------------------------------------------------------
# 1. One vocabulary across the tiers
# ---------------------------------------------------------------------------


def test_precision_vocabulary_is_shared_across_tiers():
    assert cm.ALLREDUCE_PRECISIONS == coll.ALLREDUCE_PRECISIONS
    assert cm.PRECISION_WIRE_RATIO == C.PRECISION_WIRE_RATIO
    assert cm.SPARSE_TOPK_DENSITY == C.SPARSE_TOPK_DENSITY
    assert tuple(sorted(cm.PRECISION_WIRE_RATIO)) == tuple(
        sorted(p for p in cm.ALLREDUCE_PRECISIONS if p != "topk")
    ) or set(cm.PRECISION_WIRE_RATIO) <= set(cm.ALLREDUCE_PRECISIONS)
    # the registry grew by exactly the compressed family
    assert C.QUANTIZED_PROTOCOLS == ("all_reduce_quantized",
                                     "all_reduce_sparse")
    assert F.QUANTIZED_PROTOCOLS is C.QUANTIZED_PROTOCOLS
    # the seed-pinned chaos draw set did not grow
    assert not set(C.QUANTIZED_PROTOCOLS) & set(C.PROTOCOLS)


def test_sparse_wire_fraction_is_density_times_index_overhead():
    frac = cm.precision_wire_fraction("topk")
    assert frac == cm.SPARSE_TOPK_DENSITY * cm.SPARSE_INDEX_OVERHEAD
    assert frac == 0.125
    assert cm.precision_wire_fraction("f32") == 1.0
    assert cm.precision_wire_fraction("int8") == 0.25


# ---------------------------------------------------------------------------
# 2. Protocol state machines: schedule fuzz + fault matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(8))
def test_quantized_pod_delivers_under_schedule_fuzz(seed):
    C.simulate_all_reduce_quantized(2, 2, C.Strategy(seed))


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("n", [2, 4, 5])
def test_sparse_allreduce_delivers_under_schedule_fuzz(n, seed):
    C.simulate_all_reduce_sparse(n, C.Strategy(seed))


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(64))
@pytest.mark.parametrize("shape", [(2, 2), (2, 4), (4, 2)])
def test_quantized_pod_wide_schedule_sweep(shape, seed):
    C.simulate_all_reduce_quantized(shape[0], shape[1], C.Strategy(seed))


@pytest.mark.parametrize("protocol", C.QUANTIZED_PROTOCOLS)
@pytest.mark.parametrize("fault_class", F.INTEGRITY_FAULT_CLASSES)
def test_integrity_faults_detected_framed(protocol, fault_class):
    for seed in range(4):
        plan = F.FaultPlan.random(fault_class, 4, seed)
        verdict = F.run_under_faults(protocol, 4, plan, verified=True)
        assert verdict.detected, (protocol, fault_class, seed)
        assert verdict.error_name == "IntegrityError"


@pytest.mark.parametrize("protocol", C.QUANTIZED_PROTOCOLS)
def test_bare_transport_is_silent_corruption(protocol):
    """The framing's existence proof on the compressed family: the
    same bit flip on bare transport completes with wrong delivery."""
    plan = F.FaultPlan.random("bit_flip_payload", 4, 3)
    with pytest.raises(F.SilentCorruption):
        F.run_under_faults(protocol, 4, plan, verified=False)


def test_quantized_pod_needs_divisible_ranks():
    with pytest.raises(ValueError, match="divisible"):
        F.run_under_faults("all_reduce_quantized", 5, None)


# ---------------------------------------------------------------------------
# 3. The acceptance vectors (the credits simulator)
# ---------------------------------------------------------------------------


def test_int8_two_tier_halves_the_4mib_pod_wallclock():
    """The r19 acceptance bar: int8 wire at 4 MiB on a 2x2 pod prices
    at most 0.55x the f32 makespan, and the DCN phase — the term the
    beta attack targets — drops at least as hard."""
    rep = C.quantized_wallclock_comparison(2, 2, 4 << 20, "int8")
    assert rep["quantized_s"] / rep["f32_s"] <= 0.55
    assert rep["quantized_dcn_s"] / rep["f32_dcn_s"] <= 0.55
    # both runs actually finished the same reduction (the comparison
    # itself raises on wrong delivery); the phase is a strict subset
    # of the makespan on both sides
    assert rep["quantized_dcn_s"] < rep["quantized_s"]
    assert rep["f32_dcn_s"] < rep["f32_s"]


def test_acceptance_pins_match_the_recomputation():
    from smi_tpu.analysis.perf import ANALYTIC_EXPECTED_US as E

    rep = C.quantized_wallclock_comparison(2, 2, 4 << 20, "int8")
    assert E["quantized_pod_allreduce_int8_2x2_4mib_us"] == round(
        rep["quantized_s"] * 1e6, 1)
    assert E["quantized_pod_dcn_phase_f32_2x2_4mib_us"] == round(
        rep["f32_dcn_s"] * 1e6, 1)
    assert E["quantized_pod_dcn_phase_int8_2x2_4mib_us"] == round(
        rep["quantized_dcn_s"] * 1e6, 1)
    bf16 = C.quantized_wallclock_comparison(2, 2, 4 << 20, "bf16")
    assert E["quantized_pod_allreduce_bf16_2x2_4mib_us"] == round(
        bf16["quantized_s"] * 1e6, 1)
    # the ordering the wire ratios promise: int8 < bf16 < f32
    assert (rep["quantized_s"] < bf16["quantized_s"]
            < bf16["f32_s"])


def test_wallclock_comparison_rejects_unknown_precision():
    with pytest.raises(ValueError, match="unknown precision"):
        C.quantized_wallclock_comparison(2, 2, 1 << 20, "fp4")


# ---------------------------------------------------------------------------
# 4. The accuracy contract (eager quantize primitives)
# ---------------------------------------------------------------------------


RNG = np.random.default_rng(7)


@pytest.mark.parametrize("precision,bound", [
    ("bf16", 0.01),    # bf16 mantissa: ~2^-8 per element
    ("int8", 0.02),    # 127-level symmetric grid on max-|x| scale
])
def test_quantize_relative_error_is_bounded(precision, bound):
    x = jnp.asarray(RNG.normal(size=4096).astype(np.float32))
    q = coll._quantize(x, precision)
    rel = float(jnp.linalg.norm(q - x) / jnp.linalg.norm(x))
    assert 0.0 < rel < bound, (precision, rel)


def test_topk_keeps_the_heavy_hitters_exactly():
    x = jnp.asarray(RNG.normal(size=256).astype(np.float32))
    q = coll._quantize(x, "topk")
    k = max(1, int(np.ceil(256 * cm.SPARSE_TOPK_DENSITY)))
    nz = np.flatnonzero(np.asarray(q))
    assert len(nz) <= k
    # the survivors are the largest-magnitude coordinates, unrounded
    order = np.argsort(-np.abs(np.asarray(x)))[:k]
    assert set(nz) <= set(order.tolist())
    np.testing.assert_array_equal(np.asarray(q)[nz], np.asarray(x)[nz])


def test_quantize_degenerate_shapes_fall_back_dense():
    one = jnp.asarray([2.5], dtype=jnp.float32)
    # k >= elements: top-k of everything is the identity
    np.testing.assert_array_equal(
        np.asarray(coll._quantize(one, "topk")), np.asarray(one))
    # a few elements: k clamps to 1 and the single heavy hitter stays
    tiny = jnp.asarray([1.0, -2.0, 3.0], dtype=jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(coll._quantize(tiny, "topk")),
        np.asarray([0.0, 0.0, 3.0], dtype=np.float32))
    empty = jnp.zeros((0,), dtype=jnp.float32)
    assert coll._quantize(empty, "topk").shape == (0,)
    # all-zero payload: the int8 scale guard must not divide by zero
    zeros = jnp.zeros((16,), dtype=jnp.float32)
    out = np.asarray(coll._quantize(zeros, "int8"))
    assert np.all(np.isfinite(out)) and np.all(out == 0.0)


def test_quantize_rejects_unknown_precision():
    with pytest.raises(ValueError):
        coll._quantize(jnp.ones(4), "fp4")


def test_error_feedback_drives_the_accumulated_bias_to_zero():
    """The compensated path's whole point: quantizing the SAME value
    repeatedly with residual carry makes the running mean of the
    emitted contributions converge to the true value, where the
    uncompensated path keeps a constant per-step bias."""
    x = jnp.asarray(RNG.normal(size=512).astype(np.float32) * 3.0)

    def emitted_mean(steps, compensated):
        coll.error_feedback_reset()
        total = np.zeros(512, dtype=np.float64)
        for _ in range(steps):
            fn = (coll._compensated_quantize if compensated
                  else coll._quantize)
            total += np.asarray(fn(x, "int8"), dtype=np.float64)
        return total / steps

    plain_bias = np.abs(emitted_mean(50, False) - np.asarray(x)).max()
    comp_bias = np.abs(emitted_mean(50, True) - np.asarray(x)).max()
    assert comp_bias < plain_bias / 5
    assert comp_bias < 1e-3


def test_error_feedback_is_per_call_site_and_resettable():
    x = jnp.ones(8, dtype=jnp.float32) * 0.3
    coll._compensated_quantize(x, "int8")
    assert len(coll._ERROR_FEEDBACK) == 1
    coll.error_feedback_reset()
    assert len(coll._ERROR_FEEDBACK) == 0


def test_traced_path_bypasses_the_residual_store():
    """Inside a traced region the residual store is bypassed by
    design (a Tracer cannot be stored across calls): the compensated
    wrapper degrades to the plain quantizer and writes nothing."""
    x = jnp.asarray(RNG.normal(size=64).astype(np.float32))

    plain = coll._quantize(x, "int8")
    traced = jax.jit(
        lambda v: coll._compensated_quantize(v, "int8"))(x)
    np.testing.assert_allclose(np.asarray(traced), np.asarray(plain),
                               rtol=0, atol=0)
    assert len(coll._ERROR_FEEDBACK) == 0


# ---------------------------------------------------------------------------
# 5. Precedence and loud errors (the resolve ladder)
# ---------------------------------------------------------------------------


def test_explicit_pin_outranks_env(comm8):
    """A dense pin under a lossy env var stays dense — the pin
    decides ALONE; and a lossy pin under a dense env var stays
    lossy."""
    import os

    x = jnp.ones(64, dtype=jnp.float32)
    os.environ[coll.ALLREDUCE_PRECISION_ENV] = "int8"
    try:
        assert coll._resolve_precision("f32", x, comm8,
                                       coll.SmiOp.ADD) == "f32"
    finally:
        del os.environ[coll.ALLREDUCE_PRECISION_ENV]
    assert coll._resolve_precision("bf16", x, comm8,
                                   coll.SmiOp.ADD) == "bf16"


def test_env_malformed_errors_loudly(comm8, monkeypatch):
    monkeypatch.setenv(coll.ALLREDUCE_PRECISION_ENV, "int7")
    x = jnp.ones(64, dtype=jnp.float32)
    with pytest.raises(ValueError) as err:
        coll._resolve_precision(None, x, comm8, coll.SmiOp.ADD)
    assert coll.ALLREDUCE_PRECISION_ENV in str(err.value)
    assert "int7" in str(err.value)


@pytest.mark.parametrize("source_kind", ["pin", "env"])
def test_ineligible_op_and_dtype_error_loudly(comm8, monkeypatch,
                                              source_kind):
    """Exactness is never silently traded: a lossy width forced onto
    a MAX reduction or an integer payload is a named error that says
    which knob to drop — for the pin AND the env var alike."""
    if source_kind == "env":
        monkeypatch.setenv(coll.ALLREDUCE_PRECISION_ENV, "int8")
        precision = None
    else:
        precision = "int8"
    fx = jnp.ones(64, dtype=jnp.float32)
    ix = jnp.ones(64, dtype=jnp.int32)
    with pytest.raises(ValueError, match="ADD allreduce"):
        coll._resolve_precision(precision, fx, comm8, coll.SmiOp.MAX)
    with pytest.raises(ValueError, match="floating-point payload"):
        coll._resolve_precision(precision, ix, comm8, coll.SmiOp.ADD)


def test_auto_path_never_errors_on_ineligible_shapes(comm8):
    """With NO pin and NO env var, ineligible shapes silently stay
    dense — auto must never break a working program."""
    assert coll._resolve_precision(
        None, jnp.ones(64, dtype=jnp.int32), comm8,
        coll.SmiOp.ADD) == "f32"
    assert coll._resolve_precision(
        None, jnp.ones(64, dtype=jnp.float32), comm8,
        coll.SmiOp.MAX) == "f32"


@pytest.mark.parametrize("backend", ["xla", "ring"])
@pytest.mark.parametrize("precision", ["bf16", "int8", "topk"])
def test_pinned_allreduce_is_exact_on_clean_values(comm8, backend,
                                                   precision):
    """On values every lossy grid represents exactly, the pinned
    allreduce sums exactly — the codec composes with both backends."""
    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"),
                    backend=backend)
    def app(ctx, x):
        return ctx.allreduce(x, precision=precision)[None]

    x = jnp.ones(16, dtype=jnp.float32) * 3.5
    try:
        out = np.asarray(app(x))
    except NotImplementedError as err:
        pytest.skip(str(err))   # ring tier needs Pallas interpret mode
    for r in range(8):
        np.testing.assert_allclose(out[r], 28.0)


def test_untuned_compile_is_byte_identical_to_dense_pin(comm8):
    """The heuristic rung's promise, held at the HLO level: with no
    cache and no env var, ``precision=None`` lowers to the identical
    text as an explicit dense pin — the quantize path contributes
    zero bytes to an untuned program."""
    def build(precision):
        @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
        def app(ctx, x):
            return ctx.allreduce(x, precision=precision)[None]
        return app

    x = jnp.arange(64, dtype=jnp.float32)
    auto = jax.jit(build(None)).lower(x).as_text()
    dense = jax.jit(build("f32")).lower(x).as_text()
    assert auto == dense


# ---------------------------------------------------------------------------
# 6. The plan-engine ladder
# ---------------------------------------------------------------------------


def fresh_engine(cache=None, device_kind="cpu"):
    return PlanEngine(cache=cache if cache is not None else PlanCache(),
                      device_kind=device_kind)


def bucket_key(payload, topo=TOPO8, dtype="float32",
               device_kind="cpu"):
    return PlanKey("all_reduce", payload_bucket(payload), dtype,
                   device_kind, _collective_topology(topo))


def threshold_key(outer, device_kind="cpu"):
    return PlanKey("all_reduce", "precision_threshold", "",
                   device_kind, f"dcn{outer}" if outer else "flat")


def test_untuned_ladder_bottoms_out_dense():
    e = fresh_engine()
    assert e.use_precision(4 << 20, TOPO8) == ("f32", "heuristic")


def test_explicit_override_decides_alone():
    e = fresh_engine()
    assert e.use_precision(4 << 20, TOPO8,
                           precision="int8") == ("int8", "env")


def test_cache_entry_decides_and_falls_through_when_ineligible():
    cache = PlanCache()
    cache.put(bucket_key(4 << 20), CacheEntry(
        {"precision": "int8"}, cost_us=290.0,
        provenance="sweep:allreduce-precision:4096KiB:n8"))
    e = fresh_engine(cache)
    assert e.use_precision(4 << 20, TOPO8) == ("int8", "cache")
    # the same cache consulted for an integer payload must not error
    # OR go lossy — it falls through to the dense heuristic
    assert e.use_precision(4 << 20, TOPO8,
                           dtype="int32") == ("f32", "heuristic")


def test_measured_threshold_gates_on_payload_and_eligibility():
    cache = PlanCache()
    cache.put(threshold_key(0), CacheEntry(
        {"precision_min_bytes": 1 << 20, "precision": "int8"},
        provenance="sweep:precision-crossover:n8"))
    e = fresh_engine(cache)
    assert e.use_precision(4 << 20, TOPO8) == ("int8", "cache")
    assert e.use_precision(64 << 10, TOPO8) == ("f32", "cache")
    assert e.use_precision(4 << 20, TOPO8,
                           dtype="int32") == ("f32", "cache")
    assert e.precision_threshold(0) == (1 << 20, "int8", "cache")
    assert e.precision_threshold(2) is None


def test_model_rung_is_provably_inert():
    """The margin equals the int8 byte ratio, so the modeled
    advantage of the dense quantized widths (strictly below their
    byte ratios — the alphas are unchanged) can never clear it, and
    topk — whose 8x byte-ratio bound EXCEEDS the margin — is not
    consulted by the rung at all: across payloads and topologies the
    model alone never puts a lossy width on the wire."""
    for topo in (TOPO8, POD22, cm.TopologySpec(n=2)):
        for payload in (64 << 10, 1 << 20, 4 << 20, 64 << 20):
            for p in ("bf16", "int8"):
                adv = cm.precision_advantage(payload, topo, p)
                assert adv < cm.PRECISION_MODEL_MARGIN, (
                    topo, payload, p, adv)
            assert fresh_engine().use_precision(
                payload, topo) == ("f32", "heuristic")
    # the exclusion is load-bearing, not belt-and-braces: at large
    # payloads topk's modeled advantage really does clear the margin,
    # so consulting it would flip numerics from the model alone
    assert cm.precision_advantage(
        64 << 20, TOPO8, "topk") >= cm.PRECISION_MODEL_MARGIN


def test_planned_precision_never_raises():
    assert eng.planned_precision(4 << 20, 8, 8, 0, "float32") == "f32"
    assert eng.planned_precision(
        4 << 20, 8, 8, 0, "float32", precision="topk") == "topk"
    # an engine that explodes degrades to the caller's pin / dense
    class Boom(PlanEngine):
        def use_precision(self, *a, **k):
            raise RuntimeError("boom")

    eng.set_engine(Boom(cache=PlanCache()))
    assert eng.planned_precision(4 << 20, 8, 8, 0, "float32") == "f32"
    assert eng.planned_precision(
        4 << 20, 8, 8, 0, "float32", precision="int8") == "int8"


def test_allreduce_plan_carries_the_precision_knob():
    e = fresh_engine()
    plan = e.allreduce_plan(4 << 20, TOPO8)
    assert plan.knobs["precision"] == "f32"
    assert plan.decided_by["precision"] == "heuristic"
    names = [c.name for c in plan.candidates]
    for p in cm.ALLREDUCE_PRECISIONS:
        assert p in names
    # the inert-model rationale names the margin
    assert any(f"{cm.PRECISION_MODEL_MARGIN:g}x" in line
               for line in plan.rationale)


def test_allreduce_plan_explains_the_quantize_floor_exclusions():
    """satellite 2's engine surface: below the quantize floor every
    lossy width is excluded WITH the reason, so ``tune --explain``
    renders why nothing lossy is on the table."""
    e = fresh_engine()
    plan = e.allreduce_plan(4096, TOPO8)
    floor_lines = [line for line in plan.rationale
                   if "excluded" in line]
    assert len(floor_lines) >= 3
    assert any("quantize floor" in line for line in floor_lines)


def test_cached_precision_cost_is_stitched_into_the_candidate():
    cache = PlanCache()
    cache.put(bucket_key(4 << 20), CacheEntry(
        {"precision": "int8"}, cost_us=290.0,
        provenance="sweep:allreduce-precision:4096KiB:n8"))
    plan = fresh_engine(cache).allreduce_plan(4 << 20, TOPO8)
    assert plan.knobs["precision"] == "int8"
    assert plan.decided_by["precision"] == "cache"
    int8_cands = [c for c in plan.candidates if c.name == "int8"]
    assert int8_cands and int8_cands[0].measured_us == 290.0


# ---------------------------------------------------------------------------
# 7. The measured sweep (CPU mesh — mechanics, not wire truth)
# ---------------------------------------------------------------------------


def test_sweep_persists_per_bucket_winners(comm2):
    from smi_tpu.tuning.sweep import sweep_allreduce_precision

    cache = sweep_allreduce_precision(comm2, sizes_kb=(64,), runs=1)
    key = bucket_key(64 << 10, cm.TopologySpec(n=2),
                     device_kind="cpu")
    hit = cache.lookup(key)
    assert hit is not None
    assert hit.knobs["precision"] in cm.ALLREDUCE_PRECISIONS
    assert hit.provenance.startswith("sweep:allreduce-precision:")
    assert hit.cost_us is not None and hit.cost_us > 0
    # a threshold entry exists only if a lossy width actually won on
    # this mesh — on CPU fake devices there is no real wire, so dense
    # usually wins and the crossover entry is legitimately absent;
    # whichever way it went, the cache round-trips through the engine
    e = fresh_engine(cache, device_kind="cpu")
    p, layer = e.use_precision(64 << 10, cm.TopologySpec(n=2))
    assert p in cm.ALLREDUCE_PRECISIONS
    thr = cache.lookup(threshold_key(0))
    if thr is not None:
        assert thr.provenance.startswith("sweep:precision-crossover:")
        assert int(thr.knobs["precision_min_bytes"]) > 0


# ---------------------------------------------------------------------------
# 8. The online tuner speaks precision
# ---------------------------------------------------------------------------


def test_online_tuner_can_install_a_lossy_width():
    """Once the quantized sweep's measured crossover exists, a dense
    plan timed far above the modeled lossy candidates gets retuned to
    one, and the evidence names the width transition — the from/to
    vocabulary the fleet dashboards key on."""
    topo = TOPO8
    cache = PlanCache()
    key = PlanKey("all_reduce", payload_bucket(4 << 20), "float32",
                  "live-sim", _collective_topology(topo))
    cache.put(key, CacheEntry({"algorithm": "rs_ag"}, cost_us=500.0,
                              provenance="sweep:seed"))
    cache.put(PlanKey("all_reduce", "precision_threshold", "",
                      "live-sim", "flat"),
              CacheEntry({"precision_min_bytes": 1 << 20,
                          "precision": "int8"},
                         provenance="sweep:precision-crossover:n8"))
    tuner = OnlineTuner(cache=cache, topo=topo,
                        device_kind="live-sim")
    slow_us = priced_sample_us("all_reduce", "rs_ag", 4 << 20, topo)
    for _ in range(16):
        tuner.record("all_reduce", slow_us * 5 * 1e-6,
                     payload_bytes=4 << 20)
    decisions = tuner.run_offline()
    proposals = [d for kind, d in decisions if kind == "propose"]
    assert proposals, "slow dense samples produced no proposal"
    ev = proposals[0]
    assert ev["to_precision"] in ("bf16", "int8", "topk")
    assert ev["from_precision"] == "f32"
    installed = cache.lookup(key)
    assert installed.knobs.get("precision") == ev["to_precision"]
    assert installed.provenance.startswith("live:retune:")


def test_online_tuner_never_goes_lossy_without_the_sweep_artifact():
    """The live tier holds the r19 asymmetry: lossy rivals are
    model-priced, so without the measured crossover in the cache the
    tuner may reroute (algorithm swaps) but never flips numerics —
    however slow the dense samples look."""
    topo = TOPO8
    cache = PlanCache()
    key = PlanKey("all_reduce", payload_bucket(4 << 20), "float32",
                  "live-sim", _collective_topology(topo))
    cache.put(key, CacheEntry({"algorithm": "rs_ag"}, cost_us=500.0,
                              provenance="sweep:seed"))
    tuner = OnlineTuner(cache=cache, topo=topo,
                        device_kind="live-sim")
    slow_us = priced_sample_us("all_reduce", "rs_ag", 4 << 20, topo)
    for _ in range(16):
        tuner.record("all_reduce", slow_us * 5 * 1e-6,
                     payload_bytes=4 << 20)
    for kind, d in tuner.run_offline():
        assert "to_precision" not in d
    installed = cache.lookup(key)
    assert installed.knobs.get("precision", "f32") == "f32"


def test_online_tuner_dense_swap_has_no_precision_evidence():
    """An algorithm-only retune (dense -> dense) must NOT grow the
    precision keys — the extended vocabulary appears exactly when a
    lossy width is involved."""
    cands = op_candidates("all_reduce", 4 << 20, TOPO8)
    dense = [c for c in cands if "precision" not in c.knobs]
    lossy = [c for c in cands if c.knobs.get("precision")
             not in (None, "f32")]
    assert dense and lossy
    # every lossy candidate rides an algorithm — never a forked path
    for c in lossy:
        assert "algorithm" in c.knobs


# ---------------------------------------------------------------------------
# 9. CLI surfaces
# ---------------------------------------------------------------------------


def test_tune_cli_lists_quantized_in_the_ops_error():
    proc = subprocess.run(
        [sys.executable, "-m", "smi_tpu", "tune", "--ops", "bogus"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "quantized" in proc.stderr


@pytest.mark.slow
def test_tune_cli_quantized_sweep_runs_end_to_end(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "smi_tpu", "tune", "--ops",
         "quantized", "--sizes-kb", "64", "--runs", "1",
         "--cache", str(tmp_path / "plans.json")],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr
    assert "allreduce wire precisions" in proc.stdout
