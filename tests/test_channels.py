"""P2P channel integration tests on the 8-device CPU fake mesh.

Reference: ``test/p2p/test_p2p.cpp`` — the matrix of dtypes × message
lengths × receivers, plus ``_ad`` (explicit buffer size) variants with odd
sizes. Payloads are verified element-exactly, as the reference receivers do
(``p2p_rank1`` kernels check ``i % 100`` style patterns).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import smi_tpu as smi
from smi_tpu.ops.types import dtype_to_jnp

DTYPES = ["int", "float", "double", "char", "short"]
LENGTHS = [1, 128, 1024, 10000]
RECEIVERS = [1, 4, 7]


def _payload(n, dtype):
    # mod-ranged pattern so int8 does not overflow (test_p2p.cpp uses i%100)
    return jnp.asarray(np.arange(n) % 100, dtype=dtype_to_jnp(dtype))


def _run_p2p(comm, dtype, length, dst, buffer_size=None, rendezvous=True):
    prog = smi.Program(
        [smi.Push(0, dtype, buffer_size), smi.Pop(0, dtype, buffer_size)],
        p2p_rendezvous=rendezvous,
    )

    @smi.smi_kernel(comm, in_specs=P(), out_specs=P("smi"), program=prog)
    def app(ctx, x):
        ch = ctx.open_channel(port=0, src=0, dst=dst, count=length, dtype=dtype)
        received = ctx.transfer(ch, x)
        return received[None]  # one shard per rank

    x = _payload(length, dtype)
    out = np.asarray(app(x))
    np.testing.assert_array_equal(out[dst], np.asarray(x))
    for r in range(comm.size):
        if r != dst:
            np.testing.assert_array_equal(out[r], np.zeros_like(out[r]))


@pytest.mark.parametrize("dtype", DTYPES)
def test_p2p_dtypes(comm8, dtype):
    _run_p2p(comm8, dtype, 128, dst=1)


@pytest.mark.parametrize("length", LENGTHS)
def test_p2p_lengths(comm8, length):
    _run_p2p(comm8, "float", length, dst=1)


@pytest.mark.parametrize("dst", RECEIVERS)
def test_p2p_receivers(comm8, dst):
    _run_p2p(comm8, "int", 256, dst=dst)


@pytest.mark.parametrize("buffer_size", [1, 33, 2048])
def test_p2p_ad_buffer_sizes(comm8, buffer_size):
    # _ad variants with odd asynchronicity degrees (test_p2p.cpp:101-117)
    _run_p2p(comm8, "float", 300, dst=2, buffer_size=buffer_size)


def test_p2p_eager_protocol(comm8):
    # rendezvous OFF = eager single-shot (CMakeLists.txt:16-17 bandwidth_eager)
    _run_p2p(comm8, "float", 515, dst=3, rendezvous=False)


def test_stream_consumer_overlap(comm8):
    """Streamed transfer applies the consumer per chunk (compute-while-
    receiving, the SMI value proposition)."""
    length = 7 * 8 * 4  # 4 chunks at default depth? chunk=16*7=112; 224=2 chunks

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def app(ctx, x):
        ch = ctx.open_channel(port=0, src=0, dst=1, count=length,
                              dtype="float", buffer_size=56)
        received, total = ctx.stream(
            ch, x, consumer=lambda carry, chunk: carry + jnp.sum(chunk),
            init_carry=jnp.zeros((), jnp.float32),
        )
        ok = jnp.where(ctx.rank() == 1,
                       jnp.isclose(total, jnp.sum(x)), True)
        return jnp.stack([jnp.sum(received), total, ok.astype(jnp.float32)])[None]

    x = jnp.arange(length, dtype=jnp.float32)
    out = np.asarray(app(x))
    expected = float(np.arange(length).sum())
    assert out[1, 0] == pytest.approx(expected)  # reassembled message at dst
    assert out[1, 1] == pytest.approx(expected)  # consumer saw every chunk
    assert out[1, 2] == 1.0
    assert out[0, 0] == 0.0  # src received nothing


def test_two_channels_distinct_ports(comm8):
    """Two concurrent transfers on distinct ports do not interfere
    (multi_collectives.cl's overlap property, P2P edition)."""

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def app(ctx, x):
        ch0 = ctx.open_channel(port=0, src=0, dst=1, count=64, dtype="float")
        ch1 = ctx.open_channel(port=1, src=2, dst=3, count=64, dtype="float")
        a = ctx.transfer(ch0, x)
        b = ctx.transfer(ch1, x * 2)
        return jnp.stack([jnp.sum(a), jnp.sum(b)])[None]

    x = jnp.ones(64, jnp.float32)
    out = np.asarray(app(x))
    assert out[1, 0] == 64.0 and out[1, 1] == 0.0
    assert out[3, 0] == 0.0 and out[3, 1] == 128.0


def test_ring_shift_pipeline(comm8):
    """Rank pipeline: every rank forwards to rank+1 (pipeline.cl:16-31)."""

    @smi.smi_kernel(comm8, in_specs=P("smi"), out_specs=P("smi"))
    def app(ctx, x):
        return ctx.ring_shift(x, offset=1)

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = np.asarray(app(x)).ravel()
    np.testing.assert_array_equal(out, np.roll(np.arange(8), 1))


def test_stream_tail_chunk_consumer_exact(comm8):
    """Non-additive consumers must never see padding: count not a multiple
    of the chunk size exercises the tail path (code-review regression)."""
    length, bufsize = 300, 33  # chunk = 40 packets? -> 56 elems; tail = 20

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def app(ctx, x):
        ch = ctx.open_channel(port=0, src=0, dst=1, count=length,
                              dtype="float", buffer_size=bufsize)
        received, lo = ctx.stream(
            ch, x,
            consumer=lambda c, chunk: jnp.minimum(c, jnp.min(chunk)),
            init_carry=jnp.asarray(jnp.inf, jnp.float32),
        )
        return jnp.stack([jnp.sum(received), lo])[None]

    x = jnp.arange(5, 5 + length, dtype=jnp.float32)
    out = np.asarray(app(x))
    assert out[1, 0] == float(np.arange(5, 5 + length).sum())
    assert out[1, 1] == 5.0  # min over real elements, not padded zeros


def test_stream_length_mismatch_raises(comm8):
    with pytest.raises(ValueError, match="message length"):
        @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
        def app(ctx, x):
            ch = ctx.open_channel(port=0, src=0, dst=1, count=112, dtype="float")
            return ctx.stream(ch, x)[0][None]

        app(jnp.zeros(56, jnp.float32))


def test_channel_zero_count_rejected(comm8):
    ctx = smi.SmiContext(comm8)
    with pytest.raises(ValueError, match="count"):
        ctx.open_channel(port=0, src=0, dst=1, count=0, dtype="float")


def test_stream_concurrent_two_channels(comm8):
    """Lockstep chunked streaming on two channels: exact payloads at each
    dst, zeros elsewhere (the bandwidth benchmark's transfer shape)."""
    from smi_tpu.parallel.channels import P2PChannel, stream_concurrent

    n = 300  # not a multiple of the chunk -> exercises the tail step

    def shard_fn(x):
        ch0 = P2PChannel(comm=comm8, port=0, src=0, dst=1, count=n,
                         dtype="float", buffer_size=64)
        ch1 = P2PChannel(comm=comm8, port=1, src=0, dst=2, count=n,
                         dtype="float", buffer_size=64)
        a, b = stream_concurrent((ch0, ch1), (x, x * 2))
        return jnp.stack([a, b])[None]

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm8.mesh, in_specs=P(), out_specs=P("smi"),
        check_vma=False,
    ))
    x = jnp.arange(n, dtype=jnp.float32)
    out = np.asarray(fn(x))  # (8, 2, n)
    np.testing.assert_array_equal(out[1][0], np.asarray(x))
    np.testing.assert_array_equal(out[2][1], 2 * np.asarray(x))
    np.testing.assert_array_equal(out[1][1], 0)
    np.testing.assert_array_equal(out[2][0], 0)
    np.testing.assert_array_equal(out[3], 0)


def test_stream_concurrent_mismatched_sizes_rejected(comm8):
    from smi_tpu.parallel.channels import P2PChannel, stream_concurrent

    ch0 = P2PChannel(comm=comm8, port=0, src=0, dst=1, count=64,
                     dtype="float")
    ch1 = P2PChannel(comm=comm8, port=1, src=0, dst=2, count=32,
                     dtype="float")
    with pytest.raises(ValueError, match="equal message/chunk"):
        stream_concurrent((ch0, ch1), (jnp.zeros(64), jnp.zeros(32)))


# ---------------------------------------------------------------------------
# Ring backend: credit-flow-controlled neighbour RDMA P2P tier
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dst", [1, 4, 7])
def test_ring_transfer_multi_hop(comm8, dst):
    """P2P over the explicit ring tier: non-neighbour endpoints forward
    hop-by-hop through intermediate ranks (``ckr.cl:50-60``)."""

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def app(ctx, x):
        ch = ctx.open_channel(port=0, src=0, dst=dst, count=64, dtype="float")
        return ctx.transfer(ch, x, backend="ring")[None]

    x = _payload(64, "float")
    out = np.asarray(app(x))
    np.testing.assert_array_equal(out[dst], np.asarray(x))
    for r in range(8):
        if r != dst:
            np.testing.assert_array_equal(out[r], np.zeros_like(out[r]))


@pytest.mark.parametrize("length", [1, 333, 1024])
def test_ring_stream_chunked(comm8, length):
    """Streamed ring transfer with odd lengths (chunk padding must not
    leak into the reassembled message)."""

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def app(ctx, x):
        ch = ctx.open_channel(
            port=0, src=2, dst=3, count=length, dtype="float", buffer_size=7
        )
        received, _ = ctx.stream(ch, x, backend="ring")
        return received[None]

    x = _payload(length, "float")
    out = np.asarray(app(x))
    np.testing.assert_array_equal(out[3], np.asarray(x))


def test_ring_stream_consumer_carry(comm8):
    """The consumer sees each chunk of a ring-streamed message in order."""

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def app(ctx, x):
        ch = ctx.open_channel(
            port=0, src=0, dst=1, count=300, dtype="float", buffer_size=56
        )
        received, total = ctx.stream(
            ch, x, consumer=lambda c, chunk: c + chunk.sum(),
            init_carry=jnp.float32(0), backend="ring",
        )
        return jnp.concatenate([received, total[None]])[None]

    x = _payload(300, "float")
    out = np.asarray(app(x))
    np.testing.assert_array_equal(out[1, :300], np.asarray(x))
    np.testing.assert_allclose(out[1, 300], np.asarray(x).sum())


# ---------------------------------------------------------------------------
# consecutive_reads (READS_LIMIT) burst schedule
# ---------------------------------------------------------------------------


def test_burst_schedule_changes_with_consecutive_reads(comm8):
    """The knob must change the observable chunking schedule
    (``device.cl:13-14``): bursts of k chunks per pipelining step."""
    base = dict(comm=comm8, port=0, src=0, dst=1, count=400,
                dtype="float", buffer_size=7)  # chunk = 8 packets = 56 elems
    ch1 = smi.P2PChannel(consecutive_reads=1, **base)
    ch4 = smi.P2PChannel(consecutive_reads=4, **base)
    assert ch1.burst_schedule() == [56] * 7 + [8]
    assert ch4.burst_schedule() == [224, 56, 56, 56, 8]
    assert sum(ch1.burst_schedule()) == sum(ch4.burst_schedule()) == 400


def test_burst_schedule_is_the_traced_schedule(comm8):
    """The jaxpr's transfer ops follow burst_schedule(): one ppermute per
    schedule entry outside the scan, one inside it."""
    from jax.sharding import PartitionSpec as PS

    def build(consecutive_reads):
        ch = smi.P2PChannel(
            comm=comm8, port=0, src=0, dst=1, count=400, dtype="float",
            buffer_size=7, consecutive_reads=consecutive_reads,
        )

        def shard(x):
            received, _ = ch.stream(x)
            return received

        return jax.make_jaxpr(
            jax.shard_map(shard, mesh=comm8.mesh, in_specs=PS(),
                          out_specs=PS(), check_vma=False)
        )(jnp.zeros(400, jnp.float32))

    # cr=1: scan over 7 uniform chunks (1 ppermute in the body) + tail = 2
    assert str(build(1)).count("ppermute") == 2
    # cr=4: scan over 1 burst + 3 leftover chunks + tail = 5
    assert str(build(4)).count("ppermute") == 5


def test_burst_payload_equality(comm8):
    """Burst width must not change delivered bytes or consumer results."""
    results = []
    for cr in (1, 3, 8):
        prog = smi.Program(
            [smi.Push(0, "float", 7), smi.Pop(0, "float", 7)],
            consecutive_reads=cr,
        )

        @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"), program=prog)
        def app(ctx, x):
            ch = ctx.open_channel(port=0, src=0, dst=5, count=500, dtype="float")
            assert ch.consecutive_reads == cr  # program knob reaches channel
            received, total = ctx.stream(
                ch, x, consumer=lambda c, chunk: c + chunk.sum(),
                init_carry=jnp.float32(0),
            )
            return jnp.concatenate([received, total[None]])[None]

        out = np.asarray(app(_payload(500, "float")))
        results.append(out)
    for r in results[1:]:
        np.testing.assert_array_equal(results[0], r)


# ---------------------------------------------------------------------------
# stream_reduce: accumulation_lanes (latency-masking shift register analog)
# ---------------------------------------------------------------------------


def test_stream_reduce_correct_and_lane_defaults(comm8):
    """Default lanes follow the op model: 4 for float (reduce.cl:63-70 /
    ops.py:110-141), 1 for int — and both reduce correctly."""
    for dtype, op, expect in [
        ("float", "add", lambda v: v.sum()),
        ("float", "max", lambda v: v.max()),
        ("int", "min", lambda v: v.min()),
    ]:
        @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
        def app(ctx, x):
            ch = ctx.open_channel(
                port=0, src=0, dst=2, count=600, dtype=dtype, buffer_size=7
            )
            _, total = ctx.stream_reduce(ch, x, op=op)
            return total[None][None]

        x = _payload(600, dtype)
        out = np.asarray(app(x))
        np.testing.assert_allclose(out[2, 0], expect(np.asarray(x)), rtol=1e-6)


def test_accumulation_lanes_change_float_association(comm8):
    """lanes is a live knob: different lane counts reassociate the
    streamed float sum (observably different bits), as the reference's
    shift register reassociates its accumulation."""

    def run(lanes):
        @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
        def app(ctx, x):
            ch = ctx.open_channel(
                port=0, src=0, dst=1, count=560, dtype="float", buffer_size=7
            )
            _, total = ctx.stream_reduce(ch, x, lanes=lanes)
            return total[None][None]

        # alternate huge/small whole chunks (chunk = 56 elements) so the
        # lane assignment — which chunks share an accumulator — changes
        # the float rounding
        x = jnp.asarray(
            np.where((np.arange(560) // 56) % 2 == 0, 3e7, 1.7), np.float32
        )
        return np.asarray(app(x))[1, 0]

    r1, r4 = run(1), run(4)
    expected = np.sum(
        np.where((np.arange(560) // 56) % 2 == 0, 3e7, 1.7)
    )
    np.testing.assert_allclose(r1, expected, rtol=1e-5)
    np.testing.assert_allclose(r4, expected, rtol=1e-5)
    assert r1 != r4  # the knob observably reassociates the accumulation


def test_default_lanes_match_op_model(comm8):
    """The default lane count is exactly Reduce.accumulation_lanes."""
    from smi_tpu.ops.operations import Reduce

    def run(dtype, lanes):
        @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
        def app(ctx, x):
            ch = ctx.open_channel(
                port=0, src=0, dst=1, count=560, dtype=dtype, buffer_size=7
            )
            _, total = ctx.stream_reduce(ch, x, lanes=lanes)
            return total.astype(jnp.float32)[None][None]

        x = jnp.asarray(
            np.where((np.arange(560) // 56) % 2 == 0, 3e7, 1.7),
            dtype_to_jnp(dtype),
        )
        return np.asarray(app(x))[1, 0]

    assert Reduce(0, "float").accumulation_lanes == 4
    assert run("float", None) == run("float", 4)
    assert run("float", None) != run("float", 1)


# ---------------------------------------------------------------------------
# Verified transport: per-chunk sequence-keyed checksums
# ---------------------------------------------------------------------------

from smi_tpu.parallel.channels import FrameCheck
from smi_tpu.parallel.credits import IntegrityError


def _verified_transfer(comm, count=300, dst=3, backend="xla"):
    @smi.smi_kernel(
        comm, in_specs=P(),
        out_specs=(P("smi"), (P("smi"), P("smi"), P("smi"))),
    )
    def app(ctx, x):
        ch = smi.P2PChannel(comm=comm, port=0, src=0, dst=dst,
                            count=count)
        received, check = ch.transfer_verified(x, backend=backend)
        return received[None], tuple(c[None] for c in check)

    x = np.arange(count, dtype=np.float32)
    out, (exp, got, at) = app(x)
    ch = smi.P2PChannel(comm=comm, port=0, src=0, dst=dst, count=count)
    return ch, x, np.asarray(out), (np.asarray(exp), np.asarray(got),
                                    np.asarray(at))


def test_transfer_verified_healthy_passes_at_every_rank(comm8):
    """Healthy delivery: the per-chunk checksums computed at src and
    recomputed at dst agree, and every rank's verdict is clean (the
    non-dst ranks are masked — their buffers are zeros by contract)."""
    ch, x, out, (exp, got, at) = _verified_transfer(comm8)
    np.testing.assert_array_equal(out[3], x)
    for r in range(8):
        ch.verify_frames(FrameCheck(exp[r], got[r], at[r]))
    # the dst actually compared: expected == got elementwise there
    np.testing.assert_array_equal(exp[3], got[3])
    assert at[3] == 1 and at[0] == 0


def test_transfer_verified_catches_corruption_naming_chunk(comm8):
    """A flipped element in the delivered buffer must fail verification
    with the damaged chunk named and expected vs got checksums."""
    ch, x, out, (exp, got, at) = _verified_transfer(comm8)
    tampered = out[3].copy()
    tampered[137] += 1.0  # one element, mid-message
    got_bad = np.asarray(ch.chunk_checksums(tampered))
    with pytest.raises(IntegrityError) as e:
        ch.verify_frames(FrameCheck(exp[3], got_bad, at[3]),
                         context="unit test")
    err = e.value
    assert err.kind == "checksum" and err.src == 0 and err.rank == 3
    chunk = min(ch.chunk_elements, ch.count)
    assert err.seq == 137 // chunk  # the damaged chunk, localized
    assert err.expected != err.got
    assert "unit test" in str(err)


def test_transfer_verified_catches_truncation_and_swap(comm8):
    """Truncation (zeros where payload was) and a chunk swap both
    change the sequence-keyed checksum vector."""
    ch, x, out, (exp, got, at) = _verified_transfer(comm8)
    chunk = min(ch.chunk_elements, ch.count)
    truncated = out[3].copy()
    truncated[-(ch.count - chunk):] = 0.0  # everything past chunk 0
    with pytest.raises(IntegrityError):
        ch.verify_frames(FrameCheck(
            exp[3], np.asarray(ch.chunk_checksums(truncated)), at[3]))
    swapped = out[3].copy()
    a, b = swapped[:chunk].copy(), swapped[chunk:2 * chunk].copy()
    swapped[:chunk], swapped[chunk:2 * chunk] = b, a
    with pytest.raises(IntegrityError) as e:
        ch.verify_frames(FrameCheck(
            exp[3], np.asarray(ch.chunk_checksums(swapped)), at[3]))
    assert e.value.seq == 0  # first swapped chunk named


def test_stream_verified_returns_consumer_carry(comm8):
    """stream_verified keeps stream()'s consumer contract and adds the
    integrity evidence on the same chunking."""

    @smi.smi_kernel(
        comm8, in_specs=P(),
        out_specs=(P("smi"), P("smi"),
                   (P("smi"), P("smi"), P("smi"))),
    )
    def app(ctx, x):
        ch = smi.P2PChannel(comm=comm8, port=0, src=0, dst=2,
                            count=224)
        received, carry, check = ch.stream_verified(
            x, consumer=lambda c, chunk: c + jnp.sum(chunk),
            init_carry=jnp.float32(0),
        )
        return received[None], carry[None], tuple(
            c[None] for c in check
        )

    x = np.arange(224, dtype=np.float32)
    out, carry, (exp, got, at) = app(x)
    np.testing.assert_array_equal(np.asarray(out)[2], x)
    np.testing.assert_allclose(np.asarray(carry)[2], x.sum())
    ch = smi.P2PChannel(comm=comm8, port=0, src=0, dst=2, count=224)
    for r in range(8):
        ch.verify_frames(FrameCheck(
            np.asarray(exp)[r], np.asarray(got)[r], np.asarray(at)[r]))


def test_verified_ring_backend_or_skip(comm8):
    """The verified framing rides the ring tier through the same
    machinery; on JAX builds without Pallas interpret mode the ring
    tier itself is unavailable (like every other ring test here)."""
    try:
        ch, x, out, (exp, got, at) = _verified_transfer(
            comm8, backend="ring")
    except NotImplementedError as e:
        pytest.skip(f"ring interpret tier unavailable: {e}")
    np.testing.assert_array_equal(out[3], x)
    for r in range(8):
        ch.verify_frames(FrameCheck(exp[r], got[r], at[r]))


def test_chunk_checksums_order_sensitive_beyond_sums(comm8):
    """Regression: the checksum must be content-ORDER-sensitive, not a
    plain sum — swapping two chunks that are permutations of each
    other (equal plain sums), reversing a chunk, and any single-bit
    flip must all change the vector."""
    ch = smi.P2PChannel(comm=comm8, port=0, src=0, dst=1, count=600,
                        buffer_size=1)
    chunk = min(ch.chunk_elements, ch.count)
    x = np.zeros(600, dtype=np.float32)
    x[:chunk] = np.arange(chunk)
    x[chunk:2 * chunk] = np.arange(chunk)[::-1]  # permutation: equal sums
    base = np.asarray(ch.chunk_checksums(x))
    swapped = x.copy()
    swapped[:chunk], swapped[chunk:2 * chunk] = (
        x[chunk:2 * chunk].copy(), x[:chunk].copy())
    assert not np.array_equal(base, np.asarray(ch.chunk_checksums(swapped)))
    reversed_chunk = x.copy()
    reversed_chunk[:chunk] = x[:chunk][::-1]
    assert not np.array_equal(
        base, np.asarray(ch.chunk_checksums(reversed_chunk)))
    rng = np.random.default_rng(7)
    for _ in range(64):
        y = x.copy().view(np.int32)
        y[rng.integers(0, 600)] ^= np.int32(1) << rng.integers(0, 31)
        assert not np.array_equal(
            base, np.asarray(ch.chunk_checksums(y.view(np.float32))))
