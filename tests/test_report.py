"""Compiled-artifact report (the ``aoc -rtl -report`` analog).

Reference: report targets let the reference inspect area/Fmax before a
full hardware build (``CMakeLists.txt:113-118``); here every manifest op
compiles through XLA and reports its cost/memory facts
(``smi_tpu/utils/report.py``). The CPU tier golden-tests the structure;
the numbers are informative on TPU (``build --report-topology``).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from smi_tpu.ops.operations import (  # noqa: E402
    Broadcast,
    Gather,
    Pop,
    Push,
    Reduce,
    Scatter,
)
from smi_tpu.ops.program import Program  # noqa: E402
from smi_tpu.ops.types import SmiDtype, SmiOp  # noqa: E402
from smi_tpu.utils.report import format_report, program_report  # noqa: E402


@pytest.fixture(scope="module")
def full_program():
    return Program([
        Push(port=0, dtype=SmiDtype.FLOAT, buffer_size=32),
        Pop(port=0, dtype=SmiDtype.FLOAT, buffer_size=32),
        Broadcast(port=1, dtype=SmiDtype.INT),
        Reduce(port=2, dtype=SmiDtype.FLOAT, op=SmiOp.MAX),
        Scatter(port=3, dtype=SmiDtype.FLOAT),
        Gather(port=4, dtype=SmiDtype.FLOAT),
    ])


def test_program_report_covers_every_port(comm8, full_program):
    report = program_report(full_program, comm8, count=64)
    entries = {(e["op"], e["port"]) for e in report["operations"]}
    # the push/pop pair is one channel, reported once
    assert entries == {
        ("push", 0), ("broadcast", 1), ("reduce", 2),
        ("scatter", 3), ("gather", 4),
    }
    for e in report["operations"]:
        assert e["count"] == 64
        assert "cost" in e and "memory" in e
        # XLA's cost model prices a reduction's flops > 0
        if e["op"] == "reduce":
            assert e["cost"].get("flops", 0) > 0


def test_format_report_tabulates(comm8, full_program):
    report = program_report(full_program, comm8, count=64)
    text = format_report(report)
    assert "8 ranks" in text
    for op in ("push", "broadcast", "reduce", "scatter", "gather"):
        assert op in text
