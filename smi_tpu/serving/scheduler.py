"""Stream scheduler and wire lanes: QoS order, fairness, deadlines.

The data plane of the serving front-end, modeled with the same credit
discipline the wire-level simulator proves safe
(:mod:`smi_tpu.parallel.credits`):

- a :class:`WireLane` is one destination rank's inbound wire. It holds
  :data:`WIRE_CREDITS` chunk credits; sending a chunk takes one, and
  the credit returns only when the destination's consumer CONSUMES the
  chunk — not when it lands. A stalled (or dead) consumer therefore
  exhausts the lane within ``WIRE_CREDITS`` chunks and the lane stops
  accepting sends: backpressure, expressed exactly as the rendezvous
  credits express it on the NoC. Chunks land ``TRANSIT_TICKS`` after
  the send, in order (one lane is one FIFO wire).
- the :class:`StreamScheduler` picks which accepted stream sends next
  on each lane: strict class priority
  (:data:`~smi_tpu.serving.qos.CLASS_PRIORITY`) with an **aging
  bound** — a ready stream passed over :data:`MAX_STARVE_ROUNDS`
  times is scheduled next regardless of class, so the interleaving
  gap of any stream behind higher-priority traffic is bounded (the
  serving analog of the CK loop's ``READS_LIMIT`` fairness, and of
  the bounded-gap property the tenant-fairness regression test pins
  on the credits simulator).
- every chunk send runs the stream's propagated
  :class:`~smi_tpu.utils.watchdog.Deadline` check (tick clock,
  serving state dump attached via ``with_provider``): a stream that
  cannot make progress inside its budget surfaces as a named
  ``WatchdogTimeout`` carrying per-stream state — never a silent
  hang, never a silent drop.

Chunks move as verified-transport frames
(:class:`~smi_tpu.parallel.credits.Frame`): CRC per chunk, dense
per-lane sequence numbers, checked at consumption. Damage is a named
``IntegrityError`` and the chunk replays from the front-end's WAL.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from smi_tpu.parallel.credits import (
    Frame,
    IntegrityError,
    frame_crc,
    make_frame,
)
from smi_tpu.serving.qos import CLASS_PRIORITY, Request

#: In-flight + landed-unconsumed chunk bound per destination lane —
#: the wire's credit window (the role of the ring kernels' slot pair).
WIRE_CREDITS = 4

#: Ticks between a chunk's send and its landing at the destination.
TRANSIT_TICKS = 1

#: Chunks a live destination consumes per tick (its service rate).
CONSUME_RATE = 2

#: Aging bound: scheduling decisions a ready stream may be passed
#: over before it is served regardless of class — the starvation
#: bound docs/robustness.md quotes.
MAX_STARVE_ROUNDS = 16


@dataclasses.dataclass
class StreamState:
    """One accepted stream in flight."""

    request: Request
    index: int                      # global stream number (frame src)
    dst: int                        # current destination rank
    deadline: object                # watchdog.Deadline on the tick clock
    wal: object                     # recovery.ProgressLog
    lane_epoch: int = 0             # bumps on failover -> fresh seq lane
    next_to_send: int = 0
    delivered: Dict[int, object] = dataclasses.field(
        default_factory=dict
    )
    skips: int = 0                  # aging counter
    replayed_chunks: int = 0
    sent_total: int = 0
    admitted_at: int = 0
    completed_at: Optional[int] = None

    @property
    def lane_key(self) -> Tuple[int, int]:
        """Sequence-lane identity: fresh per failover epoch, so a
        replay to an heir starts a dense lane of its own and a
        straggler frame from the old route can never alias it."""
        return (self.index, self.lane_epoch)

    @property
    def total_chunks(self) -> int:
        return len(self.request.chunks)

    @property
    def complete(self) -> bool:
        return len(self.delivered) == self.total_chunks


@dataclasses.dataclass
class _InFlight:
    ready_at: int
    stream: StreamState
    seq: int
    frame: Frame
    #: the stream's route incarnation when this chunk was sent — a
    #: mismatch with the stream's CURRENT lane_epoch at consumption
    #: marks the chunk as a pre-failover straggler
    lane_epoch: int = 0
    #: the membership epoch the send happened under (the value the
    #: consume-side stale gate validates against the current view)
    view_epoch: int = 0


class WireLane:
    """One destination rank's inbound wire under credit flow control."""

    def __init__(self, rank: int):
        self.rank = rank
        self.credits = WIRE_CREDITS
        self.in_flight: Deque[_InFlight] = deque()
        self.landed: Deque[_InFlight] = deque()
        #: receiver-side dense sequence expectation per lane_key
        self.next_seq: Dict[Tuple[int, int], int] = {}
        #: consumer stalled until this tick (SlowConsumer fault)
        self.stalled_until: int = 0
        #: membership epoch stamped onto sends (the front-end updates
        #: it every tick before scheduling)
        self.view_epoch: int = 0

    def can_send(self) -> bool:
        return self.credits > 0

    def send(self, stream: StreamState, seq: int, payload,
             now: int) -> None:
        assert self.credits > 0, "send without a wire credit"
        self.credits -= 1
        frame = make_frame(stream.index, seq, payload, wire=True)
        self.in_flight.append(
            _InFlight(now + TRANSIT_TICKS, stream, seq, frame,
                      lane_epoch=stream.lane_epoch,
                      view_epoch=self.view_epoch)
        )
        stream.sent_total += 1

    def land(self, now: int) -> None:
        while self.in_flight and self.in_flight[0].ready_at <= now:
            self.landed.append(self.in_flight.popleft())

    def drop_all(self) -> int:
        """The rank died: everything on or queued for this wire is
        lost (the front-end replays from the WAL)."""
        lost = len(self.in_flight) + len(self.landed)
        self.credits += lost
        self.in_flight.clear()
        self.landed.clear()
        return lost


class StreamScheduler:
    """Class-priority scheduling with a bounded starvation gap.

    ``max_starve_rounds`` defaults to the production
    :data:`MAX_STARVE_ROUNDS`; the control-plane model checker
    (:mod:`smi_tpu.analysis.model`) instantiates the same class with a
    scope-scaled bound so the aging property is reachable inside a
    small exhaustive scope — the bound is structural in the ordering
    rule, not in the constant, so checking it at 3 proves the same
    mechanism that ships at 16.
    """

    def __init__(self, check_deadlines: bool = True,
                 max_starve_rounds: int = MAX_STARVE_ROUNDS):
        if max_starve_rounds < 1:
            raise ValueError(
                f"max_starve_rounds must be >= 1, got {max_starve_rounds}"
            )
        self.check_deadlines = check_deadlines
        self.max_starve_rounds = max_starve_rounds
        #: optional per-chunk observation hook,
        #: ``on_send(stream, seq, lane, now)`` — called after every
        #: issued send (the front-end wires its flight recorder /
        #: metrics here; None = zero overhead). Observation only: the
        #: scheduling decision is already made when it fires.
        self.on_send: Optional[Callable] = None

    def _order(self, eligible: List[StreamState]) -> List[StreamState]:
        """Starved streams first (aging bound), then strict class
        priority, then admission order — deterministic throughout."""
        return sorted(
            eligible,
            key=lambda s: (
                0 if s.skips >= self.max_starve_rounds else 1,
                CLASS_PRIORITY[s.request.qos],
                s.index,
            ),
        )

    def schedule_lane(
        self,
        lane: WireLane,
        streams: List[StreamState],
        now: int,
        state_provider: Optional[Callable] = None,
    ) -> int:
        """Issue sends on one lane until its credits or the ready work
        run out. Returns the number of chunks sent. Every send first
        runs the stream's propagated per-chunk deadline check."""
        sent = 0
        while lane.can_send():
            eligible = [
                s for s in streams
                if s.dst == lane.rank
                and s.next_to_send < s.total_chunks
            ]
            if not eligible:
                break
            ordered = self._order(eligible)
            chosen = ordered[0]
            for other in ordered[1:]:
                other.skips += 1
            chosen.skips = 0
            if self.check_deadlines:
                deadline = chosen.deadline
                if state_provider is not None:
                    deadline = deadline.with_provider(state_provider)
                deadline.check(
                    f"chunk {chosen.next_to_send}/"
                    f"{chosen.total_chunks} of stream "
                    f"{chosen.request.stream_id} "
                    f"({chosen.request.qos}) to rank {lane.rank}"
                )
            seq = chosen.next_to_send
            lane.send(
                chosen, seq, chosen.request.chunks[seq], now
            )
            chosen.next_to_send += 1
            sent += 1
            if self.on_send is not None:
                self.on_send(chosen, seq, lane, now)
        return sent


def verify_chunk(lane: WireLane, item: _InFlight,
                 recorder=None) -> object:
    """Receiver-side verdict on one landed chunk: CRC, then dense
    per-lane sequence — the :func:`credits.verified_steps` discipline
    at the serving tier. Returns the payload; raises
    :class:`~smi_tpu.parallel.credits.IntegrityError` naming the miss
    — carrying the ``recorder``'s bounded event tail
    (``recorder_tail``) when one is wired, so a wire-damage detection
    names the serving history that led to it.
    """
    frame = item.frame
    error = None
    want = frame_crc(frame.src, frame.seq, frame.wire, frame.payload)
    if want != frame.crc:
        error = IntegrityError(
            f"rank {lane.rank}: checksum mismatch on chunk "
            f"seq={frame.seq} of stream {item.stream.request.stream_id}"
            f": frame declares crc={frame.crc:#010x} but payload "
            f"hashes to {want:#010x}",
            rank=lane.rank, src=frame.src, seq=frame.seq,
            expected=frame.crc, got=want, kind="checksum",
        )
    else:
        key = item.stream.lane_key
        expected = lane.next_seq.get(key, 0)
        if frame.seq != expected:
            error = IntegrityError(
                f"rank {lane.rank}: out-of-sequence chunk of stream "
                f"{item.stream.request.stream_id}: expected "
                f"seq={expected}, got seq={frame.seq}",
                rank=lane.rank, src=frame.src, seq=frame.seq,
                expected=expected, got=frame.seq, kind="sequence",
            )
    if error is not None:
        if recorder is not None:
            from smi_tpu.obs.events import attach_tail

            attach_tail(error, recorder)
        raise error
    lane.next_seq[item.stream.lane_key] = frame.seq + 1
    return frame.payload
