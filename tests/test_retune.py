"""Online autotuner tests: shadow comparison, PlanSwap, convergence.

The r14 subsystem end to end: env-knob discipline, the PlanSwap
state machine and its stale-plan gate, the cache-revision staleness
rule (a late offline sweep can never resurrect a retired plan), the
OnlineTuner's noise-proof thresholds, SampleSink behaviour under
retuner load (bucket edges, tenant churn, snapshot-vs-bookkeeping
equality of the tune.* counters), the engine's ``live`` provenance
tier, the seeded payload-shift campaign cells (flat -> rs_ag, pod ->
hierarchical, kill-during-shift), and the retune model-checker scope
with its two mutants.
"""

import dataclasses
import json

import pytest

from smi_tpu.obs.events import FlightRecorder
from smi_tpu.obs.metrics import MetricsRegistry, SampleSink
from smi_tpu.tuning import cost_model as cm
from smi_tpu.tuning.cache import CacheEntry, PlanCache, PlanCacheError
from smi_tpu.tuning.engine import (
    PlanEngine,
    _collective_topology,
    cache_entry_layer,
)
from smi_tpu.tuning.online import (
    DEFAULT_RETUNE_MARGIN,
    DEFAULT_RETUNE_MIN_SAMPLES,
    MARGIN_ENV,
    MIN_SAMPLES_ENV,
    ONLINE_RETUNE_ENV,
    OnlineTuner,
    online_retune_enabled,
    op_candidates,
    priced_sample_us,
    retune_margin,
    retune_min_samples,
    sample_bucket_bytes,
)
from smi_tpu.tuning.plan import LAYERS, PlanKey, payload_bucket
from smi_tpu.tuning.swap import (
    SWAP_STATES,
    PlanSwap,
    PlanSwapError,
    StalePlanError,
)

pytestmark = pytest.mark.retune

TOPO8 = cm.TopologySpec(n=8)
POD = cm.TopologySpec(n=8, inner=4, outer=2)
LARGE = 4 << 20
SMALL = 64 << 10


def large_key(topo=TOPO8, device_kind="live-sim"):
    return PlanKey("all_reduce", payload_bucket(LARGE), "float32",
                   device_kind, _collective_topology(topo))


def stale_ring_cache(topo=TOPO8, device_kind="live-sim"):
    cache = PlanCache()
    cache.put(large_key(topo, device_kind), CacheEntry(
        {"algorithm": "ring"}, cost_us=700.0,
        provenance="sweep:stale-offline",
    ))
    return cache


def fed_tuner(samples=DEFAULT_RETUNE_MIN_SAMPLES, tenant="t0",
              payload=LARGE, algorithm="ring", **kwargs):
    """A tuner over the stale-ring cache, fed ``samples`` live
    timings of ``algorithm`` at ``payload``."""
    kwargs.setdefault("cache", stale_ring_cache())
    kwargs.setdefault("topo", TOPO8)
    kwargs.setdefault("device_kind", "live-sim")
    tuner = OnlineTuner(**kwargs)
    us = priced_sample_us("all_reduce", algorithm, payload, TOPO8)
    for _ in range(samples):
        tuner.record("all_reduce", us * 1e-6, payload_bytes=payload,
                     tenant=tenant)
    return tuner


# ---------------------------------------------------------------------------
# Env knobs: the default_deadline discipline
# ---------------------------------------------------------------------------


class TestEnvKnobs:
    def test_unset_means_off_and_builtin_defaults(self, monkeypatch):
        for env in (ONLINE_RETUNE_ENV, MIN_SAMPLES_ENV, MARGIN_ENV):
            monkeypatch.delenv(env, raising=False)
        assert online_retune_enabled() is False
        assert retune_min_samples() == DEFAULT_RETUNE_MIN_SAMPLES
        assert retune_margin() == DEFAULT_RETUNE_MARGIN

    @pytest.mark.parametrize("value,expected", [
        ("1", True), ("true", True), ("YES", True), ("on", True),
        ("0", False), ("false", False), ("No", False), ("off", False),
        ("", False),
    ])
    def test_switch_vocabulary(self, monkeypatch, value, expected):
        monkeypatch.setenv(ONLINE_RETUNE_ENV, value)
        assert online_retune_enabled() is expected

    def test_malformed_switch_is_loud(self, monkeypatch):
        monkeypatch.setenv(ONLINE_RETUNE_ENV, "maybe")
        with pytest.raises(ValueError, match=ONLINE_RETUNE_ENV):
            online_retune_enabled()

    def test_min_samples_override_outranks_builtin(self, monkeypatch):
        monkeypatch.setenv(MIN_SAMPLES_ENV, "24")
        assert retune_min_samples() == 24
        assert OnlineTuner().min_samples == 24

    @pytest.mark.parametrize("value", ["0", "-3", "2.5", "lots"])
    def test_malformed_min_samples_is_loud(self, monkeypatch, value):
        monkeypatch.setenv(MIN_SAMPLES_ENV, value)
        with pytest.raises(ValueError, match=MIN_SAMPLES_ENV):
            retune_min_samples()

    def test_margin_override_outranks_builtin(self, monkeypatch):
        monkeypatch.setenv(MARGIN_ENV, "2.25")
        assert retune_margin() == 2.25
        assert OnlineTuner().margin == 2.25

    @pytest.mark.parametrize("value", ["1.0", "0.9", "nan", "inf", "x"])
    def test_malformed_margin_is_loud(self, monkeypatch, value):
        monkeypatch.setenv(MARGIN_ENV, value)
        with pytest.raises(ValueError, match=MARGIN_ENV):
            retune_margin()

    def test_explicit_argument_outranks_env(self, monkeypatch):
        monkeypatch.setenv(MIN_SAMPLES_ENV, "24")
        monkeypatch.setenv(MARGIN_ENV, "2.25")
        tuner = OnlineTuner(min_samples=5, margin=3.0)
        assert tuner.min_samples == 5 and tuner.margin == 3.0


# ---------------------------------------------------------------------------
# PlanSwap: the epoch-guarded state machine
# ---------------------------------------------------------------------------


class TestPlanSwap:
    def make(self):
        cache = stale_ring_cache()
        return cache, PlanSwap(cache, large_key())

    def rival(self):
        return CacheEntry({"algorithm": "rs_ag"},
                          provenance="live:retune:test")

    def test_happy_arc_installs_with_bumped_revision_and_epoch(self):
        cache, swap = self.make()
        assert swap.state == "idle" and swap.plan_epoch == 0
        swap.propose(self.rival(), evidence={"why": "test"})
        assert swap.state == "proposed"
        swap.quiesce(now=7)
        assert swap.state == "quiescing" and swap.quiesce_started == 7
        installed = swap.swap()
        assert swap.state == "swapped" and swap.plan_epoch == 1
        assert installed.revision == 1
        assert cache.lookup(large_key()).knobs["algorithm"] == "rs_ag"
        swap.commit()
        assert swap.state == "committed"
        assert swap.committed_swaps == 1

    def test_every_state_is_in_the_registry(self):
        cache, swap = self.make()
        seen = {swap.state}
        swap.propose(self.rival())
        seen.add(swap.state)
        swap.quiesce()
        seen.add(swap.state)
        swap.swap()
        seen.add(swap.state)
        swap.commit()
        seen.add(swap.state)
        swap.propose(self.rival())
        swap.rollback("test")
        seen.add(swap.state)
        assert seen == set(SWAP_STATES)

    def test_illegal_transitions_are_loud(self):
        cache, swap = self.make()
        with pytest.raises(PlanSwapError, match="requires"):
            swap.swap()            # idle -> swap
        with pytest.raises(PlanSwapError, match="requires"):
            swap.commit()          # idle -> commit
        with pytest.raises(PlanSwapError, match="requires"):
            swap.rollback()        # nothing in flight
        swap.propose(self.rival())
        with pytest.raises(PlanSwapError, match="requires"):
            swap.swap()            # proposed -> swap (quiesce skipped!)
        with pytest.raises(PlanSwapError, match="requires"):
            swap.propose(self.rival())   # already in flight

    def test_pre_swap_rollback_leaves_entry_and_epoch_untouched(self):
        cache, swap = self.make()
        swap.propose(self.rival())
        swap.rollback("changed my mind")
        assert swap.state == "rolled_back" and swap.plan_epoch == 0
        assert cache.lookup(large_key()).knobs["algorithm"] == "ring"
        assert swap.last_rollback_reason == "changed my mind"

    def test_post_swap_rollback_restores_under_a_further_bump(self):
        cache, swap = self.make()
        swap.propose(self.rival())
        swap.quiesce()
        swap.swap()
        assert swap.plan_epoch == 1
        swap.rollback("validation failed")
        # monotone: the restore is itself a plan change
        assert swap.plan_epoch == 2
        assert cache.lookup(large_key()).knobs["algorithm"] == "ring"

    def test_stale_plan_gate_names_key_stale_and_current(self):
        cache, swap = self.make()
        swap.propose(self.rival())
        swap.quiesce()
        swap.swap()
        swap.validate(1)  # current: fine
        with pytest.raises(StalePlanError) as e:
            swap.validate(0, what="straggler chunk")
        assert e.value.stale == 0 and e.value.current == 1
        assert e.value.key == large_key().signature()
        assert "straggler chunk" in str(e.value)
        assert "never folded in" in str(e.value)

    def test_revision_is_monotone_across_swaps(self):
        cache, swap = self.make()
        for expect in (1, 2):
            swap.propose(self.rival())
            swap.quiesce()
            assert swap.swap().revision == expect
            swap.commit()


# ---------------------------------------------------------------------------
# CacheEntry.revision: the staleness satellite
# ---------------------------------------------------------------------------


class TestCacheRevision:
    def test_default_revision_zero_keeps_json_byte_stable(self):
        e = CacheEntry({"algorithm": "ring"}, cost_us=1.0)
        assert e.revision == 0
        assert "revision" not in e.to_json()
        e2 = dataclasses.replace(e, revision=3)
        assert e2.to_json()["revision"] == 3
        back = CacheEntry.from_json("sig", e2.to_json())
        assert back.revision == 3

    @pytest.mark.parametrize("junk", ["1", 1.5, -1, True])
    def test_malformed_revision_is_loud(self, junk):
        with pytest.raises(PlanCacheError, match="revision"):
            CacheEntry.from_json("sig", {"knobs": {}, "revision": junk})

    def test_late_offline_sweep_cannot_resurrect_a_retired_plan(self):
        """THE interleaving regression: the live tuner retires ring
        (revision 1); a late-arriving offline sweep merge carries a
        better-measured ring entry at revision 0 — it must lose."""
        cache = stale_ring_cache()
        swap = PlanSwap(cache, large_key())
        swap.propose(CacheEntry({"algorithm": "rs_ag"},
                                provenance="live:retune:test"))
        swap.quiesce()
        swap.swap()
        swap.commit()
        # yesterday's sweep finishes late and merges in: measured ring
        # "better" than the live entry's (unmeasured) cost
        late_sweep = PlanCache()
        late_sweep.put(large_key(), CacheEntry(
            {"algorithm": "ring"}, cost_us=1.0,
            provenance="sweep:late",
        ))
        cache.merge(late_sweep)
        survivor = cache.lookup(large_key())
        assert survivor.knobs["algorithm"] == "rs_ag"
        assert survivor.revision == 1
        # ...and a LATER live revision displaces the earlier one
        newer = PlanCache()
        newer.put(large_key(), CacheEntry(
            {"algorithm": "hierarchical"}, revision=2,
            provenance="live:retune:newer",
        ))
        cache.merge(newer)
        assert cache.lookup(large_key()).knobs["algorithm"] \
            == "hierarchical"

    def test_revision_zero_pairs_keep_the_original_merge_rules(self):
        a = CacheEntry({"x": 1}, cost_us=5.0)
        b = CacheEntry({"x": 2}, cost_us=3.0)
        unmeasured = CacheEntry({"x": 3})
        assert b.better_than(a) and not a.better_than(b)
        assert not unmeasured.better_than(a)
        assert a.better_than(unmeasured)
        assert unmeasured.better_than(CacheEntry({"x": 4}))


# ---------------------------------------------------------------------------
# OnlineTuner: thresholds, proposals, observability
# ---------------------------------------------------------------------------


class TestOnlineTuner:
    def test_negative_sample_is_loud(self):
        with pytest.raises(ValueError, match="negative sample"):
            OnlineTuner().record("all_reduce", -1.0)

    def test_below_min_samples_never_proposes(self):
        tuner = fed_tuner(samples=DEFAULT_RETUNE_MIN_SAMPLES - 1)
        assert tuner.maybe_propose() == []
        tuner.record("all_reduce",
                     priced_sample_us("all_reduce", "ring", LARGE,
                                      TOPO8) * 1e-6,
                     payload_bytes=LARGE, tenant="t0")
        assert len(tuner.maybe_propose()) == 1

    def test_inside_the_margin_band_never_proposes(self):
        """Noise can't flip: measured just UNDER margin*rival holds
        the plan; just over proposes."""
        rival_us = priced_sample_us("all_reduce", "rs_ag", LARGE, TOPO8)
        for factor, expect in ((0.98, 0), (1.02, 1)):
            cache = stale_ring_cache()
            tuner = OnlineTuner(cache=cache, topo=TOPO8,
                                device_kind="live-sim")
            us = rival_us * tuner.margin * factor
            for _ in range(tuner.min_samples):
                tuner.record("all_reduce", us * 1e-6,
                             payload_bytes=LARGE, tenant="t0")
            assert len(tuner.maybe_propose()) == expect, factor

    def test_no_active_entry_means_nothing_to_retune(self):
        tuner = fed_tuner(cache=PlanCache())
        assert tuner.maybe_propose() == []

    def test_small_payload_with_good_plan_never_proposes(self):
        """At 64 KiB the ring IS the best candidate: even a stale
        entry naming it holds (the rival rs_ag models slower)."""
        cache = PlanCache()
        key = PlanKey("all_reduce", payload_bucket(SMALL), "float32",
                      "live-sim", _collective_topology(TOPO8))
        cache.put(key, CacheEntry({"algorithm": "ring"}, cost_us=130.0,
                                  provenance="sweep:fine"))
        tuner = fed_tuner(cache=cache, payload=SMALL)
        assert tuner.maybe_propose() == []

    def test_full_arc_installs_live_entry_and_resets_cells(self):
        tuner = fed_tuner(samples=20, tenant="t3")
        (swap,) = tuner.maybe_propose()
        ev = swap.proposal.evidence
        assert ev["from"] == "ring" and ev["to"] == "rs_ag"
        assert ev["samples"] == 20
        tuner.start_quiesce(swap)
        installed = tuner.execute_swap(swap)
        tuner.commit(swap)
        assert installed.provenance.startswith("live:retune:")
        assert "samples=20" in installed.provenance
        assert "margin=" in installed.provenance
        assert "tenant=t3" in installed.provenance
        assert installed.revision == 1
        assert tuner.swaps == 1 and tuner.proposals == 1
        # the cell reset: fresh window measures the NEW plan, so the
        # committed swap cannot immediately re-propose itself away
        rs_ag_us = priced_sample_us("all_reduce", "rs_ag", LARGE, TOPO8)
        for _ in range(tuner.min_samples):
            tuner.record("all_reduce", rs_ag_us * 1e-6,
                         payload_bytes=LARGE, tenant="t3")
        assert tuner.maybe_propose() == []

    def test_rollback_counts_and_emits(self):
        rec = FlightRecorder()
        tuner = fed_tuner(recorder=rec)
        (swap,) = tuner.maybe_propose()
        tuner.rollback(swap, "quiesce-timeout")
        assert tuner.rollbacks == 1
        assert rec.counts.get("tune.rollback") == 1
        assert tuner.cache.lookup(large_key()).knobs["algorithm"] \
            == "ring"

    def test_timed_sink_plumbing(self):
        """``tracing.timed(sink=tuner)`` streams a wall-clock sample
        into the tuner with no adapter (the SampleSink shape)."""
        from smi_tpu.utils.tracing import timed

        tuner = OnlineTuner()
        result, elapsed = timed(lambda: 41 + 1, sink=tuner,
                                op="all_reduce", payload_bytes=LARGE,
                                tenant="t9")
        assert result == 42
        assert tuner.samples_ingested == 1
        key = ("all_reduce", sample_bucket_bytes(LARGE), "t9")
        assert tuner.cells[key].count == 1

    def test_metrics_snapshot_equals_bookkeeping(self):
        """Satellite: the tune.* counters are incremented at the
        tuner's own accounting sites — snapshot == bookkeeping."""
        metrics = MetricsRegistry()
        rec = FlightRecorder()
        tuner = fed_tuner(samples=20, metrics=metrics, recorder=rec)
        for swap in tuner.maybe_propose():
            tuner.start_quiesce(swap)
            tuner.execute_swap(swap)
            tuner.commit(swap)
        # one more cell that rolls back
        sm = PlanKey("all_reduce", payload_bucket(SMALL), "float32",
                     "live-sim", _collective_topology(TOPO8))
        tuner.cache.put(sm, CacheEntry({"algorithm": "rs_ag"},
                                       provenance="sweep:bad"))
        ring_small = priced_sample_us("all_reduce", "rs_ag", SMALL,
                                      TOPO8) * tuner.margin * 1.1
        for _ in range(tuner.min_samples):
            tuner.record("all_reduce", ring_small * 1e-6,
                         payload_bytes=SMALL, tenant="t0")
        (swap2,) = tuner.maybe_propose()
        tuner.rollback(swap2, "test")
        counters = metrics.snapshot()["counters"]
        assert sum(v for k, v in counters.items()
                   if k.startswith("tune_samples_total")) \
            == tuner.samples_ingested
        assert sum(v for k, v in counters.items()
                   if k.startswith("tune_proposals_total")) \
            == tuner.proposals == 2
        assert sum(v for k, v in counters.items()
                   if k.startswith("tune_swaps_total")) \
            == tuner.swaps == 1
        assert sum(v for k, v in counters.items()
                   if k.startswith("tune_rollbacks_total")) \
            == tuner.rollbacks == 1
        # ...and the event stream agrees
        assert rec.counts["tune.sample"] == tuner.samples_ingested
        assert rec.counts["tune.propose"] == 2
        assert rec.counts["tune.swap"] == 1
        assert rec.counts["tune.rollback"] == 1

    def test_sample_event_schema_is_valid(self):
        rec = FlightRecorder()
        tuner = OnlineTuner(recorder=rec)
        tuner.record("all_reduce", 1e-3, payload_bytes=LARGE,
                     tenant="t0")
        (event,) = rec.events()
        assert event.plane == "tuning" and event.kind == "tune.sample"
        payload = event.to_json()
        assert payload["op"] == "all_reduce"
        assert payload["bucket"] == sample_bucket_bytes(LARGE)

    def test_ingest_sample_sink_round_trip(self):
        sink = SampleSink()
        us = priced_sample_us("all_reduce", "ring", LARGE, TOPO8)
        for _ in range(20):
            sink.record("all_reduce", us * 1e-6, payload_bytes=LARGE,
                        tenant="t1")
        for form in (sink, sink.snapshot(), sink.entries()):
            tuner = OnlineTuner(cache=stale_ring_cache(), topo=TOPO8,
                                device_kind="live-sim")
            assert tuner.ingest(form) == 20
            assert len(tuner.maybe_propose()) == 1, type(form)

    @pytest.mark.parametrize("junk", [
        42, [{"cost_us": 1.0}], [{"knobs": {}, "cost_us": 1.0}],
        [{"knobs": {"op": "x", "samples": 0}, "cost_us": 1.0}],
    ])
    def test_ingest_junk_is_loud(self, junk):
        with pytest.raises(ValueError):
            OnlineTuner().ingest(junk)


# ---------------------------------------------------------------------------
# SampleSink under retuner load: bucket edges + vocabulary agreement
# ---------------------------------------------------------------------------


class TestBucketBoundaries:
    def test_exact_pow2_edge_payloads_bucket_consistently(self):
        """A payload exactly at a pow2 edge lands in the plan bucket
        that covers [2^k, 2^(k+1)) — and 2^(k+1) starts a new cell —
        in BOTH the tuner's vocabulary and the plan cache's."""
        k = 20
        edge, above, top = 1 << k, (1 << k) + 1, (1 << (k + 1)) - 1
        nxt = 1 << (k + 1)
        assert sample_bucket_bytes(edge) == edge
        assert sample_bucket_bytes(above) == edge
        assert sample_bucket_bytes(top) == edge
        assert sample_bucket_bytes(nxt) == nxt
        assert payload_bucket(edge) == payload_bucket(top) == f"pow2:{k}"
        assert payload_bucket(nxt) == f"pow2:{k + 1}"
        tuner = OnlineTuner()
        for p in (edge, above, top):
            tuner.record("all_reduce", 1e-3, payload_bytes=p)
        tuner.record("all_reduce", 1e-3, payload_bytes=nxt)
        assert tuner.cells[("all_reduce", edge, None)].count == 3
        assert tuner.cells[("all_reduce", nxt, None)].count == 1

    def test_swapped_entry_is_what_the_engine_consults(self):
        """The entry a swap installs for a bucket is exactly the one
        the plan engine resolves for any payload in that bucket —
        edges included — and renders as the ``live`` layer."""
        tuner = fed_tuner(samples=20)
        for swap in tuner.maybe_propose():
            tuner.start_quiesce(swap)
            tuner.execute_swap(swap)
            tuner.commit(swap)
        engine = PlanEngine(cache=tuner.cache, device_kind="live-sim")
        for payload in (LARGE, LARGE + 1, (LARGE << 1) - 1):
            plan = engine.allreduce_plan(payload, TOPO8)
            assert plan.knobs["algorithm"] == "rs_ag", payload
            assert plan.decided_by["algorithm"] == "live", payload

    def test_sample_sink_edge_vocabulary_is_upper_bound(self):
        """The metrics-side SampleSink keeps its documented
        upper-bound grid: exactly-at-edge stays, one-over moves up —
        pinned so the tuner's deliberate divergence (plan-vocabulary
        lower bounds) stays a visible, tested decision."""
        from smi_tpu.obs.metrics import payload_bucket as sink_bucket

        assert sink_bucket(1024) == 1024
        assert sink_bucket(1025) == 2048

    def test_ingest_representative_is_the_sink_bound(self):
        """The documented ingest caveat, pinned: a recorded sink
        bucket maps through its bound, so replaying EXACT-pow2
        traffic lands on the same cell the live record() path uses —
        while interior payloads (lossy by the sink's own grid) land
        one bucket high and must prefer the live path."""
        sink = SampleSink()
        sink.record("all_reduce", 1e-3, payload_bytes=LARGE)      # 4 MiB
        sink.record("all_reduce", 1e-3, payload_bytes=LARGE - 8)  # interior
        offline = OnlineTuner()
        offline.ingest(sink)
        live = OnlineTuner()
        live.record("all_reduce", 1e-3, payload_bytes=LARGE)
        live.record("all_reduce", 1e-3, payload_bytes=LARGE - 8)
        # exact-pow2: offline cell == live cell (both at the 4 MiB key)
        assert ("all_reduce", LARGE, None) in offline.cells
        assert ("all_reduce", LARGE, None) in live.cells
        # interior: the sink already merged it into its 4 MiB bucket,
        # so offline sees ONE cell where live keeps two — the lossy
        # half of the caveat, held visible here
        assert offline.cells[("all_reduce", LARGE, None)].count == 2
        assert live.cells[("all_reduce", LARGE >> 1, None)].count == 1


# ---------------------------------------------------------------------------
# The engine's live tier
# ---------------------------------------------------------------------------


class TestLiveTier:
    def test_layers_ladder_names_live_after_cache(self):
        assert LAYERS == ("cache", "live", "model", "heuristic")

    def test_cache_entry_layer_discriminates_on_provenance(self):
        live = CacheEntry({"algorithm": "rs_ag"},
                          provenance="live:retune:samples=16:margin=2x")
        swept = CacheEntry({"algorithm": "rs_ag"},
                           provenance="sweep:allreduce:4096KiB:n8")
        assert cache_entry_layer(live) == "live"
        assert cache_entry_layer(swept) == "cache"

    def test_plan_source_ranks_live_between_cache_and_model(self):
        from smi_tpu.tuning.plan import Plan

        plan = Plan(key=large_key(), knobs={"algorithm": "rs_ag"},
                    decided_by={"algorithm": "live"})
        assert plan.source == "live"

    def test_explain_names_samples_and_margin(self):
        cache = PlanCache()
        cache.put(large_key(), CacheEntry(
            {"algorithm": "rs_ag"}, revision=1,
            provenance="live:retune:samples=48:margin=1.90x:tenant=t3",
        ))
        engine = PlanEngine(cache=cache, device_kind="live-sim")
        text = engine.allreduce_plan(LARGE, TOPO8).explain()
        assert "[live]" in text
        assert "samples=48" in text and "margin=1.90x" in text
        assert "revision 1" in text

    def test_sweep_entries_still_render_as_cache(self):
        engine = PlanEngine(cache=stale_ring_cache(),
                            device_kind="live-sim")
        plan = engine.allreduce_plan(LARGE, TOPO8)
        assert plan.decided_by["algorithm"] == "cache"

    def test_alltoall_live_tier(self):
        cache = PlanCache()
        key = PlanKey("all_to_all", payload_bucket(LARGE), "float32",
                      "live-sim", _collective_topology(TOPO8))
        cache.put(key, CacheEntry(
            {"algorithm": "bruck"},
            provenance="live:retune:samples=20:margin=4.10x",
        ))
        engine = PlanEngine(cache=cache, device_kind="live-sim")
        plan = engine.alltoall_plan(LARGE, TOPO8)
        assert plan.decided_by["algorithm"] == "live"


# ---------------------------------------------------------------------------
# The seeded payload-shift campaign cells
# ---------------------------------------------------------------------------


class TestRetuneCell:
    def test_flat_cell_converges_to_rs_ag(self):
        from smi_tpu.serving.campaign import run_retune_cell

        rep = run_retune_cell(n=4, seed=0, duration=160)
        assert rep["ok"], rep["verdict"]
        rt = rep["retune"]
        assert rt["swaps"] >= 1 and rt["rollbacks"] == 0
        assert rep["converged_algorithm"] == "rs_ag"
        assert rep["converged_algorithm"] == rep["expected_algorithm"]
        assert rep["converged_revision"] == 1
        assert rep["convergence_ticks"] is not None
        assert rep["swap_tick"] >= rep["shift_at"]
        assert rep["silent_corruptions"] == 0
        assert rep["lost_accepted"] == 0
        assert rep["stale_epoch_leaks"] == 0
        assert rt["stale_plan_leaks"] == 0
        assert rt["stale_plan_rejections"] >= 1

    def test_pod_cell_converges_to_hierarchical(self):
        from smi_tpu.serving.campaign import run_retune_cell

        rep = run_retune_cell(n=4, seed=1, duration=160, slices=2)
        assert rep["ok"], rep["verdict"]
        assert rep["converged_algorithm"] == "hierarchical"

    def test_tenant_churn_failover_during_the_window(self):
        """Satellite: samples keep flowing from a tenant whose
        destination failed over mid-window — the cells stay separate,
        the failover completes, and the tuner still converges."""
        from smi_tpu.serving.campaign import run_retune_cell

        rep = run_retune_cell(n=4, seed=3, duration=240, kill_rank=1)
        assert rep["ok"], rep["verdict"]
        assert rep["confirmed"] == [1]
        assert rep["converged_algorithm"] == "rs_ag"
        assert rep["replayed_chunks"] >= 0

    def test_cell_is_deterministic_per_seed(self):
        from smi_tpu.serving.campaign import run_retune_cell

        a = run_retune_cell(n=4, seed=7, duration=160)
        b = run_retune_cell(n=4, seed=7, duration=160)
        assert json.dumps(a, sort_keys=True) \
            == json.dumps(b, sort_keys=True)

    def test_degenerate_shapes_are_loud(self):
        from smi_tpu.serving.campaign import run_retune_cell

        with pytest.raises(ValueError, match="minimum"):
            run_retune_cell(duration=60)
        with pytest.raises(ValueError, match="same payload bucket"):
            run_retune_cell(small_kb=64, large_kb=100)
        with pytest.raises(ValueError, match="slices"):
            run_retune_cell(slices=3)
        with pytest.raises(ValueError, match="never fires"):
            run_retune_cell(duration=160, kill_rank=0, kill_at=200)

    def test_frontend_replans_streams_admitted_during_quiesce(self):
        from smi_tpu.serving.campaign import run_retune_cell

        rep = run_retune_cell(n=4, seed=0, duration=160)
        # the report carries the re-plan bookkeeping (>= 0; the drain
        # discipline means the count is exactly the proposing tenant's
        # streams admitted between propose and swap)
        assert rep["retune"]["replanned_streams"] >= 0

    @pytest.mark.slow
    def test_long_drift_soak(self):
        """The long soak: more seeds, longer schedules, both
        topologies — every cell green."""
        from smi_tpu.serving.campaign import run_retune_cell

        for seed in range(4):
            for slices in (None, 2):
                rep = run_retune_cell(n=4, seed=seed, duration=480,
                                      slices=slices)
                assert rep["ok"], (seed, slices, rep["verdict"])


# ---------------------------------------------------------------------------
# The model-checker scope + mutants (the acceptance matrix)
# ---------------------------------------------------------------------------


class TestModelRetune:
    def scope(self):
        from smi_tpu import analysis as A

        (scope,) = [s for s in A.DEFAULT_SCOPES if s.retune]
        return scope

    def test_clean_retune_scope_exhausts_ok(self):
        from smi_tpu import analysis as A

        report = A.check_scope(self.scope())
        assert report.ok, report.describe()
        assert not report.truncated
        assert "plan-epoch-safety" in report.properties
        assert "swap-lost-accepted" in report.properties

    def test_swap_without_quiesce_minimal_trace(self):
        """THE acceptance criterion: convicted by exactly
        plan-epoch-safety, with the BFS-minimal 4-step trace
        admit -> propose -> quiesce -> swap, replayable as a failing
        campaign cell."""
        from smi_tpu import analysis as A
        from smi_tpu.serving.campaign import (
            MODEL_GATES,
            replay_model_trace,
        )

        report = A.check_scope(
            self.scope(),
            world_factory=A.model_mutant_world("swap_without_quiesce"),
            mutant="swap_without_quiesce",
        )
        assert not report.ok
        assert {f.property for f in report.findings} \
            == {"plan-epoch-safety"}
        finding = report.findings[0]
        kinds = [a[0] for a in finding.trace]
        assert kinds == ["admit", "plan_propose", "plan_quiesce",
                         "plan_swap"]
        cell = replay_model_trace(self.scope(), finding.trace,
                                  mutant="swap_without_quiesce")
        assert not cell["ok"]
        assert MODEL_GATES["plan-epoch-safety"] in cell["verdict"]

    def test_rollback_discards_entry_conviction(self):
        from smi_tpu import analysis as A
        from smi_tpu.serving.campaign import (
            MODEL_GATES,
            replay_model_trace,
        )

        report = A.check_scope(
            self.scope(),
            world_factory=A.model_mutant_world(
                "rollback_discards_entry"),
            mutant="rollback_discards_entry",
        )
        assert not report.ok
        assert {f.property for f in report.findings} \
            == {"swap-lost-accepted"}
        finding = report.findings[0]
        assert [a[0] for a in finding.trace] \
            == ["plan_propose", "plan_abort"]
        cell = replay_model_trace(self.scope(), finding.trace,
                                  mutant="rollback_discards_entry")
        assert not cell["ok"]
        assert MODEL_GATES["swap-lost-accepted"] in cell["verdict"]

    def test_retune_mutants_benign_on_non_retune_scopes(self):
        """The swap seams are inert without a swap machine: both
        mutants are clean on every scope with retune=0."""
        from smi_tpu import analysis as A

        scope = A.DEFAULT_SCOPES[0]
        for mutant in ("swap_without_quiesce",
                       "rollback_discards_entry"):
            report = A.check_scope(
                scope, world_factory=A.model_mutant_world(mutant),
                mutant=mutant,
            )
            assert report.ok, mutant

    def test_scope_validation(self):
        from smi_tpu import analysis as A

        with pytest.raises(ValueError, match="retune"):
            A.Scope(retune=2)
        parsed = A.parse_scope("tenants=2,ranks=2,retune=1")
        assert parsed.retune == 1

    def test_clean_world_report_carries_the_retune_block(self):
        from smi_tpu import analysis as A

        world = A.World(self.scope())
        for action in ((("admit", 0)), ("plan_propose",),
                       ("plan_quiesce",)):
            world.apply(tuple(action))
        rep = world.report()
        assert rep["retune"]["swap_state"] == "quiescing"
        assert rep["retune"]["active_algorithm"] == "ring"


# ---------------------------------------------------------------------------
# bench.py: the additive retune field
# ---------------------------------------------------------------------------


class TestBenchRetuneField:
    def test_retune_fields_shape_and_gates(self):
        import bench

        fields = bench.retune_fields()
        assert fields["ok"] is True
        assert fields["swaps"] >= 1
        assert fields["rollbacks"] == 0
        assert fields["converged_algorithm"] \
            == fields["expected_algorithm"] == "rs_ag"
        assert fields["convergence_ticks"] is not None
        assert fields["samples_ingested"] > 0

    def test_render_line_keeps_the_legacy_contract(self):
        """The retune field is ADDITIVE: the one-line schema
        (metric/value/unit/vs_baseline) renders unchanged with it
        present."""
        import bench

        payload = {
            "metric": "stencil_throughput", "value": 1.0,
            "unit": "Gcell/s", "vs_baseline": 1.0,
            "retune": {"swaps": 1, "ok": True},
        }
        line = bench.render_line(payload)
        parsed = json.loads(line)
        for key in ("metric", "value", "unit", "vs_baseline"):
            assert key in parsed
        assert parsed["retune"]["swaps"] == 1
