"""Distributed GESUMMV: y = alpha*A@x + beta*B@x across two ranks.

Reference parity: ``examples/kernels/gesummv_rank0.cl`` /
``gesummv_rank1.cl`` + ``examples/host/gesummv_smi.cpp`` — the canonical
MPMD/tensor-parallel example: rank 1 computes ``beta*B@x`` and streams the
result through P2P port 0 (``gesummv_rank1.cl:95,182``); rank 0 computes
``alpha*A@x`` and an axpy kernel pops each element and combines it with
its own partial result as it arrives (``gesummv_rank0.cl:184-197``).
Verified against BLAS (``gesummv_smi.cpp:300-301``).

TPU re-design: one SPMD program over a 2-device mesh; rank divergence is a
masked operand (each rank's matrix is its shard of a stacked operand pair,
so the matvec runs on the MXU on both ranks), and the streamed combine is
the channel's chunked ``stream()`` with an axpy consumer — transfer of
chunk k+1 overlaps the combine of chunk k, exactly the reference's
pop-inside-compute-loop.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from smi_tpu.parallel.mesh import Communicator, make_communicator


def make_gesummv_fn(
    comm: Communicator,
    n: int,
    alpha: float,
    beta: float,
    buffer_size: Optional[int] = 2048,
    precision=None,
):
    """Build the jitted 2-rank GESUMMV.

    Takes the stacked operand ``AB`` of shape ``(2, n, n)`` sharded so
    rank 0 holds A and rank 1 holds B, plus the replicated vector ``x``.
    Returns ``y`` valid on rank 0 (the reference's result rank).
    """
    if comm.size != 2:
        raise ValueError(f"gesummv runs on exactly 2 ranks, got {comm.size}")
    axis = comm.axis_names[0]

    def shard_fn(ab_local, x):
        # ab_local: (1, n, n) — this rank's matrix
        mat = ab_local[0]
        rank = comm.rank()
        scale = jnp.where(rank == 0, alpha, beta).astype(mat.dtype)
        # HIGHEST precision by default: TPU matmuls otherwise round
        # operands to bf16; the reference verifies against exact-f32
        # BLAS. Pass Precision.DEFAULT for the native bf16 MXU rate.
        partial_y = scale * jnp.matmul(
            mat, x, precision=precision or lax.Precision.HIGHEST
        )  # MXU matvec on both ranks

        from smi_tpu.parallel.channels import P2PChannel

        ch = P2PChannel(
            comm=comm, port=0, src=1, dst=0, count=n,
            dtype="float" if mat.dtype == jnp.float32 else "double",
            buffer_size=buffer_size,
        )

        # Streamed axpy: rank 0's consumer folds each arriving chunk of
        # beta*B@x into its own alpha*A@x slice while later chunks are
        # still in flight (gesummv_rank0.cl:184-197).
        def axpy(carry, chunk):
            y, offset = carry
            y = lax.dynamic_update_slice(
                y,
                lax.dynamic_slice(y, (offset,), (chunk.shape[0],)) + chunk,
                (offset,),
            )
            return y, offset + chunk.shape[0]

        _received, (y, _) = ch.stream(
            partial_y, consumer=axpy, init_carry=(partial_y, 0)
        )
        # y now holds alpha*A@x + beta*B@x on rank 0; rank 1's copy added
        # only zeros (it received nothing).
        return y[None]

    mapped = jax.jit(
        jax.shard_map(
            shard_fn,
            mesh=comm.mesh,
            in_specs=(P(axis), P()),
            out_specs=P(axis),
            check_vma=False,
        )
    )

    def fn(ab, x):
        return mapped(ab, x)[0]  # rank 0's row

    return fn


def run_gesummv(
    a: np.ndarray,
    b: np.ndarray,
    x: np.ndarray,
    alpha: float = 1.0,
    beta: float = 1.0,
    comm: Optional[Communicator] = None,
    devices=None,
) -> jax.Array:
    if comm is None:
        comm = make_communicator(2, devices=devices)
    n = a.shape[0]
    ab = jnp.stack([jnp.asarray(a), jnp.asarray(b)])
    return make_gesummv_fn(comm, n, alpha, beta)(ab, jnp.asarray(x))


def reference_gesummv(a, b, x, alpha=1.0, beta=1.0) -> np.ndarray:
    """BLAS-equivalent serial reference (``gesummv_smi.cpp:300-301``)."""
    return alpha * (np.asarray(a) @ np.asarray(x)) + beta * (
        np.asarray(b) @ np.asarray(x)
    )
