"""The shipped default plan cache: PERF.json's measured-best configs.

Satellite of the plan engine: v5e defaults start at the *measured*
optimum instead of the dtype heuristic. Every entry cites the PERF.json
metric whose sweep produced it, and ``tests/test_perf_docs.py`` pins the
knob values against the committed measurement configs (the same drift
discipline as the README perf tables):

- bf16 causal forward: ``bq=1024 / bk=1024`` — the r5 interleaved A/B's
  bq=1024 forward tile (+1.5% at S=8192, +11% windowed) and the
  hand-swept ``block_q_kmajor_k = [1024, 1024, 1024]`` tier of
  ``flash_vs_stock_swept`` (0.98x vs 6.4x at defaults: the row that
  proves measured sweeps dominate heuristics).
- bf16 *windowed* forward: ``bk`` narrows to 512 — measured +3% at
  S=32k/window=4096 (107.6 vs 104.5 TF/s): finer tiles waste less dead
  span at the window edges.
- f32 forward keeps ``512/512`` — f32 measured fractionally *slower*
  at bk=1024 (the case the analytic model ranks wrong, which is why
  measurement outranks it).
- temporal stencil: ``depth=16`` — the measured knee (131.7 Gcell/s);
  beyond it halo-ring recompute cancels the HBM savings.
- the rs+ag switch tier ships the HLO-verified 1 MiB threshold as a
  *cache entry*, so ``smi-tpu tune`` sweeps can move it per fleet
  without a code change (env ``SMI_TPU_RS_AG_MIN_BYTES`` still wins).

Seeded costs are microseconds per timed rep, derived from each metric's
committed differential timing ``[r, 4r, t_r, t_4r]`` as
``(t_4r - t_r) / (4r - r) * 1e6`` — comparable with sweep results, so
a merge prefers whichever config actually measured faster.
"""

from __future__ import annotations

from smi_tpu.tuning.cache import CacheEntry, PlanCache
from smi_tpu.tuning.plan import PlanKey

#: the device kind every seeded entry is keyed to (normalized form of
#: PERF.json's "TPU v5 lite0" / jax's device_kind "TPU v5 lite")
SEEDED_DEVICE_KIND = "tpu v5 lite"

#: knob values drift-guarded against PERF.json configs
SEEDED_FLASH_BF16_BLOCKS = (1024, 1024)       # flash_vs_stock_swept
SEEDED_FLASH_BF16_WINDOW_BLOCKS = (1024, 512)
SEEDED_FLASH_F32_BLOCKS = (512, 512)
SEEDED_STENCIL_DEPTH = 16                     # stencil_temporal_gcells
SEEDED_RS_AG_MIN_BYTES = 1 << 20              # the HLO-verified switch

#: r18 explicit-DMA pipeline winner at the canonical 8192^2 block: the
#: 3-slot rotation with depth 8 / stripe 128 / f32 compute. Overlap
#: inverts the temporal depth knee — once the stripe stream hides
#: behind compute, the shallower depth's smaller recompute apron wins
#: (cost_model.stencil_pipeline_candidates; the un-pipelined temporal
#: entry above keeps its measured depth-16 knee untouched).
SEEDED_STENCIL_PIPELINE_KNOBS = {
    "algorithm": "pipeline", "depth": 8, "stripe": 128,
    "compute_dtype": "float32", "buffering": 3,
}


def _us(timing) -> float:
    """Per-rep microseconds of a PERF.json differential timing row."""
    r, r4, t_r, t_r4 = timing
    return (t_r4 - t_r) / (r4 - r) * 1e6


def seeded_cache() -> PlanCache:
    """A fresh copy of the shipped default cache (callers may merge
    user sweeps over it without aliasing)."""
    dk = SEEDED_DEVICE_KIND
    cache = PlanCache()

    bq, bk = SEEDED_FLASH_BF16_BLOCKS
    cache.put(
        PlanKey("flash_fwd", "causal", "bfloat16", dk, "chip"),
        CacheEntry(
            {"block_q": bq, "block_k": bk},
            cost_us=_us([256, 512, 0.3992, 0.6978]),
            provenance="seeded:PERF.json:flash_attn_fwd_s8192_bf16"
                       "+flash_vs_stock_swept",
        ),
    )
    bq, bk = SEEDED_FLASH_BF16_WINDOW_BLOCKS
    cache.put(
        PlanKey("flash_fwd", "window", "bfloat16", dk, "chip"),
        CacheEntry(
            {"block_q": bq, "block_k": bk},
            cost_us=_us([256, 512, 1.4007, 2.7085]),
            provenance="seeded:PERF.json:"
                       "flash_attn_fwd_s32768_bf16_window4096",
        ),
    )
    bq, bk = SEEDED_FLASH_F32_BLOCKS
    cache.put(
        PlanKey("flash_fwd", "causal", "float32", dk, "chip"),
        CacheEntry(
            {"block_q": bq, "block_k": bk},
            cost_us=_us([64, 256, 0.4386, 1.4499]),
            provenance="seeded:PERF.json:flash_attn_fwd_s8192_f32",
        ),
    )
    cache.put(
        PlanKey("stencil_temporal", "8192", "float32", dk, "chip"),
        CacheEntry(
            {"depth": SEEDED_STENCIL_DEPTH},
            cost_us=_us([16, 64, 1.1119, 4.2417]),
            provenance="seeded:PERF.json:stencil_temporal_gcells",
        ),
    )
    cache.put(
        PlanKey("stencil_pipeline", "8192", "float32", dk, "chip"),
        CacheEntry(
            dict(SEEDED_STENCIL_PIPELINE_KNOBS),
            cost_us=None,
            provenance="seeded:cost_model.stencil_pipeline_candidates"
                       ":8192 (proxy-sweep winner; unmeasured until a"
                       " TPU runs `smi-tpu tune --ops stencil`)",
        ),
    )
    cache.put(
        PlanKey("all_reduce", "threshold", "", dk, "any"),
        CacheEntry(
            {"rs_ag_min_bytes": SEEDED_RS_AG_MIN_BYTES},
            cost_us=None,
            provenance="seeded:collectives.RS_AG_MIN_BYTES "
                       "(HLO-verified switch test)",
        ),
    )
    return cache
