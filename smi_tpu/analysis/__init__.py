"""Static verification of the credits protocol zoo + the control plane.

The compile-time correctness tiers: :mod:`.verifier` proves
deadlock-freedom, slot-race-freedom, credit conservation, and wire-lane
monotonicity over every schedule of a registered protocol from a single
symbolic replay per rank (happens-before analysis — Lamport CACM'78,
Eraser SOSP'97; see PAPERS.md); :mod:`.model` + :mod:`.properties` are
the control-plane analog — an explicit-state model checker that
exhaustively verifies the epoch, admission, and recovery state machines
at small scopes by driving the REAL serving/membership/WAL objects
(``smi-tpu lint --model``); :mod:`.mutants` ships the broken variants —
protocol-tier event-stream transformers and control-plane seam breaks —
that prove every check can fail. Pure Python — no JAX, no devices — so
``smi-tpu lint`` runs anywhere in seconds and CI can gate merges on it.
The dynamic schedule fuzzer (``credits.explore_all_schedules``) and the
chaos campaigns remain the authority on *faulted wire* behaviour;
``docs/analysis.md`` states exactly what each tier does and does not
prove.
"""

from smi_tpu.analysis.verifier import (  # noqa: F401
    CHECKS,
    DEFAULT_SHAPES,
    MAX_LINT_N,
    AnalysisError,
    CreditConservation,
    Finding,
    SlotRace,
    StaticDeadlock,
    StaticReport,
    VerifyEvent,
    WireLaneViolation,
    build_generators,
    lint_all,
    render_reports,
    reports_to_json,
    symbolic_events,
    verify_generators,
    verify_protocol,
)
from smi_tpu.analysis.mutants import (  # noqa: F401
    MODEL_MUTANT_PROPERTY,
    MODEL_MUTANTS,
    MUTANTS,
    model_mutant_world,
    mutant_generators,
)
from smi_tpu.analysis.model import (  # noqa: F401
    DEFAULT_SCOPES,
    ModelFinding,
    ModelReport,
    Scope,
    World,
    check_scope,
    check_scopes,
    model_reports_to_json,
    parse_scope,
    render_model_reports,
)
from smi_tpu.analysis.properties import PROPERTIES  # noqa: F401
