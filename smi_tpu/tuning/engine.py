"""The plan engine: cache -> analytic model -> heuristic, never erroring.

One object answers every "which knob value here?" question the trace
paths used to answer with frozen constants. Resolution order per knob:

1. **cache** — a measured entry in the persistent plan cache (the
   shipped seeded cache merged with the user's ``$SMI_TPU_PLAN_CACHE``
   file). Measurement always has the last word.
2. **model** — the deterministic alpha-beta / roofline ranking
   (:mod:`smi_tpu.tuning.cost_model`). At trace time the model layer
   only decides where it is *confident* (payload at least
   :data:`RS_AG_MODEL_MARGIN` x away from its own crossover) and only
   when no explicit threshold override (env or cache) is in force — an
   unmeasured model ranking near its crossover must never silently flip
   a compiled program away from the measured default. ``smi-tpu tune
   --explain`` always shows the full model ranking.
3. **heuristic** — today's frozen defaults (``RS_AG_MIN_BYTES``, the
   dtype-keyed flash block constants, ``chunks=1``), byte-for-byte the
   pre-engine behavior, so a host with no cache and no model confidence
   compiles exactly what it compiled before this subsystem existed.

Trace-time consultation goes through the ``planned_*`` module functions,
which swallow *every* exception into the heuristic answer — a corrupt
cache file or an exotic backend can cost tuning, never a trace.

The engine is process-global (:func:`get_engine`); tests swap it with
:func:`set_engine` and restore with ``set_engine(None)``.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Dict, Optional, Tuple

from smi_tpu.tuning import cost_model as cm
from smi_tpu.tuning.cache import (
    CACHE_ENV,
    CacheEntry,
    PlanCache,
    default_cache_path,
)
from smi_tpu.tuning.plan import (
    Candidate,
    Plan,
    PlanKey,
    normalize_device_kind,
    payload_bucket,
)
from smi_tpu.tuning.seeded import seeded_cache

#: Model-confidence margin for trace-time algorithm decisions: the
#: model may decide only when the payload is at least this factor away
#: from its own ring/rs+ag crossover. Inside the band the measured
#: threshold default decides. With the calibrated DEFAULT_ALPHA_S the
#: confident decisions provably agree with the 1 MiB heuristic, so
#: enabling the model layer cannot change an untuned program.
RS_AG_MODEL_MARGIN = 4.0

#: Model-confidence margin for the two-tier gate: the model may engage
#: (or veto) the hierarchical form only when its modeled advantage over
#: the best flat form is at least this factor (either direction).
#: Inside the band the conservative answer — today's flat path — wins
#: until a sweep has measured the crossover. Single-slice topologies
#: are never eligible at all, which is what keeps the untuned
#: single-slice byte-identity invariant trivially intact.
HIER_MODEL_MARGIN = 4.0

#: Model-confidence margin for the all-to-all algorithm gate (same
#: discipline): an unmeasured model ranking may pick Bruck or the
#: two-tier form only when its modeled advantage over the pairwise
#: default is at least this factor. Inside the band the fused
#: ``lax.all_to_all`` compiles — at the pinned n=8 acceptance shape
#: the pairwise/Bruck ratio is (n-1)/log2(n) ~ 2.3, inside the band,
#: so an untuned program compiles byte-identically to the explicit
#: pairwise form (invariant-tested).
ALLTOALL_MODEL_MARGIN = 4.0


def _valid_flash_block(v) -> bool:
    """A flash tile target the kernels can actually use: a positive
    multiple of the widest sublane tile (16 rows bf16), bounded well
    above any real extent. Anything else is value-junk that would make
    ``_pick_block`` find no divisor and fail the trace."""
    return (
        isinstance(v, int) and not isinstance(v, bool)
        and 16 <= v <= (1 << 16) and v % 16 == 0
    )


def _collective_topology(topo: cm.TopologySpec) -> str:
    if topo.hierarchical_eligible:
        return f"n{topo.n}:dcn{topo.outer}"
    return f"n{topo.n}"


def cache_entry_layer(entry) -> str:
    """The explain-surface layer of a cache hit: ``"live"`` when the
    entry was written by the online retuner (its ``live:`` provenance
    names the sample count and win margin — the env -> cache -> live
    -> model -> heuristic ladder), else ``"cache"``."""
    provenance = str(getattr(entry, "provenance", "") or "")
    return "live" if provenance.startswith("live:") else "cache"


def _cache_hit_rationale(hit) -> Tuple[str, str]:
    """(layer, rationale line) for one algorithm cache hit — the ONE
    rendering both collective plan surfaces share, so the live-tier
    presentation cannot drift between them."""
    layer = cache_entry_layer(hit)
    if layer == "live":
        # an online-won entry names its sample count and win margin
        # (the provenance the retuner stamped at swap)
        return layer, (f"live retune entry ({hit.provenance}, "
                       f"revision {hit.revision})")
    return layer, (
        f"cache entry ({hit.provenance or 'measured sweep'}"
        + (f", {hit.cost_us:.1f} us" if hit.cost_us is not None
           else "") + ")"
    )


class PlanEngine:
    def __init__(
        self,
        cache: Optional[PlanCache] = None,
        link: Optional[cm.LinkModel] = None,
        device_kind: Optional[str] = None,
    ):
        self.cache = cache if cache is not None else _load_default_cache()
        self.link = link or cm.LinkModel()
        self._device_kind = (
            normalize_device_kind(device_kind) if device_kind else None
        )
        self._memo: Dict[tuple, object] = {}
        self._lock = threading.Lock()

    # -- device identity -------------------------------------------------
    def device_kind(self) -> str:
        """Normalized local device kind (lazy; ``"unknown"`` when no
        backend is reachable — such hosts simply never hit seeded
        device-keyed entries)."""
        if self._device_kind is None:
            self._device_kind = _detect_device_kind()
        return self._device_kind

    def _memoized(self, key: tuple, compute):
        with self._lock:
            if key in self._memo:
                return self._memo[key]
        value = compute()
        with self._lock:
            if len(self._memo) >= 4096:   # trace-cache bound
                self._memo.clear()
            self._memo[key] = value
        return value

    # -- collectives -----------------------------------------------------
    def allreduce_plan(
        self,
        payload_bytes: int,
        topo: cm.TopologySpec,
        dtype: str = "float32",
        device_kind: Optional[str] = None,
    ) -> Plan:
        """Full (algorithm, chunks) plan for an ADD allreduce — the
        ``tune``/``--explain`` entry: the model ranking is applied
        outright when no cache entry exists (the deterministic-CPU
        acceptance surface; the *trace-time* gate is
        :meth:`use_rs_ag`)."""
        dk = normalize_device_kind(device_kind or self.device_kind())
        key = PlanKey("all_reduce", payload_bucket(payload_bytes), dtype,
                      dk, _collective_topology(topo))
        cands = cm.allreduce_candidates(payload_bytes, topo,
                                        link=self.link)
        knobs: Dict[str, object] = {}
        decided: Dict[str, str] = {}
        rationale = []
        hit = self.cache.lookup(key)
        if hit is not None and "algorithm" in hit.knobs:
            layer, why = _cache_hit_rationale(hit)
            knobs["algorithm"] = hit.knobs["algorithm"]
            decided["algorithm"] = layer
            rationale.append(why)
            cands = [
                Candidate(c.name, c.knobs, c.modeled_us,
                          hit.cost_us if c.knobs.get("algorithm")
                          == hit.knobs["algorithm"] else None, c.note)
                for c in cands
            ]
        else:
            knobs["algorithm"] = cands[0].knobs["algorithm"]
            decided["algorithm"] = "model"
            xover = cm.rs_ag_crossover_bytes(topo.n, self.link)
            rationale.append(
                f"alpha-beta ranking (ring/rs+ag crossover at "
                f"{xover:.0f} B for n={topo.n})"
            )
        chunks, chunks_layer = self.collective_chunks(
            "all_reduce", payload_bytes, topo.n, dtype, device_kind=dk
        )
        knobs["chunks"] = chunks
        decided["chunks"] = chunks_layer
        threshold, thr_layer = self.rs_ag_threshold(device_kind=dk)
        knobs["rs_ag_min_bytes"] = threshold
        decided["rs_ag_min_bytes"] = thr_layer
        if topo.hierarchical_eligible:
            hier, hier_layer = self.use_hierarchical(
                payload_bytes, topo, dtype
            )
            knobs["hierarchical"] = hier
            decided["hierarchical"] = hier_layer
            thr = self.hier_threshold(topo.outer or 0)
            if thr is not None:
                rationale.append(
                    f"two-tier gate: measured flat/hierarchical "
                    f"crossover at {thr[0]} B for dcn{topo.outer} "
                    f"(plan cache)"
                )
            else:
                advantage = cm.hierarchical_advantage(
                    payload_bytes, topo, link=self.link
                )
                rationale.append(
                    f"two-tier gate: modeled advantage "
                    f"{advantage:.2f}x over best flat (engages "
                    f"outside the {HIER_MODEL_MARGIN:g}x confidence "
                    f"band only)"
                )
        pcands = cm.allreduce_precision_candidates(
            payload_bytes, topo, dtype=dtype, link=self.link
        )
        p, p_layer = self.use_precision(payload_bytes, topo, dtype)
        if (hit is not None and "precision" in hit.knobs
                and p == str(hit.knobs["precision"])):
            pcands = cm.CandidateSet(
                [
                    Candidate(c.name, c.knobs, c.modeled_us,
                              hit.cost_us if c.name == p else None,
                              c.note)
                    for c in pcands
                ],
                pcands.excluded,
            )
        knobs["precision"] = p
        decided["precision"] = p_layer
        if p_layer in ("model", "heuristic"):
            rationale.append(
                f"wire precision: dense f32 — the model may propose a "
                f"lossy width only past "
                f"{cm.PRECISION_MODEL_MARGIN:g}x modeled advantage, "
                f"a bar the byte ratio alone cannot clear; int8/topk "
                f"reach the auto path through a measured sweep "
                f"crossover or an explicit pin"
            )
        for dropped in pcands.excluded:
            rationale.append(
                f"excluded {dropped.name}: {dropped.note}"
            )
        cands = list(cands) + list(pcands)
        return Plan(key=key, knobs=knobs, decided_by=decided,
                    candidates=cands, rationale=rationale)

    def rs_ag_threshold(
        self, device_kind: Optional[str] = None
    ) -> Tuple[int, str]:
        """(bytes, layer) of the rs+ag switch tier: plan-cache entry
        when one exists, else the built-in heuristic constant. The env
        override (``SMI_TPU_RS_AG_MIN_BYTES``) is applied by the
        caller (``collectives.rs_ag_min_bytes``) — an explicit user
        setting outranks every engine layer."""
        dk = normalize_device_kind(device_kind or self.device_kind())

        def compute():
            for kind in (dk, "unknown"):
                hit = self.cache.lookup(
                    PlanKey("all_reduce", "threshold", "", kind, "any")
                )
                if hit is not None and "rs_ag_min_bytes" in hit.knobs:
                    return int(hit.knobs["rs_ag_min_bytes"]), "cache"
            from smi_tpu.parallel.collectives import RS_AG_MIN_BYTES

            return int(RS_AG_MIN_BYTES), "heuristic"

        return self._memoized(("rs_ag_threshold", dk), compute)

    def use_rs_ag(
        self,
        payload_bytes: int,
        topo: cm.TopologySpec,
        dtype: str = "float32",
        threshold: Optional[int] = None,
        threshold_layer: str = "env",
    ) -> Tuple[bool, str]:
        """Trace-time algorithm gate for an *eligible* ADD allreduce.

        ``threshold`` given = an explicit override (env var) — it
        decides ALONE: not even a measured cache entry may outrank the
        operator's word (the env path exists precisely to pin the
        bit-exact single-psum form regardless of what a sweep found).
        Otherwise: per-bucket cache entry, then the model where
        confident, then the resolved threshold tier.
        """
        dk = self.device_kind()

        def compute():
            if threshold is not None:
                return payload_bytes >= threshold, threshold_layer
            key = PlanKey("all_reduce", payload_bucket(payload_bytes),
                          dtype, dk, _collective_topology(topo))
            hit = self.cache.lookup(key)
            if hit is not None and "algorithm" in hit.knobs:
                return hit.knobs["algorithm"] == "rs_ag", "cache"
            thr, thr_layer = self.rs_ag_threshold()
            if thr_layer == "heuristic":
                # no explicit tier in force: the model decides where
                # it is confidently away from its own crossover
                xover = cm.rs_ag_crossover_bytes(topo.n, self.link)
                if payload_bytes >= RS_AG_MODEL_MARGIN * xover:
                    return True, "model"
                if payload_bytes <= xover / RS_AG_MODEL_MARGIN:
                    return False, "model"
            return payload_bytes >= thr, thr_layer

        # exact bytes, not the bucket: the threshold/model comparisons
        # are exact, so a bucket-wide memo would be first-call-wins
        # for payloads straddling a crossover inside one bucket
        return self._memoized(
            ("use_rs_ag", payload_bytes, topo, dtype,
             threshold, threshold_layer, dk),
            compute,
        )

    def hier_threshold(
        self, outer: int, device_kind: Optional[str] = None
    ) -> Optional[Tuple[int, str]]:
        """(bytes, "cache") of the measured flat/hierarchical
        crossover for an ``outer``-slice pod, or ``None`` when no
        sweep has persisted one. Written by
        ``sweep.sweep_allreduce_hierarchical`` per (device kind,
        slice count) — the ATLAS discipline: the crossover is a
        measured artifact, not a frozen constant."""
        dk = normalize_device_kind(device_kind or self.device_kind())

        def compute():
            for kind in (dk, "unknown"):
                hit = self.cache.lookup(
                    PlanKey("all_reduce", "hier_threshold", "", kind,
                            f"dcn{outer}")
                )
                if hit is not None and "hier_min_bytes" in hit.knobs:
                    return int(hit.knobs["hier_min_bytes"]), "cache"
            return None

        return self._memoized(("hier_threshold", outer, dk), compute)

    def use_hierarchical(
        self,
        payload_bytes: int,
        topo: cm.TopologySpec,
        dtype: str = "float32",
        min_slices: Optional[int] = None,
        min_slices_layer: str = "env",
    ) -> Tuple[bool, str]:
        """Trace-time gate for the two-tier allreduce on an *eligible*
        payload (ADD, hybrid multi-slice communicator, divisible
        leading dim — structural eligibility is the caller's check).

        ``min_slices`` given = the explicit ``$SMI_TPU_HIER_MIN_SLICES``
        override — it decides ALONE (not even a measured cache entry
        outranks the operator's word), mirroring the rs+ag env
        semantics. Otherwise: per-bucket cache entry, then the
        measured crossover threshold, then the model where its
        advantage is confidently (:data:`HIER_MODEL_MARGIN`) away
        from parity, then the conservative flat default.
        """
        dk = self.device_kind()

        def compute():
            if not topo.hierarchical_eligible:
                return False, "heuristic"
            if min_slices is not None:
                return (topo.outer or 0) >= min_slices, min_slices_layer
            key = PlanKey("all_reduce", payload_bucket(payload_bytes),
                          dtype, dk, _collective_topology(topo))
            hit = self.cache.lookup(key)
            if hit is not None and "algorithm" in hit.knobs:
                return hit.knobs["algorithm"] == "hierarchical", "cache"
            thr = self.hier_threshold(topo.outer or 0)
            if thr is not None:
                return payload_bytes >= thr[0], "cache"
            advantage = cm.hierarchical_advantage(
                payload_bytes, topo, link=self.link
            )
            if advantage >= HIER_MODEL_MARGIN:
                return True, "model"
            if advantage and advantage <= 1.0 / HIER_MODEL_MARGIN:
                return False, "model"
            return False, "heuristic"

        # keyed on EXACT bytes: the threshold/model branches compare
        # exact payloads, so a bucket-wide memo would be
        # first-call-wins for every other payload in the bucket
        return self._memoized(
            ("use_hier", payload_bytes, topo, dtype,
             min_slices, min_slices_layer, dk),
            compute,
        )

    def precision_threshold(
        self, outer: int, device_kind: Optional[str] = None
    ) -> Optional[Tuple[int, str, str]]:
        """(bytes, precision, "cache") of the measured dense/lossy
        wire-width crossover for an ``outer``-slice pod (0 = flat), or
        ``None`` when no sweep has persisted one. Written by
        ``sweep.sweep_allreduce_precision`` per (device kind, slice
        count) — the ATLAS discipline applied to the wire width: a
        lossy precision reaches the auto path only through a
        measurement, never through the model alone."""
        dk = normalize_device_kind(device_kind or self.device_kind())

        def compute():
            for kind in (dk, "unknown"):
                hit = self.cache.lookup(
                    PlanKey("all_reduce", "precision_threshold", "",
                            kind, f"dcn{outer}" if outer else "flat")
                )
                if (hit is not None
                        and "precision_min_bytes" in hit.knobs
                        and "precision" in hit.knobs):
                    return (int(hit.knobs["precision_min_bytes"]),
                            str(hit.knobs["precision"]), "cache")
            return None

        return self._memoized(("precision_threshold", outer, dk),
                              compute)

    def use_precision(
        self,
        payload_bytes: int,
        topo: cm.TopologySpec,
        dtype: str = "float32",
        op: str = "add",
        precision: Optional[str] = None,
        precision_layer: str = "env",
    ) -> Tuple[str, str]:
        """Trace-time wire-precision gate for
        ``collectives.allreduce(precision=None)``.

        ``precision`` given = an explicit override (the ``precision=``
        pin or the ``$SMI_TPU_ALLREDUCE_PRECISION`` env var) — it
        decides ALONE; eligibility (ADD op, floating dtype) is the
        CALLER's loud error, never a silent f32 fallback. Otherwise:
        per-bucket cache entry (skipped with a fall-through when it
        names a precision this op/dtype cannot run — a cache written
        for one call site must not error another), then the measured
        crossover threshold, then the model — which may propose a
        lossy width only past :data:`cm.PRECISION_MODEL_MARGIN`, a
        margin chosen to EQUAL the int8 byte ratio so the modeled
        advantage (strictly below it; the alphas are unchanged) can
        never clear it: the model alone never flips numerics. Then
        the heuristic: dense f32, byte-for-byte the untuned lowering.
        """
        dk = self.device_kind()

        def compute():
            if precision is not None:
                return precision, precision_layer
            key = PlanKey("all_reduce", payload_bucket(payload_bytes),
                          dtype, dk, _collective_topology(topo))
            hit = self.cache.lookup(key)
            if hit is not None and "precision" in hit.knobs:
                p = str(hit.knobs["precision"])
                if (p in cm.ALLREDUCE_PRECISIONS
                        and cm.precision_ineligibility(
                            p, op, dtype, payload_bytes) is None):
                    return p, cache_entry_layer(hit)
            outer = ((topo.outer or 0)
                     if topo.hierarchical_eligible else 0)
            thr = self.precision_threshold(outer)
            if thr is not None:
                min_bytes, p, _layer = thr
                if (payload_bytes >= min_bytes
                        and p in cm.ALLREDUCE_PRECISIONS
                        and cm.precision_ineligibility(
                            p, op, dtype, payload_bytes) is None):
                    return p, "cache"
                return "f32", "cache"
            # the model rung — provably inert by construction (the
            # margin equals int8's 4x byte-ratio bound, and the
            # advantage is strictly below the ratio), kept so the
            # ladder stays uniform and the explain surface can say WHY
            # the model never decides here. topk is not consulted: its
            # 8x byte ratio EXCEEDS the margin, and sparsification
            # drops coordinates outright — it reaches the wire only
            # through a measured crossover or an explicit pin
            for p in ("int8", "bf16"):
                if cm.precision_ineligibility(
                        p, op, dtype, payload_bytes) is not None:
                    continue
                advantage = cm.precision_advantage(
                    payload_bytes, topo, p, link=self.link
                )
                if advantage >= cm.PRECISION_MODEL_MARGIN:
                    return p, "model"
            return "f32", "heuristic"

        return self._memoized(
            ("use_precision", payload_bytes, topo, dtype, op,
             precision, precision_layer, dk),
            compute,
        )

    def _alltoall_structural(self, algorithm: str,
                             topo: cm.TopologySpec) -> bool:
        """Can this shape run the algorithm at all? (Bruck needs a
        power-of-two rank count, the two-tier form a multi-slice pod;
        pairwise runs anywhere.)"""
        if algorithm == "bruck":
            return topo.n >= 1 and not (topo.n & (topo.n - 1))
        if algorithm == "hierarchical":
            return topo.hierarchical_eligible
        return algorithm == "pairwise"

    def use_alltoall(
        self,
        payload_bytes: int,
        topo: cm.TopologySpec,
        dtype: str = "float32",
        algorithm: Optional[str] = None,
        algorithm_layer: str = "env",
    ) -> Tuple[str, str]:
        """Trace-time algorithm gate for ``all_to_all(algorithm=None)``.

        ``algorithm`` given = an explicit override (the
        ``$SMI_TPU_ALLTOALL_ALGO`` env var) — it decides ALONE, and a
        structurally impossible request (Bruck on a non-power-of-two
        ring, hierarchical off-pod) is the CALLER's loud error, never
        a silent fallback. Otherwise: per-bucket cache entry (skipped
        with a fall-through when it names an algorithm this shape
        cannot run — a cache written on one topology must not error a
        trace on another), then the model where its advantage is
        confidently (:data:`ALLTOALL_MODEL_MARGIN`) away from the
        pairwise default, then pairwise — the fused single collective,
        byte-for-byte what an untuned program compiles.
        """
        dk = self.device_kind()

        def compute():
            if algorithm is not None:
                return algorithm, algorithm_layer
            key = PlanKey("all_to_all", payload_bucket(payload_bytes),
                          dtype, dk, _collective_topology(topo))
            hit = self.cache.lookup(key)
            if (hit is not None and "algorithm" in hit.knobs
                    and self._alltoall_structural(
                        str(hit.knobs["algorithm"]), topo)):
                return str(hit.knobs["algorithm"]), "cache"
            if topo.hierarchical_eligible:
                advantage = cm.alltoall_advantage(
                    payload_bytes, topo, link=self.link
                )
                if advantage >= ALLTOALL_MODEL_MARGIN:
                    return "hierarchical", "model"
            if topo.n >= 2 and not (topo.n & (topo.n - 1)):
                # the flat-form comparison also applies ON a pod when
                # the two-tier form did not confidently win: price the
                # flat candidates at the tier that gates their lockstep
                # steps there (DCN — the alltoall_candidates rule)
                flat_link = (cm.dcn_link_model()
                             if topo.hierarchical_eligible
                             else self.link)
                pairwise = cm.pairwise_alltoall_us(
                    payload_bytes, topo.n, flat_link
                )
                bruck = cm.bruck_alltoall_us(
                    payload_bytes, topo.n, flat_link
                )
                if bruck * ALLTOALL_MODEL_MARGIN <= pairwise:
                    return "bruck", "model"
            return "pairwise", "heuristic"

        # exact payload, not the bucket (the use_rs_ag discipline): a
        # bucket-wide memo would be first-call-wins across a model
        # crossover inside one pow2 bucket
        return self._memoized(
            ("use_alltoall", payload_bytes, topo, dtype,
             algorithm, algorithm_layer, dk),
            compute,
        )

    def alltoall_plan(
        self,
        payload_bytes: int,
        topo: cm.TopologySpec,
        dtype: str = "float32",
        device_kind: Optional[str] = None,
    ) -> Plan:
        """Full algorithm plan for an all-to-all — the ``tune
        --explain all_to_all`` entry: all three candidates priced,
        structurally excluded ones named with the reason (no silent
        caps), the deciding layer per knob."""
        dk = normalize_device_kind(device_kind or self.device_kind())
        key = PlanKey("all_to_all", payload_bucket(payload_bytes),
                      dtype, dk, _collective_topology(topo))
        cands = cm.alltoall_candidates(payload_bytes, topo,
                                       link=self.link)
        knobs: Dict[str, object] = {}
        decided: Dict[str, str] = {}
        rationale = []
        hit = self.cache.lookup(key)
        if (hit is not None and "algorithm" in hit.knobs
                and self._alltoall_structural(
                    str(hit.knobs["algorithm"]), topo)):
            layer, why = _cache_hit_rationale(hit)
            knobs["algorithm"] = hit.knobs["algorithm"]
            decided["algorithm"] = layer
            rationale.append(why)
            cands = cm.CandidateSet(
                [Candidate(c.name, c.knobs, c.modeled_us,
                           hit.cost_us if c.knobs.get("algorithm")
                           == hit.knobs["algorithm"] else None, c.note)
                 for c in cands],
                cands.excluded,
            )
        else:
            algo, layer = self.use_alltoall(payload_bytes, topo, dtype)
            knobs["algorithm"] = algo
            decided["algorithm"] = layer
            rationale.append(
                f"alpha-beta ranking (pairwise {topo.n - 1} alphas vs "
                f"Bruck log2(n) aggregate steps; model engages only "
                f"outside the {ALLTOALL_MODEL_MARGIN:g}x confidence "
                f"band — inside it the fused pairwise collective "
                f"compiles byte-identically)"
            )
        for dropped in cands.excluded:
            rationale.append(f"excluded {dropped.name}: {dropped.note}")
        return Plan(key=key, knobs=knobs, decided_by=decided,
                    candidates=list(cands), rationale=rationale)

    def collective_chunks(
        self,
        family: str,
        payload_bytes: int,
        n: int,
        dtype: str = "float32",
        device_kind: Optional[str] = None,
    ) -> Tuple[int, str]:
        """(chunks, layer) for a collective whose caller left
        ``chunks=None``: cache entry, else today's unchunked default.
        (The pipeline model's chunk suggestion is advisory — shown by
        ``--explain``, applied only once a sweep has measured it.)"""
        dk = normalize_device_kind(device_kind or self.device_kind())

        def compute():
            key = PlanKey(family, payload_bucket(payload_bytes), dtype,
                          dk, f"n{n}")
            hit = self.cache.lookup(key)
            if hit is not None and "chunks" in hit.knobs:
                return max(1, int(hit.knobs["chunks"])), "cache"
            return 1, "heuristic"

        return self._memoized(
            ("chunks", family, payload_bucket(payload_bytes), n, dtype,
             dk),
            compute,
        )

    # -- kernels ---------------------------------------------------------
    def flash_blocks(
        self,
        dtype: str,
        windowed: bool,
        device_kind: Optional[str] = None,
    ) -> Optional[Tuple[int, int, str]]:
        """(block_q, block_k, layer) for the flash forward tiles, or
        ``None`` when no cache entry exists — the kernel then keeps its
        measured-constant heuristics (which the seeded v5e entries
        reproduce exactly, so hardware behavior is unchanged until a
        sweep says otherwise)."""
        dk = normalize_device_kind(device_kind or self.device_kind())

        def compute():
            key = PlanKey("flash_fwd", "window" if windowed else "causal",
                          dtype, dk, "chip")
            hit = self.cache.lookup(key)
            if hit is not None and {"block_q", "block_k"} <= set(hit.knobs):
                bq, bk = hit.knobs["block_q"], hit.knobs["block_k"]
                if _valid_flash_block(bq) and _valid_flash_block(bk):
                    return int(bq), int(bk), "cache"
                # value-junk in a schema-valid entry: the kernel's
                # _pick_block would find no divisor and raise at trace
                # time — the heuristics apply instead (broken cache
                # costs tuning, never a trace)
            return None

        return self._memoized(("flash", dtype, windowed, dk), compute)

    def flash_plan(
        self,
        dtype: str = "bfloat16",
        windowed: bool = False,
        s: int = 8192,
        d: int = 128,
        device_kind: Optional[str] = None,
    ) -> Plan:
        """Explain-surface flash plan: cache choice next to the model's
        VMEM-gated candidate ranking and the dtype heuristic."""
        dk = normalize_device_kind(device_kind or self.device_kind())
        key = PlanKey("flash_fwd", "window" if windowed else "causal",
                      dtype, dk, "chip")
        cands = cm.flash_block_candidates(s, d, dtype, windowed)
        picked = self.flash_blocks(dtype, windowed, device_kind=dk)
        from smi_tpu.kernels import flash as _flash

        heur = (_flash._block_q_fwd(dtype),
                _flash._block_k_fwd(dtype, 4096 if windowed else None))
        if picked is not None:
            bq, bk, layer = picked
            rationale = ["measured cache entry; heuristic tier would "
                         f"pick bq{heur[0]}/bk{heur[1]}"]
        else:
            bq, bk = heur
            layer = "heuristic"
            rationale = [
                "no cache entry for this device kind; dtype-keyed "
                "measured constants apply (model ranking shown is "
                "advisory until swept)"
            ]
        # no silent caps: VMEM-rejected targets are named with their
        # failing footprint (tune --explain prints rationale lines), so
        # a shorter candidate table never reads as the full search space
        for dropped in getattr(cands, "excluded", ()):
            rationale.append(f"excluded {dropped.name}: {dropped.note}")
        return Plan(
            key=key,
            knobs={"block_q": bq, "block_k": bk},
            decided_by={"block_q": layer, "block_k": layer},
            candidates=list(cands),
            rationale=rationale,
        )

    def stencil_depth(
        self,
        extent: int = 8192,
        dtype: str = "float32",
        device_kind: Optional[str] = None,
    ) -> Tuple[Optional[int], str]:
        """(depth, layer) for the temporal stencil: seeded/swept cache
        entry, else ``None`` + heuristic (``pick_temporal_depth``)."""
        dk = normalize_device_kind(device_kind or self.device_kind())
        hit = self.cache.lookup(
            PlanKey("stencil_temporal", str(extent), dtype, dk, "chip")
        )
        if hit is not None and "depth" in hit.knobs:
            return int(hit.knobs["depth"]), "cache"
        return None, "heuristic"

    def stencil_pipeline_knobs(
        self,
        extent: int = 8192,
        dtype: str = "float32",
        device_kind: Optional[str] = None,
    ) -> Optional[Tuple[Dict[str, object], str]]:
        """(knobs, layer) for the explicit-DMA stencil pipeline, or
        ``None`` when no cache entry exists — callers then take the
        cost model's best feasible candidate (which the seeded entry
        reproduces, so behavior is unchanged until a sweep disagrees)."""
        dk = normalize_device_kind(device_kind or self.device_kind())

        def compute():
            hit = self.cache.lookup(
                PlanKey("stencil_pipeline", str(extent), dtype, dk,
                        "chip")
            )
            wanted = {"algorithm", "depth", "stripe",
                      "compute_dtype", "buffering"}
            if hit is not None and wanted <= set(hit.knobs):
                return dict(hit.knobs), cache_entry_layer(hit)
            return None

        return self._memoized(("stencil_pipeline", extent, dtype, dk),
                              compute)

    def stencil_pipeline_plan(
        self,
        h: int = 8192,
        w: int = 8192,
        dtype: str = "float32",
        device_kind: Optional[str] = None,
    ) -> Plan:
        """Explain-surface stencil plan: the cached (seeded or swept)
        pipeline knobs next to the model's full depth x stripe x
        compute-dtype ranking, VMEM exclusions named, plus every
        legacy tier's fallback decision (the r18 no-silent-caps fix:
        the ``_pick_*`` pickers now explain a ``None``)."""
        dk = normalize_device_kind(device_kind or self.device_kind())
        key = PlanKey("stencil_pipeline", str(h), dtype, dk, "chip")
        cands = cm.stencil_pipeline_candidates(h, w, dtype)
        picked = self.stencil_pipeline_knobs(h, dtype, device_kind=dk)
        if picked is not None:
            knobs, layer = picked
            hit = self.cache.lookup(key)
            _, line = _cache_hit_rationale(hit)
            rationale = [line]
        elif len(cands):
            best = cands[0]
            knobs, layer = dict(best.knobs), "model"
            rationale = [
                "no cache entry for this device kind; the model's "
                "best-priced feasible candidate applies until swept"
            ]
        else:
            knobs, layer = {"algorithm": "unfused"}, "heuristic"
            rationale = [
                f"no feasible pipeline candidate at {h}x{w} "
                f"dtype={dtype}; the unfused jacobi path applies"
            ]
        for dropped in getattr(cands, "excluded", ()):
            rationale.append(f"excluded {dropped.name}: {dropped.note}")
        # the legacy tiers' picker verdicts: why a shape would (not)
        # fall back, one line each, never a silent None
        from smi_tpu.kernels import stencil as _kstencil
        from smi_tpu.kernels import stencil_pipeline as _kpipe
        from smi_tpu.kernels import stencil_temporal as _ktemporal

        depth = int(knobs.get("depth", 8) or 8)
        for tier, note in (
            ("pipeline", _kpipe.pick_pipeline_stripe_explained(
                h, w, depth)[1]),
            ("temporal", _ktemporal.pick_stripe_explained(
                h, w, depth)[1]),
            ("temporal-tiled", _ktemporal.pick_col_tile_explained(
                w + 2 * _ktemporal.LANE_PAD)[1]),
            ("fused", _kstencil.pick_tile_explained(h, w)[1]),
        ):
            rationale.append(f"{tier} tier: {note}")
        return Plan(
            key=key,
            knobs=knobs,
            decided_by={k: layer for k in knobs},
            candidates=list(cands),
            rationale=rationale,
        )

    # -- explain ---------------------------------------------------------
    def explain_text(
        self,
        op: str,
        n: int = 8,
        dtype: str = "float32",
        sizes_kb: Tuple[int, ...] = (4, 64, 1024, 16384),
        slices: Optional[int] = None,
    ) -> str:
        """The ``smi-tpu tune --explain OP`` payload: candidate tables
        with modeled vs measured costs and the deciding layer per knob.
        Deterministic on CPU — no devices are touched beyond reading
        the local device kind. ``slices >= 2`` models a multi-slice
        pod: the all_reduce table then prices all THREE candidates
        (flat ring / rs+ag / hierarchical) and names the two-tier
        gate's deciding layer."""
        op = op.replace("-", "_")
        if op in ("all_reduce", "allreduce"):
            if slices is not None and slices > 1:
                if n % slices:
                    raise ValueError(
                        f"n={n} ranks do not split into {slices} slices"
                    )
                topo = cm.TopologySpec(n=n, inner=n // slices,
                                       outer=slices)
                where = (f"{slices} slices x {n // slices} "
                         f"ranks (ICI x DCN pod)")
            else:
                topo = cm.TopologySpec(n=n)
                where = f"n={n} ranks"
            parts = [
                f"all_reduce over {where}, dtype={dtype}, device "
                f"kind '{self.device_kind()}'"
            ]
            for kb in sizes_kb:
                parts.append(
                    self.allreduce_plan(kb * 1024, topo, dtype).explain()
                )
            return "\n\n".join(parts)
        if op in ("all_to_all", "alltoall"):
            if slices is not None and slices > 1:
                if n % slices:
                    raise ValueError(
                        f"n={n} ranks do not split into {slices} slices"
                    )
                topo = cm.TopologySpec(n=n, inner=n // slices,
                                       outer=slices)
                where = (f"{slices} slices x {n // slices} "
                         f"ranks (ICI x DCN pod)")
            else:
                topo = cm.TopologySpec(n=n)
                where = f"n={n} ranks"
            parts = [
                f"all_to_all over {where}, dtype={dtype}, device "
                f"kind '{self.device_kind()}'"
            ]
            for kb in sizes_kb:
                parts.append(
                    self.alltoall_plan(kb * 1024, topo, dtype).explain()
                )
            return "\n\n".join(parts)
        if op == "flash_fwd":
            return "\n\n".join(
                self.flash_plan(dtype=dt, windowed=w).explain()
                for dt in ("bfloat16", "float32")
                for w in (False, True)
            )
        if op in ("stencil", "stencil_pipeline"):
            return self.stencil_pipeline_plan(dtype=dtype).explain()
        if op == "stencil_temporal":
            depth, layer = self.stencil_depth()
            via = ("plan cache" if layer == "cache"
                   else "pick_temporal_depth heuristic")
            return (
                f"plan stencil_temporal|8192|float32|"
                f"{self.device_kind()}|chip\n"
                f"  depth = {depth!r}  [{layer}] ({via})"
            )
        if op in ("ring_all_reduce", "ring"):
            chunks, layer = self.collective_chunks(
                "ring_all_reduce", 1 << 20, n, dtype
            )
            return (
                f"plan ring_all_reduce|{payload_bucket(1 << 20)}|{dtype}"
                f"|{self.device_kind()}|n{n}\n"
                f"  chunks = {chunks}  [{layer}]"
            )
        raise ValueError(
            f"unknown op {op!r}; explainable ops: all_reduce, "
            f"all_to_all, flash_fwd, stencil, stencil_temporal, "
            f"ring_all_reduce"
        )


# ---------------------------------------------------------------------------
# Process-global engine + never-erroring trace-time entry points
# ---------------------------------------------------------------------------

_ENGINE: Optional[PlanEngine] = None
_ENGINE_LOCK = threading.Lock()


def _detect_device_kind() -> str:
    try:
        import jax

        return normalize_device_kind(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def _load_default_cache() -> PlanCache:
    """Shipped seeded cache, with the user's cache file (when present)
    merged over it. A malformed user file costs tuning, not a trace:
    it is reported once as a warning and skipped."""
    cache = seeded_cache()
    path = default_cache_path()
    try:
        if path and os.path.exists(path):
            cache.merge(PlanCache.load(path))
    except Exception as e:
        warnings.warn(
            f"ignoring unreadable plan cache at {path!r} "
            f"({type(e).__name__}: {e}); run `smi-tpu tune` to "
            f"regenerate it, or unset ${CACHE_ENV}",
            stacklevel=2,
        )
    return cache


def get_engine() -> PlanEngine:
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            _ENGINE = PlanEngine()
        return _ENGINE


def set_engine(engine: Optional[PlanEngine]) -> None:
    """Install (or with ``None`` reset) the process-global engine —
    the test seam, and how ``smi-tpu tune`` activates a fresh cache."""
    global _ENGINE
    with _ENGINE_LOCK:
        _ENGINE = engine


def planned_flash_blocks(
    dtype: str, windowed: bool
) -> Optional[Tuple[int, int]]:
    """Trace-time flash consult: (bq, bk) from the cache, or ``None``
    (keep the kernel's heuristics). Never raises."""
    try:
        got = get_engine().flash_blocks(dtype, windowed)
        return None if got is None else (got[0], got[1])
    except Exception:
        return None


def planned_stencil_pipeline(
    extent: int = 8192, dtype: str = "float32",
) -> Optional[Dict[str, object]]:
    """Trace-time stencil-pipeline consult: the cached knob dict
    (algorithm/depth/stripe/compute_dtype/buffering), or ``None``
    (callers keep their defaults). Never raises."""
    try:
        got = get_engine().stencil_pipeline_knobs(extent, dtype)
        return None if got is None else dict(got[0])
    except Exception:
        return None


def planned_chunks(
    family: str, payload_bytes: int, n: int, dtype: str
) -> int:
    """Trace-time chunks consult for a ``chunks=None`` caller. Never
    raises; the heuristic answer is 1 (unchunked)."""
    try:
        return get_engine().collective_chunks(
            family, payload_bytes, n, dtype
        )[0]
    except Exception:
        return 1


def planned_hierarchical(
    payload_bytes: int,
    n: int,
    inner: int,
    outer: int,
    dtype: str,
    min_slices: Optional[int] = None,
) -> bool:
    """Trace-time two-tier gate for an eligible ADD allreduce on a
    hybrid multi-slice communicator. ``min_slices`` carries the
    explicit ``$SMI_TPU_HIER_MIN_SLICES`` override. Never raises; the
    fallback is today's flat path (False)."""
    try:
        return get_engine().use_hierarchical(
            payload_bytes,
            cm.TopologySpec(n=n, inner=inner, outer=outer),
            dtype,
            min_slices=min_slices,
        )[0]
    except Exception:
        return False if min_slices is None else outer >= min_slices


def planned_alltoall(
    payload_bytes: int,
    n: int,
    inner: int,
    outer: int,
    dtype: str,
    algorithm: Optional[str] = None,
) -> str:
    """Trace-time all-to-all algorithm consult. ``algorithm`` carries
    the explicit ``$SMI_TPU_ALLTOALL_ALGO`` override. Never raises; the
    fallback is the fused pairwise collective — byte-for-byte what an
    explicit ``algorithm='pairwise'`` call compiles."""
    try:
        return get_engine().use_alltoall(
            payload_bytes,
            cm.TopologySpec(
                n=n,
                inner=inner if outer and outer > 1 else None,
                outer=outer if outer and outer > 1 else None,
            ),
            dtype,
            algorithm=algorithm,
        )[0]
    except Exception:
        return "pairwise" if algorithm is None else algorithm


def planned_precision(
    payload_bytes: int,
    n: int,
    inner: int,
    outer: int,
    dtype: str,
    precision: Optional[str] = None,
) -> str:
    """Trace-time wire-precision consult for an eligible ADD allreduce.
    ``precision`` carries an explicit override (the ``precision=`` pin
    or ``$SMI_TPU_ALLREDUCE_PRECISION``) — it decides ALONE. Never
    raises; the fallback is dense f32, byte-for-byte the untuned
    lowering."""
    try:
        return get_engine().use_precision(
            payload_bytes,
            cm.TopologySpec(
                n=n,
                inner=inner if outer and outer > 1 else None,
                outer=outer if outer and outer > 1 else None,
            ),
            dtype,
            precision=precision,
        )[0]
    except Exception:
        return "f32" if precision is None else precision


def planned_rs_ag(
    payload_bytes: int,
    n: int,
    dtype: str,
    threshold: Optional[int] = None,
) -> bool:
    """Trace-time rs+ag gate for an eligible ADD allreduce. ``threshold``
    carries an explicit env override. Never raises; the fallback is the
    built-in constant comparison."""
    try:
        return get_engine().use_rs_ag(
            payload_bytes, cm.TopologySpec(n=n), dtype,
            threshold=threshold,
        )[0]
    except Exception:
        from smi_tpu.parallel.collectives import RS_AG_MIN_BYTES

        thr = RS_AG_MIN_BYTES if threshold is None else threshold
        return payload_bytes >= thr
