"""Persistent plan cache: versioned JSON, schema-validated, mergeable.

The ATLAS half of the plan engine (PAPERS.md): measured-best configs
survive the process that measured them. One cache file holds entries
for any number of device kinds/topologies (the key carries both), so a
fleet can merge per-host sweeps into one artifact:

- **versioned** — ``schema_version`` is checked on load; a mismatch is
  a loud :class:`PlanCacheError`, never a silent reinterpretation of
  old knobs under new semantics.
- **schema-validated** — every entry must carry a knob dict and a
  well-formed cost; junk entries name themselves on load.
- **mergeable** — :meth:`PlanCache.merge` keeps, per key, the entry
  with the *better measured cost* (lower ``cost_us``); a measured
  entry always beats an unmeasured one, and between two unmeasured
  entries the incoming one wins (newer sweep metadata).

Cost unit is microseconds-per-op (lower is better) — the one scalar
every sweep and the analytic model both speak, so merge order is total.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional

from smi_tpu.tuning.plan import PlanKey

SCHEMA_VERSION = 1

#: Environment variable naming the user's persistent cache file; the
#: engine merges it over the shipped seeded cache at load.
CACHE_ENV = "SMI_TPU_PLAN_CACHE"


class PlanCacheError(ValueError):
    """Malformed or version-mismatched plan-cache payload."""


@dataclasses.dataclass
class CacheEntry:
    """Measured-best knobs for one :class:`PlanKey`."""

    knobs: Dict[str, object]
    cost_us: Optional[float] = None     # lower is better; None = seeded
    provenance: str = ""                # e.g. "sweep:2026-08-03" or
    #                                     "seeded:PERF.json:<metric>" or
    #                                     "live:retune:samples=N:..."
    #: Monotonic staleness counter, bumped on every online swap
    #: install (:meth:`smi_tpu.tuning.swap.PlanSwap.swap`). A higher
    #: revision ALWAYS wins a merge regardless of measured cost: a
    #: late-arriving offline sweep (revision 0, possibly with a
    #: better-looking ``cost_us`` measured under yesterday's traffic)
    #: can no longer silently resurrect a plan the live tuner just
    #: retired. Revision-0 vs revision-0 keeps the original
    #: best-measured-cost merge rules byte-for-byte.
    revision: int = 0

    def better_than(self, other: Optional["CacheEntry"]) -> bool:
        if other is None:
            return True
        if self.revision != other.revision:
            # staleness outranks cost: the live tuner's bumped
            # revision reflects the CURRENT traffic; the older
            # revision's measurement, however good, priced a
            # distribution that no longer exists
            return self.revision > other.revision
        if self.cost_us is None:
            # unmeasured never displaces measured; vs unmeasured the
            # incoming entry wins (merge order: other.merge(self))
            return other.cost_us is None
        if other.cost_us is None:
            return True
        return self.cost_us < other.cost_us

    def to_json(self) -> dict:
        out: dict = {"knobs": dict(self.knobs)}
        if self.cost_us is not None:
            out["cost_us"] = self.cost_us
        if self.provenance:
            out["provenance"] = self.provenance
        if self.revision:
            # absent when 0: pre-revision cache files stay byte-stable
            out["revision"] = self.revision
        return out

    @staticmethod
    def from_json(sig: str, payload: object) -> "CacheEntry":
        if not isinstance(payload, dict) or not isinstance(
            payload.get("knobs"), dict
        ):
            raise PlanCacheError(
                f"plan-cache entry {sig!r} is not "
                f"{{'knobs': {{...}}, ...}}: {payload!r}"
            )
        cost = payload.get("cost_us")
        if cost is not None and not isinstance(cost, (int, float)):
            raise PlanCacheError(
                f"plan-cache entry {sig!r} has non-numeric cost_us "
                f"{cost!r}"
            )
        revision = payload.get("revision", 0)
        if (not isinstance(revision, int) or isinstance(revision, bool)
                or revision < 0):
            raise PlanCacheError(
                f"plan-cache entry {sig!r} has a malformed revision "
                f"{revision!r} (want an integer >= 0)"
            )
        return CacheEntry(
            knobs=dict(payload["knobs"]),
            cost_us=None if cost is None else float(cost),
            provenance=str(payload.get("provenance", "")),
            revision=revision,
        )


@dataclasses.dataclass
class PlanCache:
    entries: Dict[str, CacheEntry] = dataclasses.field(default_factory=dict)

    def lookup(self, key: PlanKey) -> Optional[CacheEntry]:
        return self.entries.get(key.signature())

    def put(self, key: PlanKey, entry: CacheEntry,
            keep_best: bool = True) -> bool:
        """Insert; with ``keep_best`` an existing better-measured entry
        survives. Returns whether ``entry`` landed."""
        sig = key.signature()
        if keep_best and not entry.better_than(self.entries.get(sig)):
            return False
        self.entries[sig] = entry
        return True

    def merge(self, other: "PlanCache") -> "PlanCache":
        """Per-key best-measured union of two caches (see module doc
        for the tie rules). Returns ``self`` for chaining."""
        for sig, entry in other.entries.items():
            if entry.better_than(self.entries.get(sig)):
                self.entries[sig] = entry
        return self

    # -- serialization --------------------------------------------------
    def to_json(self) -> dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "entries": {
                sig: e.to_json() for sig, e in sorted(self.entries.items())
            },
        }

    @staticmethod
    def from_json(payload: object) -> "PlanCache":
        if not isinstance(payload, dict):
            raise PlanCacheError(
                f"plan cache must be a JSON object, got "
                f"{type(payload).__name__}"
            )
        version = payload.get("schema_version")
        if version != SCHEMA_VERSION:
            raise PlanCacheError(
                f"plan-cache schema_version {version!r} does not match "
                f"this build's {SCHEMA_VERSION}; refusing to "
                f"reinterpret tuned knobs across schema changes — "
                f"re-run `smi-tpu tune` to regenerate the cache"
            )
        raw = payload.get("entries", {})
        if not isinstance(raw, dict):
            raise PlanCacheError("plan-cache 'entries' must be an object")
        entries = {}
        for sig, e in raw.items():
            PlanKey.from_signature(sig)   # validates key shape loudly
            entries[sig] = CacheEntry.from_json(sig, e)
        return PlanCache(entries=entries)

    def save(self, path: str) -> str:
        """Write the cache crash-safely: temp file + fsync + atomic
        rename (the checkpoint layer's shared durability idiom), so a
        crash mid-save leaves the previous cache intact — a fleet host
        can never load a half-written entries table as its tuning
        truth."""
        from smi_tpu.parallel.checkpoint import write_atomic

        payload = json.dumps(self.to_json(), indent=2, sort_keys=True)
        write_atomic(path, (payload + "\n").encode())
        return path

    @staticmethod
    def load(path: str) -> "PlanCache":
        with open(path) as f:
            try:
                payload = json.load(f)
            except json.JSONDecodeError as e:
                raise PlanCacheError(
                    f"plan cache {path!r} is not valid JSON: {e}"
                ) from e
        return PlanCache.from_json(payload)


def default_cache_path() -> Optional[str]:
    """The user cache file: $SMI_TPU_PLAN_CACHE when set, else the
    conventional per-user location."""
    env = os.environ.get(CACHE_ENV, "").strip()
    if env:
        return env
    home = os.path.expanduser("~")
    if home and home != "/":
        return os.path.join(home, ".cache", "smi_tpu", "plans.json")
    return None
