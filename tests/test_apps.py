"""Application integration tests on the CPU fake mesh, verified against
serial references — the reference's app-level verification strategy
(``stencil_smi.cpp:33-46,395-407``, ``gesummv_smi.cpp:300-301``)."""

import jax.numpy as jnp
import numpy as np
import pytest

import smi_tpu as smi
from smi_tpu.models import gesummv, kmeans, onchip, stencil
from smi_tpu.parallel.halo import halo_exchange_2d, pad_with_halos


# ---------------------------------------------------------------- halo --


def test_halo_exchange_2d(eight_devices):
    from jax.sharding import PartitionSpec as P
    import jax

    comm = smi.make_communicator(
        shape=(2, 4), axis_names=("hx", "hy"), devices=eight_devices
    )

    @jax.jit
    def run(g):
        def shard_fn(block):
            halos = halo_exchange_2d(block, comm)
            return pad_with_halos(block, halos)

        return jax.shard_map(
            shard_fn, mesh=comm.mesh,
            in_specs=P("hx", "hy"), out_specs=P("hx", "hy"),
            check_vma=False,
        )(g)

    g = jnp.arange(8 * 16, dtype=jnp.float32).reshape(8, 16)
    out = np.asarray(run(g))  # (2*6, 4*6) = padded tiles tiled
    ref = np.asarray(g)

    # examine the tile of rank (1, 2): block rows 4..8, cols 8..12
    tile = out[6:12, 12:18]
    np.testing.assert_array_equal(tile[1:-1, 1:-1], ref[4:8, 8:12])
    np.testing.assert_array_equal(tile[0, 1:-1], ref[3, 8:12])    # top halo
    np.testing.assert_array_equal(tile[1:-1, 0], ref[4:8, 7])     # left halo
    np.testing.assert_array_equal(tile[1:-1, -1], ref[4:8, 12])   # right halo
    np.testing.assert_array_equal(tile[-1, 1:-1], 0)  # bottom edge of mesh

    # edge rank (0, 0): top/left halos are domain boundary -> zeros
    tile00 = out[0:6, 0:6]
    np.testing.assert_array_equal(tile00[0, :], 0)
    np.testing.assert_array_equal(tile00[1:-1, 0], 0)


# -------------------------------------------------------------- stencil --


@pytest.mark.parametrize("px,py,iters", [(2, 4, 5), (2, 2, 3)])
def test_stencil_matches_serial_reference(eight_devices, px, py, iters):
    x, y = 16, 32
    grid = stencil.initial_grid(x, y)
    grid[:, -1] = 2.0  # asymmetric boundary to catch orientation bugs
    out = stencil.run_stencil(
        jnp.asarray(grid), iters, px=px, py=py, devices=eight_devices
    )
    ref = stencil.reference_stencil(grid, iters)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6, atol=1e-6)


def test_stencil_indivisible_grid_rejected(eight_devices):
    with pytest.raises(ValueError, match="divisible"):
        stencil.run_stencil(
            jnp.zeros((10, 16)), 1, px=4, py=2, devices=eight_devices
        )


# -------------------------------------------------------------- gesummv --


@pytest.mark.parametrize("n", [32, 100])
def test_gesummv_matches_reference(eight_devices, n):
    rng = np.random.RandomState(0)
    a = rng.rand(n, n).astype(np.float32)
    b = rng.rand(n, n).astype(np.float32)
    x = rng.rand(n).astype(np.float32)
    out = gesummv.run_gesummv(
        a, b, x, alpha=1.5, beta=0.5, devices=eight_devices
    )
    ref = gesummv.reference_gesummv(a, b, x, alpha=1.5, beta=0.5)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4)


def test_gesummv_wrong_rank_count(eight_devices):
    comm = smi.make_communicator(4, devices=eight_devices)
    with pytest.raises(ValueError, match="2 ranks"):
        gesummv.make_gesummv_fn(comm, 8, 1.0, 1.0)


# --------------------------------------------------------------- kmeans --


def test_kmeans_matches_reference(eight_devices):
    rng = np.random.RandomState(42)
    # three well-separated blobs
    blobs = [
        rng.randn(40, 2) * 0.1 + center
        for center in ([0, 0], [5, 5], [-5, 5])
    ]
    points = np.concatenate(blobs).astype(np.float32)
    rng.shuffle(points)
    points = points[:120]  # divisible by 8
    init = points[:3].copy()

    out = kmeans.run_kmeans(points, init, 10, devices=eight_devices)
    ref = kmeans.reference_kmeans(points, init, 10)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_stencil_onchip_matches_distributed(eight_devices):
    """The single-device baseline and the 8-rank SMI variant agree —
    the reference's onchip-vs-smi comparison (``examples/CMakeLists``)."""
    grid = stencil.initial_grid(16, 32)
    grid[:, -1] = 2.0
    dist = stencil.run_stencil(
        jnp.asarray(grid), 6, px=2, py=4, devices=eight_devices
    )
    base = onchip.run_stencil_onchip(grid, 6)
    np.testing.assert_allclose(
        np.asarray(dist), np.asarray(base), rtol=1e-6, atol=1e-6
    )


def test_gesummv_onchip_matches_distributed(eight_devices):
    rng = np.random.RandomState(7)
    a = rng.rand(64, 64).astype(np.float32)
    b = rng.rand(64, 64).astype(np.float32)
    x = rng.rand(64).astype(np.float32)
    dist = gesummv.run_gesummv(
        a, b, x, alpha=2.0, beta=0.25, devices=eight_devices
    )
    base = onchip.run_gesummv_onchip(a, b, x, alpha=2.0, beta=0.25)
    np.testing.assert_allclose(
        np.asarray(dist), np.asarray(base), rtol=2e-4
    )


def test_onchip_baselines_match_numpy():
    grid = stencil.initial_grid(32, 32)
    out = np.asarray(onchip.run_stencil_onchip(grid, 4))
    ref = stencil.reference_stencil(grid, 4)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)

    rng = np.random.RandomState(1)
    a, b = rng.rand(2, 48, 48).astype(np.float32)
    x = rng.rand(48).astype(np.float32)
    y = np.asarray(onchip.run_gesummv_onchip(a, b, x, alpha=1.5, beta=0.5))
    np.testing.assert_allclose(
        y, gesummv.reference_gesummv(a, b, x, 1.5, 0.5), rtol=2e-4
    )


def test_kmeans_indivisible_points_rejected(eight_devices):
    comm = smi.make_communicator(8, devices=eight_devices)
    with pytest.raises(ValueError, match="divisible"):
        kmeans.run_kmeans(
            np.zeros((13, 2), np.float32), np.zeros((2, 2), np.float32), 1,
            comm=comm,
        )


def test_stencil_ring_backend_matches_xla(eight_devices):
    """The stencil's halo exchange over the explicit neighbour RDMA
    tier (backend="ring" — the reference's four bridge-kernel P2P
    ports, stencil_smi.cl:236-386) produces the same grid as the XLA
    tier on the 2-D mesh."""
    import jax.numpy as jnp

    from smi_tpu.models import stencil

    comm = smi.make_communicator(
        shape=(2, 4), axis_names=("sx", "sy"), devices=eight_devices
    )
    grid = jnp.asarray(stencil.initial_grid(16, 32))
    out_x = np.asarray(stencil.make_stencil_fn(comm, iterations=3)(grid))
    out_r = np.asarray(
        stencil.make_stencil_fn(comm, iterations=3, backend="ring")(grid)
    )
    np.testing.assert_allclose(out_r, out_x, rtol=1e-6, atol=1e-6)
