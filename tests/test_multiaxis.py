"""Multi-axis communicators and concurrent ring streams.

Reference parity: the SMI network addresses ranks globally whatever the
physical topology — the stencil drives P2P ports across a 2-D FPGA grid
(``examples/kernels/stencil_smi.cl:236-386``) and concurrent channels
share the NoC regardless of shape (``microbenchmarks/kernels/
bandwidth_0.cl:14-33``). Here the same holds on TPU meshes:

- Rooted collectives and P2P channels accept a communicator spanning
  SEVERAL mesh axes — the axis tuple is one flattened rank space (the
  ``Communicator.rank`` row-major order) on both backends.
- Ring kernels over a strict SUBSET of the mesh axes resolve remote
  device ids globally (``kernels/ring.py::_logical_id_fn``); passing
  the axis-local index instead cross-signals other rings' devices —
  the interpret tier reported leaked semaphores and then deadlocked
  (a silent data race on hardware) before the fix.
- ``stream_concurrent(backend="ring")`` interleaves the channels'
  bursts at READS_LIMIT granularity with per-port semaphore domains.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

import smi_tpu as smi  # noqa: E402
from smi_tpu.kernels import ring  # noqa: E402
from smi_tpu.parallel.channels import (  # noqa: E402
    P2PChannel,
    stream_concurrent,
)
from smi_tpu.parallel.mesh import Communicator  # noqa: E402

BACKENDS = ["xla", "ring"]


@pytest.fixture(scope="module")
def comm2d(eight_devices):
    return smi.make_communicator(
        shape=(2, 4), axis_names=("mx", "my"), devices=eight_devices
    )


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("root", [0, 5])
def test_rooted_collectives_two_axis(comm2d, backend, root):
    """bcast/reduce address flattened ranks over BOTH mesh axes."""

    @smi.smi_kernel(comm2d, in_specs=P(), out_specs=P(("mx", "my")),
                    backend=backend)
    def app(ctx, x):
        contrib = x + ctx.rank().astype(x.dtype)
        total = ctx.reduce(contrib, op="add", root=root, port=0)
        return ctx.bcast(total, root=root, port=1)[None]

    x = jnp.arange(16, dtype=jnp.float32)
    out = np.asarray(app(x))
    expected = np.arange(16) * 8 + sum(range(8))
    for r in range(8):
        np.testing.assert_allclose(out[r], expected, err_msg=f"rank {r}")


@pytest.mark.parametrize("backend", BACKENDS)
def test_scatter_gather_two_axis(comm2d, backend):
    @smi.smi_kernel(comm2d, in_specs=P(), out_specs=P(("mx", "my")),
                    backend=backend)
    def app(ctx, x):
        mine = ctx.scatter(
            jnp.where(ctx.rank() == 3, x, jnp.zeros_like(x)),
            root=3, port=0,
        )
        return ctx.gather(mine, root=2, port=1, all_ranks=True)[None]

    x = jnp.arange(8 * 16, dtype=jnp.float32)
    out = np.asarray(app(x))
    for r in range(8):
        np.testing.assert_allclose(out[r], np.arange(8 * 16))


@pytest.mark.parametrize("backend", BACKENDS)
def test_p2p_transfer_two_axis(comm2d, backend):
    """src=1 -> dst=6 crosses the mx boundary of the (2, 4) mesh."""

    @smi.smi_kernel(comm2d, in_specs=P(), out_specs=P(("mx", "my")),
                    backend=backend)
    def app(ctx, x):
        ch = ctx.open_channel(port=0, src=1, dst=6, count=x.shape[0],
                              dtype="float")
        payload = x * (ctx.rank() + 1).astype(x.dtype)
        return ctx.transfer(ch, payload)[None]

    x = jnp.arange(32, dtype=jnp.float32)
    out = np.asarray(app(x))
    np.testing.assert_allclose(out[6], np.arange(32) * 2)
    for r in range(8):
        if r != 6:
            np.testing.assert_array_equal(out[r], np.zeros(32))


def test_subset_axis_ring_collective(comm2d):
    """Independent ``my``-rings, one per ``mx`` row: remote device ids
    must resolve to the caller's OWN row. Before the fix this leaked
    credit semaphores across rows and deadlocked."""
    mesh = comm2d.mesh
    sub = Communicator(mesh=mesh, axis_names=("my",))
    mesh_axes = ring.mesh_axes_of(sub)

    def shard(x):
        return ring.ring_all_reduce(
            x[0], "my", 4, interpret=True, mesh_axes=mesh_axes
        )[None]

    f = jax.jit(
        jax.shard_map(
            shard, mesh=mesh, in_specs=P(("mx", "my"), None),
            out_specs=P(("mx", "my"), None), check_vma=False,
        )
    )
    x = jnp.arange(8, dtype=jnp.float32)[:, None] * jnp.ones((1, 128))
    out = np.asarray(f(x))
    # row 0 holds ranks 0-3 (sum 6), row 1 ranks 4-7 (sum 22)
    np.testing.assert_allclose(out[:4, 0], 6.0)
    np.testing.assert_allclose(out[4:, 0], 22.0)


def test_subset_axis_ring_gather_outer_axis(comm2d):
    """Rings over the OUTER axis (mx) with my varying: the non-ring
    coordinate sits in the minor position of the logical id."""
    mesh = comm2d.mesh
    sub = Communicator(mesh=mesh, axis_names=("mx",))
    mesh_axes = ring.mesh_axes_of(sub)

    def shard(x):
        return ring.ring_all_gather(
            x, "mx", 2, interpret=True, mesh_axes=mesh_axes
        )

    f = jax.jit(
        jax.shard_map(
            shard, mesh=mesh, in_specs=P(("mx", "my"), None),
            out_specs=P(("mx", "my"), None), check_vma=False,
        )
    )
    # shard r holds one row of value r; gather over mx pairs r and r+4
    x = jnp.arange(8, dtype=jnp.float32)[:, None] * jnp.ones((1, 128))
    out = np.asarray(f(x))
    # every rank's own gathered copy comes back (out rows [2r, 2r+2)),
    # so the assertion does not depend on which replica a replicated
    # out_spec would keep: rank (mx=a, my=b)'s column-ring holds rows
    # of values b and 4+b, in mx order
    for r in range(8):
        b = r % 4
        np.testing.assert_allclose(out[2 * r, 0], float(b))
        np.testing.assert_allclose(out[2 * r + 1, 0], float(4 + b))


@pytest.mark.parametrize("comm_kind", ["1d", "2d"])
def test_stream_concurrent_ring_matches_xla(eight_devices, comm_kind):
    """The ring tier's burst-interleaved concurrent streams deliver the
    same messages as the XLA tier, with per-port semaphore domains."""
    if comm_kind == "1d":
        comm = smi.make_communicator(8, devices=eight_devices)
        spec = P("smi")
    else:
        comm = smi.make_communicator(
            shape=(2, 4), axis_names=("mx", "my"), devices=eight_devices
        )
        spec = P(("mx", "my"))

    count = 48
    chans = [
        P2PChannel(comm=comm, port=0, src=0, dst=2, count=count,
                   buffer_size=8, consecutive_reads=2),
        P2PChannel(comm=comm, port=1, src=3, dst=1, count=count,
                   buffer_size=8, consecutive_reads=2),
    ]
    x0 = jnp.arange(count, dtype=jnp.float32)
    x1 = jnp.arange(count, dtype=jnp.float32) * 3

    def shard(a, b, backend):
        def payload(data, src):
            return jnp.where(comm.rank() == src, data,
                             jnp.zeros_like(data))
        got = stream_concurrent(
            chans, (payload(a, 0), payload(b, 3)), backend=backend,
        )
        return tuple(o[None] for o in got)

    outs = {}
    for backend in BACKENDS:
        f = jax.jit(
            jax.shard_map(
                partial_shard(shard, backend), mesh=comm.mesh,
                in_specs=(P(), P()),
                out_specs=(spec, spec),
                check_vma=False,
            )
        )
        outs[backend] = tuple(np.asarray(o) for o in f(x0, x1))

    for backend in BACKENDS:
        a, b = outs[backend]
        np.testing.assert_allclose(a[2], np.arange(count),
                                   err_msg=backend)
        np.testing.assert_allclose(b[1], np.arange(count) * 3,
                                   err_msg=backend)
        for r in range(8):
            if r != 2:
                np.testing.assert_array_equal(a[r], 0.0)
            if r != 1:
                np.testing.assert_array_equal(b[r], 0.0)


def partial_shard(shard, backend):
    def inner(a, b):
        return shard(a, b, backend)
    return inner
