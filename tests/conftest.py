"""Test harness: 8 virtual CPU devices = the reference's emulator mode.

The reference tests run 8 MPI ranks against the Intel FPGA CPU emulator
with strict channel depths (``test/CMakeLists.txt:46-50``,
``CMakeLists.txt:188-191``). Here the same tier is JAX's CPU backend with
``--xla_force_host_platform_device_count=8``: every test traces the exact
``shard_map``/collective code path that runs on TPU — no host-loop cheats —
so tests transfer to hardware.

Must run before any ``import jax`` anywhere in the test session.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Force the CPU backend even when the environment points JAX at a TPU
# (tests are the hardware-free tier; bench.py uses the real chip). The env
# var alone is not enough here: site customization may import jax at
# interpreter startup, capturing JAX_PLATFORMS before this file runs, so
# the config is also updated post-import (backends init lazily).
# SMI_TPU_RUN_TPU_TESTS=1 opts into the hardware tier instead
# (tests/test_flash_tpu.py): the TPU platform stays visible and the
# compiled Mosaic paths run on the real chip. "0"/"false"/"no"/"" all
# mean off, so CI matrices can set the variable explicitly either way.
def _opted_in(var: str) -> bool:
    return os.environ.get(var, "").strip().lower() not in (
        "", "0", "false", "no"
    )


_tpu_tier = _opted_in("SMI_TPU_RUN_TPU_TESTS")
# The AOT tier (tests/test_aot_tpu.py) compiles the multi-chip surface
# for a real TPU topology from this (possibly CPU-only) host; like the
# hardware tier it is run as its own pytest invocation.
_aot_tier = _opted_in("SMI_TPU_RUN_AOT_TESTS")
if not _tpu_tier:
    os.environ["JAX_PLATFORMS"] = "cpu"
if not _tpu_tier and not _aot_tier:
    # emulator tier: AOT topology lookups must fail FAST. With libtpu
    # installed but no TPU attached, the topology client can spin for
    # minutes holding the GIL, which stalls the whole suite — the
    # aot-touching tests expect a quick raise and skip themselves
    # (see smi_tpu.parallel.aot.topology_devices).
    os.environ.setdefault("SMI_TPU_DISABLE_AOT_TOPOLOGY", "1")

import jax  # noqa: E402

if not _tpu_tier:
    jax.config.update("jax_platforms", "cpu")

# The SMI surface includes a 'double' dtype (include/smi/data_types.h);
# emulator-tier tests exercise it with real float64. The TPU-targeting
# tiers (hardware and AOT) keep the default 32-bit mode — the hardware
# has no f64, x64-widened literals break tracing of the compiled
# kernels, and Mosaic's lowering of stray int64 converts recurses
# without bound (jax 0.9 _convert_element_type_lowering_rule).
if not _tpu_tier and not _aot_tier:
    jax.config.update("jax_enable_x64", True)

import faulthandler  # noqa: E402
import sys  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402

#: Per-test deadlock watchdog, the reference's ``ASSERT_DURATION_LE``
#: (``test/p2p/test_p2p.cpp:30-42``): a detached watchdog turns a hung
#: collective into a visible failure instead of a silent CI stall. A hang
#: inside XLA C++ can't be interrupted from Python, so like the
#: reference's detached-thread assert the watchdog *aborts the process* —
#: after naming the hung test and dumping all thread stacks.
WATCHDOG_SECS = int(os.environ.get("SMI_TEST_TIMEOUT", "300"))


@pytest.fixture(autouse=True)
def deadlock_watchdog(request):
    def abort():
        sys.stderr.write(
            f"\n[watchdog] {request.node.nodeid} exceeded "
            f"{WATCHDOG_SECS}s — aborting (suspected deadlock)\n"
        )
        faulthandler.dump_traceback(file=sys.stderr)
        os._exit(70)

    timer = threading.Timer(WATCHDOG_SECS, abort)
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()


@pytest.fixture(scope="session")
def eight_devices():
    devices = jax.devices()
    assert len(devices) >= 8, (
        "emulator tier needs 8 virtual devices; got "
        f"{len(devices)} — was jax imported before conftest set XLA_FLAGS?"
    )
    return devices[:8]


@pytest.fixture(scope="session")
def comm8(eight_devices):
    import smi_tpu as smi

    return smi.make_communicator(8, devices=eight_devices)


@pytest.fixture(scope="session")
def comm2(eight_devices):
    import smi_tpu as smi

    return smi.make_communicator(2, devices=eight_devices)
