"""MPMD-under-SPMD: per-rank program divergence.

Reference: the routing file's program map lets different ranks run
different bitstreams — sender/receiver in the bandwidth benchmark
(``microbenchmarks/kernels/bandwidth_0.cl``/``bandwidth_1.cl``,
``bandwidth.json:2-11``) and the two GESUMMV ranks. Here the same
capability is ``combined_program`` (one validated union program for the
SPMD trace) plus ``ctx.select`` (``lax.switch`` on the axis index for
communication-free local divergence).
"""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import smi_tpu as smi
from smi_tpu.ops.program import PortConflict, combined_program


def _mapping(programs, n=2):
    devices = [smi.Device("node", i) for i in range(n)]
    return smi.ProgramMapping(
        programs=list(programs),
        device_to_program={
            d: programs[i % len(programs)] for i, d in enumerate(devices)
        },
    )


def test_combined_program_complementary_endpoints():
    sender = smi.Program([smi.Push(0, "float", 256)])
    receiver = smi.Program([smi.Pop(0, "float", 256)])
    union = combined_program(_mapping([sender, receiver]))
    kinds = sorted((op.NAME, op.port) for op in union.operations)
    assert kinds == [("pop", 0), ("push", 0)]


def test_combined_program_dedupes_spmd():
    prog = smi.Program([smi.Push(1, "int"), smi.Pop(1, "int")])
    union = combined_program(_mapping([prog, prog]))
    assert len(union.operations) == 2


def test_combined_program_conflict_rejected():
    a = smi.Program([smi.Broadcast(2, "float")])
    b = smi.Program([smi.Reduce(2, "float", op="add")])
    with pytest.raises(PortConflict):
        combined_program(_mapping([a, b]))


def test_combined_program_reduce_op_conflict_rejected():
    """Reduce ops differing only in the operator must not silently merge."""
    a = smi.Program([smi.Reduce(3, "float", op="add")])
    b = smi.Program([smi.Reduce(3, "float", op="max")])
    with pytest.raises(PortConflict):
        combined_program(_mapping([a, b]))


def test_combined_program_rendezvous_must_agree():
    a = smi.Program([smi.Push(0, "int")], p2p_rendezvous=True)
    b = smi.Program([smi.Pop(0, "int")], p2p_rendezvous=False)
    with pytest.raises(ValueError, match="p2p_rendezvous"):
        combined_program(_mapping([a, b]))


def test_mpmd_bandwidth_pattern(comm8):
    """Sender/receiver divergence: rank 0 builds the payload, rank 1
    verifies, everyone else idles — one SPMD program."""
    n = 64
    sender = smi.Program([smi.Push(0, "float", 128)])
    receiver = smi.Program([smi.Pop(0, "float", 128)])
    union = combined_program(_mapping([sender, receiver], n=8))

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"), program=union)
    def app(ctx, x):
        # local divergence: only the sender scales its payload
        payload = ctx.select(
            [lambda v: v * 3.0, lambda v: jnp.zeros_like(v)], x
        )
        # shared communication structure: every rank runs the transfer
        ch = ctx.open_channel(port=0, src=0, dst=1, count=n, dtype="float")
        received = ctx.transfer(ch, payload)
        # receiver-side verification mark (bandwidth_1.cl's check)
        expected = 3.0 * jnp.arange(n, dtype=jnp.float32)
        ok = ctx.select(
            [
                lambda v: jnp.zeros((), jnp.float32),
                lambda v: jnp.where(
                    jnp.all(v == expected),
                    jnp.float32(1.0),
                    jnp.float32(-1.0),
                ),
            ],
            received,
        )
        return jnp.concatenate([received, ok[None]])[None]

    x = jnp.arange(n, dtype=jnp.float32)
    out = np.asarray(app(x))
    np.testing.assert_array_equal(out[1][:n], 3.0 * np.asarray(x))
    assert out[1][n] == 1.0  # receiver verified
    assert out[0][n] == 0.0  # sender branch


def test_mpmd_select_clips_extra_ranks(comm8):
    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def app(ctx, x):
        return ctx.select([lambda v: v + 1, lambda v: v * 10], x)[None]

    out = np.asarray(app(jnp.ones(4, jnp.float32)))
    np.testing.assert_array_equal(out[0], 2.0)
    for r in range(1, 8):  # ranks >= len(branches) take the last branch
        np.testing.assert_array_equal(out[r], 10.0)
