"""Static protocol verifier: happens-before analysis of the credits zoo.

Reference parity: the SMI toolchain verifies programs *at compile time*
— codegen derives routing tables and channel descriptors and rejects
ill-formed programs before anything runs. The TPU port's protocol layer
(:mod:`smi_tpu.parallel.credits`) has so far been verified dynamically:
``explore_all_schedules`` walks interleavings, but the composite and pod
schedule spaces are beyond exhaustive reach (PR 6 capped them with
``allow_budget=``). This module closes that gap with a *static* pass
that proves the invariants for the WHOLE schedule space in polynomial
time, in the tradition of Lamport's happens-before relation (CACM'78)
and Eraser-style race detection (Savage et al., SOSP'97 — lockset /
vector-clock checking; PAPERS.md).

Why a single symbolic replay is enough
--------------------------------------
Every registered protocol obeys the one-yield-per-primitive discipline:
a rank's generator emits a *schedule-independent* primitive sequence —
control flow never branches on a payload, so replaying each generator
once (feeding a symbolic token to every ``read_slot``) recovers the
complete per-rank event alphabet. The verifier double-traces each rank
and insists the two sequences are identical, so the assumption is
checked, not trusted.

On those fixed sequences the system is a monotone counting-semaphore
program: signals only ever *add* permission, each semaphore domain
``(rank, sem, index)`` has exactly one consumer (the owning rank, which
waits in program order), and DMA landings affect data, never progress.
Such systems are **confluent** (Keller's persistence/diamond argument):
whether the program terminates — and how many units each domain ends
with — is the same under every schedule. One canonical replay therefore
decides deadlock-freedom and credit balance for the whole space.

What each check proves (see ``docs/analysis.md`` for the fine print):

1. **deadlock** — the canonical replay either completes (no schedule
   can deadlock) or blocks; on a block the cross-rank wait-for relation
   is analysed and the finding names the minimal cycle — or the starved
   wait no remaining signal can ever satisfy — as
   ``(rank, step, primitive)`` events.
2. **slot-race** — a static happens-before graph is built from the
   matched signal/wait pairs (fixpoint-refined, see
   :func:`_happens_before`) and every pair of accesses to one comm slot
   (DMA landings, local writes, reads) must be HB-ordered; an unordered
   write/write or write/read pair is a race, verified on the reachability
   closure (the vector-clock formulation with one component per event)
   and reported with both events named.
3. **credit-conservation** — per semaphore domain, total signalled
   units must equal total consumed units: a surplus is a leak (the
   count Pallas would report non-zero at exit), a deficit is a wait
   that must starve.
4. **wire-lane** — per (src, dst) destination lane — and per-rank local
   lane — consumption order must equal send order with strictly
   increasing sequence numbers (re-reads of the last frame allowed),
   statically proving the PR 2/PR 6 verified-transport framing
   invariant for race-free protocols.

Scope: the static guarantee is **fault-free only** — it quantifies over
schedules, not over dropped grants, dead links, or in-flight damage.
Faults remain the chaos campaign's job (:mod:`smi_tpu.parallel.faults`);
the two tiers are cross-validated by ``tests/test_analysis.py``'s
differential harness (every space the dynamic fuzzer can exhaust must
agree with the verifier, on clean protocols and on the broken mutants of
:mod:`smi_tpu.analysis.mutants` alike).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from smi_tpu.parallel import credits as C

#: The checks the verifier runs, in order. ``docs/analysis.md`` must
#: document every one of them (drift-guarded by tests/test_perf_docs).
CHECKS = ("deadlock", "slot-race", "credit-conservation", "wire-lane")

#: Largest ring the ``route --check --lint`` tier verifies per protocol:
#: the protocols are size-generic, so a representative instance stands
#: for the topology (the graph grows ~n^2 events; n=8 stays instant).
MAX_LINT_N = 8


class AnalysisError(ValueError):
    """The verifier's own preconditions failed (nondeterministic rank
    sequence, malformed primitive) — a bug in the *input*, distinct
    from a protocol finding."""


# ---------------------------------------------------------------------------
# Symbolic replay: recover each rank's schedule-independent sequence
# ---------------------------------------------------------------------------


class _Sym:
    """Placeholder payload fed to every ``read_slot``: absorbs the
    union-combines the registered protocols apply to arrived values, so
    the trace never depends on real data.

    Any OBSERVATION of the payload — equality, ordering, truth-testing,
    hashing — raises :class:`AnalysisError`: a generator that branches
    on what arrived is not schedule-independent, and silently taking
    the same (arbitrary) branch in both replays would let the
    double-trace mis-verify it instead of rejecting it."""

    __slots__ = ()

    def __or__(self, other):
        return self

    def __ror__(self, other):
        return self

    def __repr__(self):
        return "<sym>"

    def _observed(self, *_args):
        raise AnalysisError(
            "protocol control flow depends on a read payload (the "
            "symbolic token was compared/tested/hashed): the sequence "
            "is not schedule-independent and no static claim is "
            "possible"
        )

    __eq__ = __ne__ = __lt__ = __le__ = __gt__ = __ge__ = _observed
    __bool__ = __hash__ = _observed


SYM = _Sym()


def symbolic_events(gen: Iterator) -> List[tuple]:
    """Drive one rank's protocol generator to completion, feeding the
    symbolic token to every ``read_slot`` — the single replay that
    recovers the rank's full primitive sequence."""
    events: List[tuple] = []
    value = None
    while True:
        try:
            action = gen.send(value)
        except StopIteration:
            return events
        if not isinstance(action, tuple) or not action:
            raise AnalysisError(f"malformed primitive {action!r}")
        events.append(action)
        value = SYM if action[0] == "read_slot" else None


def _describe(action: tuple) -> tuple:
    """Normalize a primitive for reporting: payloads elided (they are
    symbolic anyway), structure kept."""
    kind = action[0]
    if kind == "dma":
        _, target, slot, _payload, send_index, recv_index = action
        return ("dma", target, slot, send_index, recv_index)
    if kind == "write_slot":
        return ("write_slot", action[1])
    if kind == "output":
        return ("output", action[1])
    return action


@dataclasses.dataclass(frozen=True)
class VerifyEvent:
    """One (rank, step, primitive) coordinate in a finding — ``step``
    indexes the rank's recovered primitive sequence."""

    rank: int
    step: int
    primitive: tuple

    def __str__(self) -> str:
        return f"(rank {self.rank}, step {self.step}, {self.primitive})"

    def to_json(self) -> dict:
        return {"rank": self.rank, "step": self.step,
                "primitive": list(map(str, self.primitive))}


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    """One verified defect. ``events`` carries the (rank, step,
    primitive) coordinates the message names; the structured fields let
    the differential harness compare against the dynamic fuzzer's named
    errors without string parsing."""

    CHECK = "?"

    message: str
    events: Tuple[VerifyEvent, ...] = ()
    rank: Optional[int] = None
    slot: Optional[int] = None
    domain: Optional[tuple] = None
    expected: Optional[object] = None
    got: Optional[object] = None

    @property
    def check(self) -> str:
        return type(self).CHECK

    def to_json(self) -> dict:
        out = {
            "check": self.check,
            "message": self.message,
            "events": [e.to_json() for e in self.events],
        }
        for key in ("rank", "slot"):
            if getattr(self, key) is not None:
                out[key] = getattr(self, key)
        if self.domain is not None:
            out["domain"] = list(map(str, self.domain))
        if self.expected is not None:
            out["expected"] = str(self.expected)
        if self.got is not None:
            out["got"] = str(self.got)
        return out

    def __str__(self) -> str:
        lines = [f"[{self.check}] {self.message}"]
        lines.extend(f"    at {e}" for e in self.events)
        return "\n".join(lines)


class StaticDeadlock(Finding):
    """A wait-for cycle — or a starved wait — proving some (hence, by
    confluence, every) schedule cannot complete."""

    CHECK = "deadlock"


class SlotRace(Finding):
    """Two accesses to one comm slot with no happens-before edge — the
    clobber the credit protocol exists to prevent."""

    CHECK = "slot-race"


class CreditConservation(Finding):
    """A semaphore domain whose signalled and consumed totals differ —
    surplus units leak (poisoning the next collective on the
    semaphore), missing units starve a wait."""

    CHECK = "credit-conservation"


class WireLaneViolation(Finding):
    """A destination consumed frames out of send order on one sequence
    lane — the framing invariant (`credits.verified_steps`) would raise
    ``IntegrityError(kind="sequence")`` at runtime."""

    CHECK = "wire-lane"


@dataclasses.dataclass(frozen=True)
class StaticReport:
    """Verdict of one protocol instance. ``checks`` lists the checks
    that actually ran (a deadlock stops the HB-dependent checks; slot
    races invalidate the wire-lane claim — see docs/analysis.md)."""

    protocol: str
    shape: Dict[str, int]
    ranks: int
    events: int
    findings: Tuple[Finding, ...]
    checks: Tuple[str, ...] = CHECKS

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {
            "protocol": self.protocol,
            "shape": dict(self.shape),
            "ranks": self.ranks,
            "events": self.events,
            "ok": self.ok,
            "checks": list(self.checks),
            "findings": [f.to_json() for f in self.findings],
        }

    def describe(self) -> str:
        shape = ", ".join(f"{k}={v}" for k, v in sorted(self.shape.items()))
        head = f"{self.protocol} [{shape}]"
        if self.ok:
            return (f"{head}: ok ({self.events} events, "
                    f"checks: {', '.join(self.checks)})")
        body = "\n".join(f"  {line}" for f in self.findings
                         for line in str(f).splitlines())
        return f"{head}: {len(self.findings)} finding(s)\n{body}"


# ---------------------------------------------------------------------------
# Event graph
# ---------------------------------------------------------------------------


class _Graph:
    """Static event graph of one protocol instance.

    Nodes are (a) every rank primitive, in program order, and (b) one
    *landing* node per DMA (the copy arriving at the target — ordered
    after its start, unordered with anything else until semaphore
    matching adds edges). Semaphore bookkeeping is per *domain*
    ``(owner_rank, sem_name, index)``: producers are signal events and
    DMA send/landing side-effects; consumers are the owner's waits in
    program order.
    """

    def __init__(self, seqs: Sequence[Sequence[tuple]]):
        self.seqs = [list(s) for s in seqs]
        self.n_ranks = len(self.seqs)
        self.offsets: List[int] = []
        total = 0
        for s in self.seqs:
            self.offsets.append(total)
            total += len(s)
        self.n_rank_nodes = total
        #: landing node per dma node id
        self.land_of: Dict[int, int] = {}
        #: landing node id -> its dma node id
        self.dma_of_land: Dict[int, int] = {}
        #: node id -> (rank, step) for rank nodes
        self.preds: List[List[int]] = [[] for _ in range(total)]
        #: domain -> [(node, amount)] in no particular cross-producer order
        self.producers: Dict[tuple, List[Tuple[int, int]]] = {}
        #: domain -> [(node, amount)] in the owner's program order
        self.waits: Dict[tuple, List[Tuple[int, int]]] = {}
        #: per (rank, slot): [(node, "read"|"write")] — landings included
        self.accesses: Dict[Tuple[int, int], List[Tuple[int, str]]] = {}
        #: dma node -> (src, dst, per-destination wire sequence number)
        self.lane_of: Dict[int, Tuple[int, int, int]] = {}
        #: local write_slot node -> (rank, local sequence number)
        self.local_lane_of: Dict[int, Tuple[int, int]] = {}

        wire_seqs: Dict[Tuple[int, int], int] = {}
        for r, seq in enumerate(self.seqs):
            local_seq = 0
            for i, action in enumerate(seq):
                nid = self.nid(r, i)
                if i:
                    self.preds[nid].append(nid - 1)
                kind = action[0]
                if kind == "signal":
                    _, target, name, index, inc = action
                    self._produce((target, name, index), nid, inc)
                elif kind == "wait":
                    _, name, index, amount = action
                    self.waits.setdefault((r, name, index), []).append(
                        (nid, amount)
                    )
                elif kind == "dma":
                    _, target, slot, _p, send_index, recv_index = action
                    land = len(self.preds)
                    self.preds.append([nid])
                    self.land_of[nid] = land
                    self.dma_of_land[land] = nid
                    seq_no = wire_seqs.get((r, target), 0)
                    wire_seqs[(r, target)] = seq_no + 1
                    self.lane_of[nid] = (r, target, seq_no)
                    self._produce((r, C.SEM_SEND, send_index), nid, 1)
                    self._produce((target, C.SEM_RECV, recv_index), land, 1)
                    self.accesses.setdefault((target, slot), []).append(
                        (land, "write")
                    )
                elif kind == "write_slot":
                    _, slot, _p = action
                    self.local_lane_of[nid] = (r, local_seq)
                    local_seq += 1
                    self.accesses.setdefault((r, slot), []).append(
                        (nid, "write")
                    )
                elif kind == "read_slot":
                    _, slot = action
                    self.accesses.setdefault((r, slot), []).append(
                        (nid, "read")
                    )
                elif kind != "output":
                    raise AnalysisError(f"unknown primitive {action!r}")

    def nid(self, rank: int, step: int) -> int:
        return self.offsets[rank] + step

    def _produce(self, domain: tuple, nid: int, amount: int) -> None:
        self.producers.setdefault(domain, []).append((nid, amount))

    def event(self, nid: int) -> VerifyEvent:
        """The reporting coordinate of a node; landings report as the
        originating dma with a ``dma-land`` primitive."""
        if nid in self.dma_of_land:
            dma = self.dma_of_land[nid]
            rank, step = self.rank_step(dma)
            action = self.seqs[rank][step]
            return VerifyEvent(rank, step, (
                "dma-land", action[1], action[2], action[5]
            ))
        rank, step = self.rank_step(nid)
        return VerifyEvent(rank, step, _describe(self.seqs[rank][step]))

    def rank_step(self, nid: int) -> Tuple[int, int]:
        lo, hi = 0, self.n_ranks - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self.offsets[mid] <= nid:
                lo = mid
            else:
                hi = mid - 1
        return lo, nid - self.offsets[lo]


# ---------------------------------------------------------------------------
# Check 3: credit conservation (pure counting over the sequences)
# ---------------------------------------------------------------------------


def _check_credit_conservation(g: _Graph) -> List[Finding]:
    findings: List[Finding] = []
    domains = sorted(set(g.producers) | set(g.waits), key=repr)
    for domain in domains:
        produced = sum(a for _, a in g.producers.get(domain, ()))
        consumed = sum(a for _, a in g.waits.get(domain, ()))
        if produced == consumed:
            continue
        if produced > consumed:
            # name the tail producers whose units can never drain
            surplus = produced - consumed
            tail: List[VerifyEvent] = []
            acc = 0
            for nid, amount in reversed(g.producers.get(domain, ())):
                tail.append(g.event(nid))
                acc += amount
                if acc >= surplus:
                    break
            findings.append(CreditConservation(
                message=(
                    f"semaphore domain {domain} leaks {surplus} unit(s): "
                    f"{produced} signalled but only {consumed} consumed — "
                    f"the count stays non-zero at exit and poisons the "
                    f"next collective on this semaphore"
                ),
                events=tuple(reversed(tail)),
                rank=domain[0], domain=domain,
                expected=consumed, got=produced,
            ))
        else:
            deficit = consumed - produced
            waiters = tuple(
                g.event(nid) for nid, _ in g.waits.get(domain, ())
            )[-1:]
            findings.append(CreditConservation(
                message=(
                    f"semaphore domain {domain} is short {deficit} "
                    f"unit(s): {consumed} consumed by waits but only "
                    f"{produced} ever signalled — the final wait must "
                    f"starve under every schedule"
                ),
                events=waiters,
                rank=domain[0], domain=domain,
                expected=consumed, got=produced,
            ))
    return findings


# ---------------------------------------------------------------------------
# Canonical replay: deadlock freedom + the read/write observation map
# ---------------------------------------------------------------------------


def _future_producers(g: _Graph, pcs: List[int], domain: tuple) -> List[int]:
    """Ranks whose *remaining* sequence still produces units on
    ``domain`` (signals, or DMAs whose send/landing side-effects land
    there)."""
    out = []
    for p in range(g.n_ranks):
        for action in g.seqs[p][pcs[p]:]:
            kind = action[0]
            if kind == "signal" and (action[1], action[2],
                                     action[3]) == domain:
                out.append(p)
                break
            if kind == "dma":
                _, target, _slot, _pl, send_index, recv_index = action
                if ((p, C.SEM_SEND, send_index) == domain
                        or (target, C.SEM_RECV, recv_index) == domain):
                    out.append(p)
                    break
    return out


def _shortest_cycle(edges: Dict[int, set]) -> Optional[List[int]]:
    """Shortest directed cycle in a tiny digraph (BFS from each node)."""
    best: Optional[List[int]] = None
    for start in edges:
        # BFS back to start
        parent = {start: None}
        frontier = [start]
        found = None
        while frontier and found is None:
            nxt = []
            for v in frontier:
                for w in edges.get(v, ()):
                    if w == start:
                        found = v
                        break
                    if w not in parent:
                        parent[w] = v
                        nxt.append(w)
                if found is not None:
                    break
            frontier = nxt
        if found is None:
            continue
        cycle = [found]
        while parent[cycle[-1]] is not None:
            cycle.append(parent[cycle[-1]])
        cycle.reverse()
        if best is None or len(cycle) < len(best):
            best = cycle
    return best


@dataclasses.dataclass
class _Replay:
    """Result of the canonical eager execution."""

    completed: bool
    findings: List[Finding]
    #: read node -> writer node it observed (None: unwritten slot)
    observed: Dict[int, Optional[int]]


def _replay(g: _Graph) -> _Replay:
    """Run the canonical schedule: every rank advances as far as it
    can, DMAs land immediately. By confluence (module docstring) the
    outcome — completion vs deadlock, and final semaphore counts —
    holds for every schedule."""
    pcs = [0] * g.n_ranks
    sems: Dict[tuple, int] = {}
    slots: Dict[Tuple[int, int], Optional[int]] = {}
    observed: Dict[int, Optional[int]] = {}
    findings: List[Finding] = []

    progress = True
    while progress:
        progress = False
        for r in range(g.n_ranks):
            while pcs[r] < len(g.seqs[r]):
                action = g.seqs[r][pcs[r]]
                kind = action[0]
                nid = g.nid(r, pcs[r])
                if kind == "wait":
                    _, name, index, amount = action
                    key = (r, name, index)
                    if sems.get(key, 0) < amount:
                        break
                    sems[key] = sems.get(key, 0) - amount
                elif kind == "signal":
                    _, target, name, index, inc = action
                    key = (target, name, index)
                    sems[key] = sems.get(key, 0) + inc
                elif kind == "dma":
                    _, target, slot, _p, send_index, recv_index = action
                    sems[(r, C.SEM_SEND, send_index)] = (
                        sems.get((r, C.SEM_SEND, send_index), 0) + 1
                    )
                    # land immediately: landings only add permission,
                    # so the eager landing is progress-equivalent
                    slots[(target, slot)] = g.land_of[nid]
                    sems[(target, C.SEM_RECV, recv_index)] = (
                        sems.get((target, C.SEM_RECV, recv_index), 0) + 1
                    )
                elif kind == "write_slot":
                    _, slot, _p = action
                    slots[(r, slot)] = nid
                elif kind == "read_slot":
                    _, slot = action
                    observed[nid] = slots.get((r, slot))
                pcs[r] += 1
                progress = True

    if all(pcs[r] >= len(g.seqs[r]) for r in range(g.n_ranks)):
        # reads of slots no sequence ever writes are broken regardless
        # of schedule; reads whose writer merely raced are the slot-race
        # check's business (the write exists, ordering is the question)
        for nid, writer in observed.items():
            if writer is None:
                rank, step = g.rank_step(nid)
                slot = g.seqs[rank][step][1]
                if not any(
                    kind == "write"
                    for _, kind in g.accesses.get((rank, slot), ())
                ):
                    findings.append(SlotRace(
                        message=(
                            f"rank {rank} reads slot {slot} which no "
                            f"rank's sequence ever writes"
                        ),
                        events=(g.event(nid),), rank=rank, slot=slot,
                    ))
        return _Replay(True, findings, observed)

    # blocked: analyse the cross-rank wait-for relation
    blocked: Dict[int, Tuple[int, tuple, tuple]] = {}
    for r in range(g.n_ranks):
        if pcs[r] >= len(g.seqs[r]):
            continue
        action = g.seqs[r][pcs[r]]
        # only waits can block the eager replay
        _, name, index, amount = action
        blocked[r] = (g.nid(r, pcs[r]), (r, name, index), action)

    waitfor: Dict[int, set] = {}
    starved: List[int] = []
    for r, (nid, domain, _a) in blocked.items():
        producers = [p for p in _future_producers(g, pcs, domain)
                     if p != r]
        if not producers:
            starved.append(r)
        waitfor[r] = set(producers)

    if starved:
        s = starved[0]
        nid, domain, action = blocked[s]
        chain = [g.event(nid)]
        chain += [g.event(blocked[r][0]) for r in sorted(blocked)
                  if r != s]
        findings.append(StaticDeadlock(
            message=(
                f"rank {s} waits on semaphore domain {domain} but no "
                f"remaining signal in any rank's sequence can satisfy "
                f"it — every schedule deadlocks with "
                f"{len(blocked)} rank(s) blocked"
            ),
            events=tuple(chain), rank=s, domain=domain,
        ))
    else:
        cycle = _shortest_cycle(waitfor)
        if cycle is None:  # pragma: no cover — see docs: impossible at
            cycle = sorted(blocked)  # a blocked fixpoint w/o starvation
        findings.append(StaticDeadlock(
            message=(
                "cross-rank wait-for cycle: "
                + " -> ".join(
                    f"rank {r} at {_describe(blocked[r][2])}"
                    for r in cycle
                )
                + f" -> rank {cycle[0]} — no schedule can complete"
            ),
            events=tuple(g.event(blocked[r][0]) for r in cycle),
            rank=cycle[0], domain=blocked[cycle[0]][1],
        ))
    return _Replay(False, findings, observed)


# ---------------------------------------------------------------------------
# Happens-before graph (fixpoint) + slot races
# ---------------------------------------------------------------------------


def _ancestor_sets(n_nodes: int, preds: Sequence[Sequence[int]],
                   extra: Dict[int, set]) -> Optional[List[int]]:
    """Strict-ancestor bitmask per node (int bitsets — the vector-clock
    closure with one binary component per event). None on a cycle."""
    succs: List[List[int]] = [[] for _ in range(n_nodes)]
    indeg = [0] * n_nodes
    for v in range(n_nodes):
        ps = list(preds[v]) + list(extra.get(v, ()))
        indeg[v] = len(ps)
        for p in ps:
            succs[p].append(v)
    order = [v for v in range(n_nodes) if indeg[v] == 0]
    anc = [0] * n_nodes
    done = 0
    while order:
        v = order.pop()
        done += 1
        mask = anc[v] | (1 << v)
        for s in succs[v]:
            anc[s] |= mask
            indeg[s] -= 1
            if indeg[s] == 0:
                order.append(s)
    return anc if done == n_nodes else None


def _happens_before(g: _Graph) -> Optional[List[int]]:
    """The static happens-before closure.

    Base edges: program order and dma-start -> landing. Signal/wait
    matching is refined to a fixpoint: a wait whose cumulative demand is
    ``c`` happens-after exactly those increments without which the
    domain's *causally available* units fall below ``c`` (increments the
    wait itself precedes are not available to it — that exclusion is
    what the fixpoint iterates on). For the zoo's domains — single
    producer per credit/recv/send lane, the symmetric two-producer
    barrier — this matching is exact, not just sound; see
    docs/analysis.md for the precision statement.
    """
    n_nodes = len(g.preds)
    extra: Dict[int, set] = {}
    for _ in range(n_nodes + 1):
        anc = _ancestor_sets(n_nodes, g.preds, extra)
        if anc is None:
            return None  # HB cycle: inconsistent protocol
        changed = False
        for domain, waits in g.waits.items():
            producers = g.producers.get(domain, ())
            cumulative = 0
            for wnid, amount in waits:
                cumulative += amount
                candidates = [
                    (pid, a) for pid, a in producers
                    if not (anc[pid] >> wnid) & 1
                ]
                total = sum(a for _, a in candidates)
                for pid, a in candidates:
                    if total - a < cumulative:
                        if pid not in extra.setdefault(wnid, set()):
                            extra[wnid].add(pid)
                            changed = True
        if not changed:
            return anc
    raise AnalysisError("happens-before fixpoint did not converge")


def _check_slot_races(g: _Graph, anc: List[int]) -> List[Finding]:
    findings: List[Finding] = []
    for (rank, slot), accs in sorted(g.accesses.items()):
        for i in range(len(accs)):
            a_nid, a_kind = accs[i]
            for j in range(i + 1, len(accs)):
                b_nid, b_kind = accs[j]
                if a_kind == "read" and b_kind == "read":
                    continue
                if ((anc[b_nid] >> a_nid) & 1
                        or (anc[a_nid] >> b_nid) & 1):
                    continue
                ea, eb = g.event(a_nid), g.event(b_nid)
                findings.append(SlotRace(
                    message=(
                        f"rank {rank} slot {slot}: {a_kind} by {ea} "
                        f"races {b_kind} by {eb} — no happens-before "
                        f"edge orders them, so some schedule clobbers "
                        f"unconsumed data"
                    ),
                    events=(ea, eb), rank=rank, slot=slot,
                ))
    return findings


# ---------------------------------------------------------------------------
# Wire-lane monotonicity
# ---------------------------------------------------------------------------


def _check_wire_lanes(g: _Graph,
                      observed: Dict[int, Optional[int]]) -> List[Finding]:
    """Per destination, frames must be consumed in send order.

    Uses the replay's read -> writer map: in a race-free protocol each
    read observes the same writer under every schedule (data-race
    freedom determinism), so the replay's lane order IS the protocol's
    lane order. The re-read of the lane's last frame is legal (the
    all-gather's deliver-then-forward double read), mirroring
    ``credits._verify_frame``.
    """
    findings: List[Finding] = []
    # (reader_rank, lane key) -> (last seq, last writer nid)
    state: Dict[tuple, Tuple[int, int]] = {}
    for nid in sorted(observed):
        writer = observed[nid]
        if writer is None:
            continue
        reader, _ = g.rank_step(nid)
        if writer in g.dma_of_land:
            src, dst, seq = g.lane_of[g.dma_of_land[writer]]
            lane = (reader, ("wire", src, dst))
        else:
            src, seq = g.local_lane_of[writer]
            lane = (reader, ("local", src))
        last = state.get(lane)
        if last is not None:
            last_seq, last_writer = last
            if writer == last_writer:
                continue  # verified re-read of the same frame
            if seq != last_seq + 1:
                findings.append(WireLaneViolation(
                    message=(
                        f"rank {reader} consumed frame seq={seq} on "
                        f"lane {lane[1]} after seq={last_seq} — "
                        f"consumption order diverges from send order; "
                        f"the verified-transport framing would raise "
                        f"IntegrityError(kind='sequence') here"
                    ),
                    events=(g.event(nid), g.event(writer)),
                    rank=reader, expected=last_seq + 1, got=seq,
                ))
                continue
        elif seq != 0:
            findings.append(WireLaneViolation(
                message=(
                    f"rank {reader} consumed frame seq={seq} as the "
                    f"FIRST frame of lane {lane[1]} — frames before it "
                    f"were lost or overtaken"
                ),
                events=(g.event(nid), g.event(writer)),
                rank=reader, expected=0, got=seq,
            ))
            continue
        state[lane] = (seq, writer)
    return findings


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def verify_generators(
    make_generators: Callable[[], Sequence[Iterator]],
    protocol: str = "<anonymous>",
    shape: Optional[Dict[str, int]] = None,
) -> StaticReport:
    """Statically verify one protocol instance.

    ``make_generators`` builds the per-rank generators fresh (the same
    zero-arg-factory contract as ``credits.explore_all_schedules``); it
    is called twice so the recovered sequences can be compared — the
    schedule-independence assumption is checked, not trusted.
    """
    seqs = [symbolic_events(gen) for gen in make_generators()]
    seqs2 = [symbolic_events(gen) for gen in make_generators()]
    norm = [[_describe(a) for a in s] for s in seqs]
    norm2 = [[_describe(a) for a in s] for s in seqs2]
    if norm != norm2:
        # name the first diverging (rank, step, primitive) pair — a
        # bare "sequences differ" leaves the author of a
        # nondeterministic protocol grepping blind
        if len(norm) != len(norm2):
            raise AnalysisError(
                f"{protocol}: the two symbolic replays produced "
                f"{len(norm)} vs {len(norm2)} rank sequences — the "
                f"factory is not rebuilding the same instance, and no "
                f"static claim is possible"
            )
        rank, step, first, second = next(
            (r, i,
             s1[i] if i < len(s1) else "<end of sequence>",
             s2[i] if i < len(s2) else "<end of sequence>")
            for r, (s1, s2) in enumerate(zip(norm, norm2))
            for i in range(max(len(s1), len(s2)))
            if (s1[i:i + 1] or ["<end>"]) != (s2[i:i + 1] or ["<end>"])
        )
        raise AnalysisError(
            f"{protocol}: rank {rank} diverges at step {step} between "
            f"two symbolic replays — first replay yielded {first}, "
            f"second yielded {second}; the one-yield-per-primitive "
            f"discipline is violated and no static claim is possible"
        )
    g = _Graph(seqs)
    findings: List[Finding] = []
    checks: List[str] = ["credit-conservation"]
    findings.extend(_check_credit_conservation(g))
    replay = _replay(g)
    checks.append("deadlock")
    findings.extend(replay.findings)
    if replay.completed:
        anc = _happens_before(g)
        if anc is None:
            findings.append(StaticDeadlock(
                message=(
                    "happens-before graph contains a cycle — the "
                    "signal/wait matching is circular"
                ),
            ))
        else:
            checks.append("slot-race")
            races = _check_slot_races(g, anc)
            findings.extend(races)
            if not races:
                # lane order is schedule-independent only under DRF
                checks.append("wire-lane")
                findings.extend(_check_wire_lanes(g, replay.observed))
    ordered = tuple(c for c in CHECKS if c in checks)
    return StaticReport(
        protocol=protocol,
        shape=dict(shape or {}),
        ranks=g.n_ranks,
        events=len(g.preds),
        findings=tuple(findings),
        checks=ordered,
    )


# ---------------------------------------------------------------------------
# Registry: every protocol the fault layer knows, buildable by name
# ---------------------------------------------------------------------------


def _registered() -> Tuple[str, ...]:
    # the consolidated registry (credits.all_protocol_registries) is
    # the one enumeration — a protocol family registered there joins
    # the verifier, the perf decomposer, and the launch gate at once
    return C.registered_protocols()


def build_generators(protocol: str, n: int, chunks: int = 3,
                     slices: int = 2,
                     flow_control: bool = True) -> List[Iterator]:
    """Fresh per-rank generators for a registered protocol, with the
    standard symbolic contributions (mirrors the harnesses in
    :mod:`smi_tpu.parallel.credits`)."""
    if protocol == "all_gather":
        return [C.all_gather_rank(r, n, ("chunk", r),
                                  flow_control=flow_control)
                for r in range(n)]
    if protocol == "all_reduce":
        return [C.all_reduce_rank(r, n, frozenset([r]), lambda a, b: a | b,
                                  flow_control=flow_control)
                for r in range(n)]
    if protocol == "reduce_scatter":
        return [C.reduce_scatter_rank(
            r, n, [frozenset([(r, b)]) for b in range(n)],
            lambda a, b: a | b, flow_control=flow_control)
            for r in range(n)]
    if protocol == "neighbour_stream":
        return [C.neighbour_stream_rank(
            r, n, [(r, c) for c in range(chunks)],
            flow_control=flow_control)
            for r in range(n)]
    if protocol == "all_reduce_chunked":
        return [C.all_reduce_chunked_rank(
            r, n, [frozenset([(r, c)]) for c in range(chunks)],
            lambda a, b: a | b, flow_control=flow_control)
            for r in range(n)]
    if protocol == "allreduce_pod":
        if n % slices:
            raise ValueError(
                f"allreduce_pod needs n divisible by slices, got "
                f"n={n} slices={slices}"
            )
        return C.allreduce_pod_generators(slices, n // slices,
                                          flow_control=flow_control)
    if protocol == "all_to_all":
        return C.all_to_all_generators(n, flow_control=flow_control)
    if protocol == "all_to_all_bruck":
        # non-power-of-two n raises inside the generator factory — the
        # loud refusal the "no silent caps" satellite demands
        return C.all_to_all_generators(n, variant="bruck",
                                       flow_control=flow_control)
    if protocol == "all_to_all_pod":
        if n % slices:
            raise ValueError(
                f"all_to_all_pod needs n divisible by slices, got "
                f"n={n} slices={slices}"
            )
        return C.all_to_all_pod_generators(slices, n // slices,
                                           flow_control=flow_control)
    if protocol == "all_reduce_quantized":
        if n % slices:
            raise ValueError(
                f"all_reduce_quantized needs n divisible by slices, "
                f"got n={n} slices={slices}"
            )
        # symbolic-safe identity codec: the wire codec is caller
        # policy applied to opaque values and the structure does not
        # depend on it — the double-trace proves exactly that
        per_slice = n // slices
        return [
            C.all_reduce_quantized_rank(
                g, slices, per_slice,
                [frozenset([(g, c)]) for c in range(per_slice)],
                lambda a, b: a | b, flow_control=flow_control,
            )
            for g in range(n)
        ]
    if protocol == "all_reduce_sparse":
        return [
            C.all_reduce_sparse_rank(r, n, ("bundle", r),
                                     lambda bs: bs,
                                     flow_control=flow_control)
            for r in range(n)
        ]
    raise ValueError(
        f"unknown protocol {protocol!r}; known: {_registered()}"
    )


#: The shapes ``lint_all`` (and the CLI's ``smi-tpu lint``) verifies per
#: protocol — small enough to be instant, varied enough to cover the
#: degenerate (n=2) and odd cases the protocols special-case.
DEFAULT_SHAPES: Dict[str, Tuple[Dict[str, int], ...]] = {
    "all_gather": ({"n": 2}, {"n": 3}, {"n": 5}),
    "all_reduce": ({"n": 2}, {"n": 3}, {"n": 5}),
    "reduce_scatter": ({"n": 2}, {"n": 3}, {"n": 5}),
    "neighbour_stream": (
        {"n": 2, "chunks": 3}, {"n": 4, "chunks": 5},
    ),
    "all_reduce_chunked": (
        {"n": 2, "chunks": 2}, {"n": 3, "chunks": 3},
    ),
    "allreduce_pod": (
        {"n": 4, "slices": 2}, {"n": 6, "slices": 2},
        {"n": 6, "slices": 3},
    ),
    "all_to_all": ({"n": 2}, {"n": 3}, {"n": 5}),
    # Bruck is power-of-two only (loud otherwise), so its grid is too
    "all_to_all_bruck": ({"n": 2}, {"n": 4}, {"n": 8}),
    "all_to_all_pod": (
        {"n": 4, "slices": 2}, {"n": 6, "slices": 2},
        {"n": 6, "slices": 3},
    ),
    # the compressed-wire family (r19): the quantized composition over
    # the pod grid, the sparse gather over the ring grid
    "all_reduce_quantized": (
        {"n": 4, "slices": 2}, {"n": 6, "slices": 2},
        {"n": 6, "slices": 3},
    ),
    "all_reduce_sparse": ({"n": 2}, {"n": 3}, {"n": 5}),
}


def verify_protocol(protocol: str, n: int, chunks: int = 3,
                    slices: int = 2) -> StaticReport:
    """Statically verify one registered protocol at one shape."""
    shape: Dict[str, int] = {"n": n}
    if protocol in ("neighbour_stream", "all_reduce_chunked"):
        shape["chunks"] = chunks
    if protocol in ("allreduce_pod", "all_to_all_pod",
                    "all_reduce_quantized"):
        shape["slices"] = slices
    return verify_generators(
        lambda: build_generators(protocol, n, chunks=chunks,
                                 slices=slices),
        protocol=protocol, shape=shape,
    )


def lint_all(
    protocols: Optional[Sequence[str]] = None,
    shapes: Optional[Dict[str, Sequence[Dict[str, int]]]] = None,
) -> List[StaticReport]:
    """Verify every registered protocol (or the named subset) over the
    default shape grid — the ``smi-tpu lint`` engine."""
    known = _registered()
    if protocols is None:
        protocols = known
    else:
        unknown = [p for p in protocols if p not in known]
        if unknown:
            raise ValueError(
                f"unknown protocol(s) {unknown}; known: {list(known)}"
            )
    shapes = dict(DEFAULT_SHAPES, **(shapes or {}))
    reports = []
    for protocol in protocols:
        for shape in shapes[protocol]:
            reports.append(verify_protocol(protocol, **shape))
    return reports


def reports_to_json(reports: Sequence[StaticReport]) -> dict:
    """The ``smi-tpu lint --json`` payload (schema-tested)."""
    return {
        "ok": all(r.ok for r in reports),
        "findings": sum(len(r.findings) for r in reports),
        "checks": list(CHECKS),
        "protocols": [r.to_json() for r in reports],
    }


def render_reports(reports: Sequence[StaticReport]) -> str:
    lines = [r.describe() for r in reports]
    n_findings = sum(len(r.findings) for r in reports)
    lines.append(
        f"{len(reports)} protocol instance(s) verified, "
        f"{n_findings} finding(s)"
    )
    return "\n".join(lines)


def _json_default(o):  # pragma: no cover — debugging convenience
    return str(o)


def dumps(reports: Sequence[StaticReport]) -> str:
    return json.dumps(reports_to_json(reports), indent=2,
                      default=_json_default)
