"""Routing layer: topology graph, egress/ingress tables, load balancing.

Reference parity: ``codegen/routing.py`` + ``codegen/routing_table.py``.
The reference compiles, per FPGA and per physical channel, two lookup
tables that drive its packet-switched NoC:

- the CKS (egress) table maps ``(dst_rank, port)`` to {0 = out the wire,
  1 = deliver locally, 2+k = hand to the k-th sibling channel}, built from
  all-pairs shortest paths and then *balanced* so equal-cost routes spread
  across QSFP links by occupancy (``routing_table.py:150-202``);
- the CKR (ingress) table maps ``(port, data|control)`` to {0 = bounce to
  egress, 1+k = sibling ingress, N+j = j-th local op slot}
  (``routing_table.py:205-234``).

On TPU, XLA routes over the ICI torus and none of this is needed for
correctness — but the layer is kept at full fidelity because (a) it is the
reference's most heavily unit-tested component, (b) its binary artifacts
feed the native C++ host runtime exactly as the reference's tables feed
``LoadRoutingTable`` (``include/utils/smi_utils.hpp:24-39``), and (c) the
balanced egress decision tells the TPU runtime which mesh *neighbour* a
logical port should prefer (``egress_link_toward``), informing how P2P
ports map onto ICI directions.

Table entry encodings are kept bit-identical to the reference so table
files interoperate with reference-format loaders.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import networkx

from smi_tpu.ops.operations import IN_CTRL, IN_DATA, OUT_CTRL, OUT_DATA
from smi_tpu.ops.program import Device, Program
from smi_tpu.ops.serialization import Topology

#: Edge weights (``codegen/program.py:7-8``): hopping between devices is
#: two orders costlier than moving between links inside one device.
COST_INTER_DEVICE = 100
COST_INTRA_DEVICE = 1

#: Links (physical channels) per device (``CHANNELS_PER_FPGA = 4``).
LINKS_PER_DEVICE = 4

#: Egress table target codes (``routing_table.py:9-10,125-140``).
EGRESS_WIRE = 0    # leave the device through this link's physical wire
EGRESS_LOCAL = 1   # deliver to this link's ingress side (same device)
# 2 + sibling_index(...)  = forward to a sibling link's egress


class NoRouteFound(Exception):
    """No path exists between two devices in the topology graph."""


class RouteCutError(NoRouteFound):
    """A route exists in the healthy topology but the excluded
    links/devices cut it. ``cut`` names the exclusion set responsible —
    the reference's static tables have no answer to this (a compiled
    CKS entry points at a dead wire forever); the TPU layer recomputes
    around the failure and names the cut when it cannot."""

    def __init__(self, message: str, cut: "FailureSet"):
        super().__init__(message)
        self.cut = cut


@dataclasses.dataclass(frozen=True)
class FailureSet:
    """Failed hardware to route around.

    ``links`` are wire *endpoints* ``(device, link_index)`` — excluding
    either endpoint takes the whole physical wire down (both directions;
    a dead QSFP/ICI link is dead both ways). ``devices`` are whole
    devices: their wires go down and nothing may transit them, but they
    KEEP their rank slot — table shape and rank numbering must stay
    stable so healthy ranks' tables remain valid (shrinking the rank
    space itself is :meth:`Communicator.shrink`'s job).
    """

    links: frozenset = frozenset()    # of (Device, link_index)
    devices: frozenset = frozenset()  # of Device

    def __post_init__(self):
        object.__setattr__(self, "links", frozenset(self.links))
        object.__setattr__(self, "devices", frozenset(self.devices))

    @property
    def empty(self) -> bool:
        return not self.links and not self.devices

    def wire_down(self, a: Link, b: Link) -> bool:
        """Is the physical wire between endpoints ``a`` and ``b`` down?"""
        for end in (a, b):
            if end.device in self.devices:
                return True
            if (end.device, end.index) in self.links:
                return True
        return False

    def __str__(self) -> str:
        parts = []
        if self.links:
            parts.append(
                "links {"
                + ", ".join(
                    sorted(f"{d}:ch{i}" for d, i in self.links)
                )
                + "}"
            )
        if self.devices:
            parts.append(
                "devices {" + ", ".join(sorted(map(str, self.devices))) + "}"
            )
        return " + ".join(parts) if parts else "(none)"


@dataclasses.dataclass(frozen=True, order=True)
class Link:
    """One physical link endpoint of a device."""

    device: Device
    index: int

    def __str__(self) -> str:
        return f"{self.device}:ch{self.index}"


def sibling_index(source: int, target: int) -> int:
    """Index of ``target`` among a device's links with ``source`` skipped.

    The inter-link forwarding convention (``codegen/program.py:163-169``):
    a link never addresses itself, so sibling numbering omits it.
    """
    if source == target:
        raise ValueError("a link has no sibling index for itself")
    return target if target < source else target - 1


@dataclasses.dataclass
class RoutingContext:
    """Topology graph + all-pairs shortest paths + ranked devices.

    Reference: ``codegen/common.py`` ``RoutingContext{graph, routes,
    fpgas}`` built by ``create_routing_context`` (``routing.py:18-24``).
    """

    graph: networkx.Graph
    paths: Dict[Link, Dict[Link, List[Link]]]
    devices: List[Device]
    links_per_device: int = LINKS_PER_DEVICE
    topology: Optional[Topology] = None
    #: Failure set this context was built around (None = healthy).
    excluded: Optional["FailureSet"] = None

    def rank_of(self, device: Device) -> int:
        return self.devices.index(device)

    def links(self, device: Device) -> List[Link]:
        return [Link(device, i) for i in range(self.links_per_device)]


#: Memo for :func:`build_routing_context`, keyed by topology IDENTITY
#: (topologies hold dicts, so they are not hashable; the cached entry
#: pins the topology object, which keeps its ``id`` from being reused
#: while the entry lives). Bounded: oldest entry evicted past the cap.
_CONTEXT_CACHE: "Dict[Tuple[int, int, Optional[FailureSet]], Tuple[Topology, RoutingContext]]" = {}
_CONTEXT_CACHE_MAX = 16
#: build counter (cache misses), asserted on by the retrace-cache test.
_context_builds = 0


def build_routing_context(
    topology: Topology,
    links_per_device: int = LINKS_PER_DEVICE,
    excluded: Optional[FailureSet] = None,
) -> RoutingContext:
    """Build the weighted link graph and solve all-pairs shortest paths.

    Inter-device edges come from the topology's connection list; every
    device's links are additionally fully meshed at intra-device cost
    (``routing.py:49-54``) — the analog of the CK interconnect.

    ``excluded`` (a :class:`FailureSet`) builds the *degraded* context:
    down wires are omitted, down devices lose all edges (no transit) but
    keep their rank slot so table shapes and rank numbering stay stable.

    Memoized per ``(topology identity, links, failure set)``: the
    all-pairs Dijkstra is the expensive step and used to rerun on
    every call — ``egress_link_toward`` per traced program point, and
    the :class:`RouteCutError` classifier's healthy-topology rebuild
    per unroutable pair. Contexts are immutable in practice (callers
    only read), so one instance serves all of them.
    """
    global _context_builds
    key = (id(topology), links_per_device, excluded)
    hit = _CONTEXT_CACHE.get(key)
    if hit is not None and hit[0] is topology:
        return hit[1]
    ctx = _build_routing_context(topology, links_per_device, excluded)
    if len(_CONTEXT_CACHE) >= _CONTEXT_CACHE_MAX:
        _CONTEXT_CACHE.pop(next(iter(_CONTEXT_CACHE)))
    _CONTEXT_CACHE[key] = (topology, ctx)
    _context_builds += 1
    return ctx


def _build_routing_context(
    topology: Topology,
    links_per_device: int,
    excluded: Optional[FailureSet],
) -> RoutingContext:
    graph = networkx.Graph()
    devices = topology.devices
    known = set(devices)
    for device in devices:
        for link in (Link(device, i) for i in range(links_per_device)):
            graph.add_node(link)
    for (src_dev, src_l), (dst_dev, dst_l) in topology.connections.items():
        for dev in (src_dev, dst_dev):
            # fail loudly on pass-through devices absent from the program
            # map, as the reference does (codegen/routing.py:38 KeyError)
            if dev not in known:
                raise KeyError(
                    f"device {dev} appears in connections but has no "
                    f"program mapping"
                )
        if excluded is not None and excluded.wire_down(
            Link(src_dev, src_l), Link(dst_dev, dst_l)
        ):
            continue
        graph.add_edge(
            Link(src_dev, src_l), Link(dst_dev, dst_l), weight=COST_INTER_DEVICE
        )
    for device in devices:
        if excluded is not None and device in excluded.devices:
            continue  # a dead device forwards nothing, not even internally
        for a in range(links_per_device):
            for b in range(a + 1, links_per_device):
                graph.add_edge(
                    Link(device, a), Link(device, b), weight=COST_INTRA_DEVICE
                )
    paths = dict(networkx.all_pairs_dijkstra_path(graph, weight="weight"))
    return RoutingContext(
        graph=graph, paths=paths, devices=devices,
        links_per_device=links_per_device, topology=topology,
        excluded=excluded,
    )


def degraded_context(
    ctx: RoutingContext, excluded: FailureSet
) -> RoutingContext:
    """Rebuild a routing context with a failure set applied.

    Requires the context to carry its topology (contexts built by
    :func:`build_routing_context` from a parsed topology file do).
    """
    if ctx.topology is None:
        raise ValueError(
            "degraded routing needs the context's topology; build the "
            "context with build_routing_context(topology)"
        )
    return build_routing_context(
        ctx.topology, ctx.links_per_device, excluded=excluded
    )


def _check_stream_count(ctx: RoutingContext, program: Program) -> None:
    """Stream indices double as link indices in the tables; a mismatch
    would silently alias forward codes with local-slot codes."""
    if program.num_streams != ctx.links_per_device:
        raise ValueError(
            f"program allocated over {program.num_streams} streams but the "
            f"routing context has {ctx.links_per_device} links per device; "
            f"they must match"
        )


# ---------------------------------------------------------------------------
# Egress (CKS-equivalent) tables
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class EgressTable:
    """``(dst_rank, port) -> target code`` for one link."""

    n_ranks: int
    n_ports: int
    data: List[List[int]] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if not self.data:
            self.data = [
                [EGRESS_WIRE] * self.n_ports for _ in range(self.n_ranks)
            ]

    def __getitem__(self, key: Tuple[int, int]) -> int:
        rank, port = key
        return self.data[rank][port]

    def __setitem__(self, key: Tuple[int, int], value: int) -> None:
        rank, port = key
        self.data[rank][port] = value

    def flat(self) -> List[int]:
        return [v for row in self.data for v in row]


def _paths_to_device(
    ctx: RoutingContext, link: Link, dst: Device
) -> List[List[Link]]:
    """All shortest full paths (source link included) from ``link`` to the
    links of ``dst``, deterministically ordered (``routing_table.py:108-122``
    analog; the source stays on the path so device-hop counting matches the
    reference's ``path_fpga_length``).

    In a degraded context (``ctx.excluded``) a missing route is
    classified: if the *healthy* topology routes the pair, the failure
    set is the cause and a :class:`RouteCutError` names it; only a
    topology that never routed the pair raises plain
    :class:`NoRouteFound`.
    """
    routes = ctx.paths.get(link, {})
    found = [
        path
        for target, path in routes.items()
        if target.device == dst and len(path) > 1
    ]
    if not found:
        if ctx.excluded is not None and ctx.topology is not None:
            healthy = build_routing_context(
                ctx.topology, ctx.links_per_device
            )
            try:
                _paths_to_device(healthy, link, dst)
            except NoRouteFound:
                pass  # never routable: not the cut's fault
            else:
                raise RouteCutError(
                    f"no route from {link} to {dst}: the failure set "
                    f"[{ctx.excluded}] cuts every path",
                    cut=ctx.excluded,
                )
        raise NoRouteFound(f"no route from {link} to {dst}")
    found.sort(key=lambda p: (len(p), [(l.device.key, l.index) for l in p]))
    return found


def _devices_on_path(path: Sequence[Link]) -> int:
    return len({l.device for l in path})


def _first_hop_code(link: Link, path: Sequence[Link]) -> int:
    """Encode a full path's first hop as an egress target code."""
    hop = path[1]
    if hop.device != link.device:
        return EGRESS_WIRE
    return 2 + sibling_index(link.index, hop.index)


def _exit_link(link: Link, path: Sequence[Link]) -> Link:
    """The local link through which this full path leaves the device."""
    hop = path[1]
    return link if hop.device != link.device else hop


def egress_tables(
    device: Device, ctx: RoutingContext, program: Program,
    excluded: Optional[FailureSet] = None,
) -> Dict[Link, EgressTable]:
    """Build the per-link egress tables for one device, two-pass.

    Pass 1 (``routing_table.py:186-191``): route every (dst, port) along
    the plain shortest path (inter-link hops included in the cost).

    Pass 2 (``routing_table.py:193-202``): for the ports actually
    allocated to each link's outgoing streams, re-decide among all routes
    that are equally short in *device* hops, picking the least-occupied
    exit link — spreading traffic across the device's wires.

    ``excluded`` computes *degraded-mode* tables: routes avoid the
    failed links/devices when a path exists, and a destination the
    failure set cuts off raises :class:`RouteCutError` naming the cut —
    a place the TPU design is strictly stronger than the reference,
    whose compiled static tables cannot reroute at all.
    """
    if excluded is not None and not excluded.empty:
        ctx = degraded_context(ctx, excluded)
    _check_stream_count(ctx, program)
    n_ranks = len(ctx.devices)
    n_ports = program.logical_port_count
    links = ctx.links(device)
    tables = {link: EgressTable(n_ranks, n_ports) for link in links}
    occupancy = {link: 0 for link in links}

    for dst in ctx.devices:
        for link in links:
            if dst == device:
                code = EGRESS_LOCAL
            else:
                best = _paths_to_device(ctx, link, dst)[0]  # shortest, det.
                code = _first_hop_code(link, best)
            rank = ctx.rank_of(dst)
            for port in range(n_ports):
                tables[link][rank, port] = code

    for dst in ctx.devices:
        if dst == device:
            continue
        rank = ctx.rank_of(dst)
        for link in links:
            usages = _outgoing_allocations(program, link.index)
            if not usages:
                continue
            # candidate grouping depends only on (link, dst): hoist it out
            # of the per-usage loop (only occupancy changes inside)
            candidates = _paths_to_device(ctx, link, dst)
            fewest_devs = min(_devices_on_path(p) for p in candidates)
            by_exit: Dict[Link, int] = {}  # exit link -> min hop count
            for p in candidates:
                if _devices_on_path(p) != fewest_devs:
                    continue
                e = _exit_link(link, p)
                by_exit[e] = min(by_exit.get(e, len(p)), len(p))
            for family, port, key in usages:
                # pick least occupied (tie: shortest, then lowest link
                # index — routing_table.py:166-168)
                exit_link = min(
                    by_exit,
                    key=lambda e: (occupancy[e], by_exit[e], e.index),
                )
                if exit_link == link:
                    code = EGRESS_WIRE
                else:
                    code = 2 + sibling_index(link.index, exit_link.index)
                tables[link][rank, port] = code
                occupancy[exit_link] += 1
    return tables


def _outgoing_allocations(
    program: Program, link_index: int
) -> List[Tuple[str, int, str]]:
    """(family, port, key) triples whose outgoing stream is this link, in
    deal order (``program.py:116-117`` ``get_channel_allocations_with_prefix``)."""
    return [
        usage
        for usage in program.stream_allocations(link_index)
        if usage[2] in (OUT_DATA, OUT_CTRL)
    ]


# ---------------------------------------------------------------------------
# Ingress (CKR-equivalent) tables
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class IngressTable:
    """``(port, data|control) -> target code`` for one link, flattened as
    ``[port0_data, port0_ctrl, port1_data, ...]`` (``ckr.cl:54``)."""

    data: List[int]

    def flat(self) -> List[int]:
        return list(self.data)


def ingress_table(
    link: Link, ctx: RoutingContext, program: Program,
    excluded: Optional[FailureSet] = None,
) -> IngressTable:
    """Build one link's ingress table.

    Codes (``routing_table.py:205-225``): 0 = hand back to the egress side
    (packet not consumed here — used both for foreign packets and ports
    with no local consumer); 1 + sibling = forward to a sibling link's
    ingress; ``links_per_device + j`` = deliver to the j-th local op slot
    served by this link.

    Ingress delivery is intra-device (the CK interconnect, not a
    physical wire), so a failure set cannot change the entries — but a
    table for a link or device the set declares dead is a contradiction
    the caller should hear about, not a silently valid artifact.
    """
    if excluded is not None and (
        link.device in excluded.devices
        or (link.device, link.index) in excluded.links
    ):
        raise RouteCutError(
            f"ingress table requested for {link}, which the failure set "
            f"[{excluded}] declares down",
            cut=excluded,
        )
    _check_stream_count(ctx, program)
    n = ctx.links_per_device
    consumers: Dict[Tuple[int, str], int] = {}
    for (family, port, key), stream in program.allocation.items():
        if key in (IN_DATA, IN_CTRL):
            consumers[(port, key)] = stream

    # slot numbering follows the deal order of this link's allocations
    # (routing_table.py:223-225 uses the channel allocation list order)
    local_slots = [
        (port, key)
        for (family, port, key) in program.stream_allocations(link.index)
        if key in (IN_DATA, IN_CTRL)
    ]

    table: List[int] = []
    for port in range(program.logical_port_count):
        for key in (IN_DATA, IN_CTRL):
            stream = consumers.get((port, key))
            if stream is None:
                table.append(0)
            elif stream != link.index:
                table.append(1 + sibling_index(link.index, stream))
            else:
                table.append(n + local_slots.index((port, key)))
    return IngressTable(table)


# ---------------------------------------------------------------------------
# Serialization + neighbour queries
# ---------------------------------------------------------------------------


def serialize_table(flat: Sequence[int], width: int = 1) -> bytes:
    """Little-endian fixed-width bytes (``routing_table.py:57-63``)."""
    fmt = {1: "<B", 2: "<H", 4: "<I"}[width]
    return b"".join(struct.pack(fmt, v) for v in flat)


def deserialize_table(raw: bytes, width: int = 1) -> List[int]:
    fmt = {1: "<B", 2: "<H", 4: "<I"}[width]
    size = struct.calcsize(fmt)
    return [
        struct.unpack(fmt, raw[i : i + size])[0]
        for i in range(0, len(raw), size)
    ]


def write_routing_tables(
    directory, topology: Topology, ctx: Optional[RoutingContext] = None
) -> None:
    """Emit the binary table files for every device and link.

    File naming matches the reference host loader
    (``include/utils/smi_utils.hpp:24-39``): ``cks-rank{r}-channel{c}``
    for egress, ``ckr-rank{r}-channel{c}`` for ingress.
    """
    import os

    if ctx is None:
        ctx = build_routing_context(topology)
    os.makedirs(directory, exist_ok=True)
    for device in ctx.devices:
        program = topology.mapping.program_for(device)
        rank = ctx.rank_of(device)
        etables = egress_tables(device, ctx, program)
        for link in ctx.links(device):
            with open(
                os.path.join(directory, f"cks-rank{rank}-channel{link.index}"),
                "wb",
            ) as f:
                f.write(serialize_table(etables[link].flat()))
            with open(
                os.path.join(directory, f"ckr-rank{rank}-channel{link.index}"),
                "wb",
            ) as f:
                f.write(
                    serialize_table(ingress_table(link, ctx, program).flat())
                )


def check_all_pairs_routable(
    ctx: RoutingContext, devices: Optional[Sequence[Device]] = None
) -> None:
    """Assert every (src link, dst) pair among ``devices`` routes.

    The same granularity table building demands: every link of every
    source must reach every destination. Raises :class:`RouteCutError`
    (naming the cut) when the context's failure set severs a pair, or
    plain :class:`NoRouteFound` when the topology never routed it —
    the public surface behind ``python -m smi_tpu route --check``.
    ``devices`` defaults to all of the context's devices; pass the
    healthy subset to validate a degraded context whose down devices
    are expected to be unreachable.
    """
    devices = ctx.devices if devices is None else list(devices)
    for src in devices:
        for dst in devices:
            if src == dst:
                continue
            for link in ctx.links(src):
                _paths_to_device(ctx, link, dst)


def grid_topology(
    nrow: int,
    ncol: int,
    wrap: bool = True,
    program: Optional[Program] = None,
) -> Topology:
    """Build an ``nrow x ncol`` grid/torus topology (1-D ring when
    ``nrow == 1``).

    Link convention per device: 0 = east, 1 = west, 2 = south,
    3 = north — each physical endpoint used exactly once, matching the
    topology-file invariant. ``wrap`` closes each row/column into a
    ring, the ICI-torus shape the degraded-routing property tests cut
    links out of. All devices run ``program`` (default: a minimal
    Push/Pop pair), mirroring the SPMD common case.
    """
    from smi_tpu.ops.operations import Pop, Push
    from smi_tpu.ops.program import ProgramMapping

    if nrow < 1 or ncol < 1:
        raise ValueError(f"grid must be >= 1x1, got {nrow}x{ncol}")
    if program is None:
        program = Program([Push(0), Pop(0)])
    devices = {
        (r, c): Device(node=f"node-{r}-{c}", index=0)
        for r in range(nrow)
        for c in range(ncol)
    }
    connections: Dict[Tuple[Device, int], Tuple[Device, int]] = {}

    def wire(a: Device, la: int, b: Device, lb: int) -> None:
        connections[(a, la)] = (b, lb)
        connections[(b, lb)] = (a, la)

    for r in range(nrow):
        for c in range(ncol):
            if ncol > 1:
                if c + 1 < ncol:
                    wire(devices[(r, c)], 0, devices[(r, c + 1)], 1)
                elif wrap:
                    wire(devices[(r, c)], 0, devices[(r, 0)], 1)
            if nrow > 1:
                if r + 1 < nrow:
                    wire(devices[(r, c)], 2, devices[(r + 1, c)], 3)
                elif wrap:
                    wire(devices[(r, c)], 2, devices[(0, c)], 3)
    mapping = ProgramMapping(
        programs=[program],
        device_to_program={d: program for d in devices.values()},
    )
    return Topology(connections=connections, mapping=mapping)


#: The link indices that carry CROSS-SLICE (DCN) wires in a pod
#: topology: :func:`pod_topology` routes slice rings over east/west
#: (0/1) and the inter-slice columns over south/north (2/3), so a
#: failure set naming a (device, 2|3) endpoint cuts DCN capacity while
#: (device, 0|1) cuts ICI — the two tiers are physically distinct
#: wire populations, exactly as on a real pod.
POD_DCN_LINK_INDICES = (2, 3)


def pod_topology(
    n_slices: int,
    per_slice: int,
    program: Optional[Program] = None,
) -> Topology:
    """A ``(slices, ranks_per_slice)`` pod-of-slices topology.

    Row ``s`` is slice ``s``: a ring of ``per_slice`` devices over the
    east/west wires (the ICI tier). Same-index ranks across slices
    ring up over the south/north wires (the DCN tier) — one cross
    ring per in-slice position, which is exactly the wire population
    the two-tier allreduce's phase B uses (``credits.
    allreduce_pod_rank``). Structurally this IS the wrap grid of
    :func:`grid_topology` with rows = slices — the pod is the torus
    read tier-wise — so every existing degraded-routing property
    (FailureSet cuts, RouteCutError naming, all-pairs checks) applies
    to pods unchanged. Rank order is row-major: slice ``s`` owns
    ranks ``[s*per_slice, (s+1)*per_slice)``, matching
    ``mesh.make_hybrid_communicator`` and ``credits.pod_slice_of``.
    """
    if n_slices < 1 or per_slice < 1:
        raise ValueError(
            f"pod must be >= 1x1, got {n_slices}x{per_slice}"
        )
    return grid_topology(n_slices, per_slice, wrap=True, program=program)


def pod_slice_partition(topology: Topology, n_slices: int):
    """Contiguous rank groups of a pod topology: slice ``s`` = the
    ``s``-th equal block of the topology's rank order. Loud on a
    device count the slice count does not divide — a launcher asking
    for 3 slices of an 8-device pod is a config error, not a guess."""
    devices = topology.devices
    if n_slices < 1:
        raise ValueError(f"n_slices must be >= 1, got {n_slices}")
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} "
            f"equal slices"
        )
    k = len(devices) // n_slices
    return [devices[s * k:(s + 1) * k] for s in range(n_slices)]


def alltoall_pairwise_schedule(n: int) -> List[List[Tuple[int, int]]]:
    """The pairwise-exchange step schedule as data: step ``s`` (1-based
    in protocol terms, list index ``s - 1`` here) pairs every rank
    ``g`` with destination ``(g + s) % n`` — the exact rotation
    ``credits.all_to_all_rank`` executes, exposed so launchers and the
    membership layer can reason about which wires each step drives.

    Invariants (property-tested): every ordered (src, dst) pair with
    ``src != dst`` appears exactly once across the ``n - 1`` steps,
    and within one step the send set is a permutation (each rank sends
    once and receives once) — the schedule shape that lets a step's
    exchanges share the fabric without head-of-line blocking. ``n``
    follows the CURRENT communicator size, which is what makes the
    schedule shrink/regrow-compatible: after a membership change the
    surviving ranks' schedule is simply the smaller ``n``'s (see
    :meth:`smi_tpu.parallel.mesh.Communicator.alltoall_schedule`).
    """
    if n < 1:
        raise ValueError(f"need n >= 1 ranks, got {n}")
    return [
        [(g, (g + s) % n) for g in range(n)]
        for s in range(1, n)
    ]


def egress_link_toward(
    src: Device,
    dst: Device,
    ctx: RoutingContext,
    program: Optional[Program] = None,
    port: int = 0,
    stream_key: str = OUT_DATA,
    tables: Optional[Dict[Link, EgressTable]] = None,
) -> Tuple[int, Device]:
    """Which local wire leaves ``src`` toward ``dst``, and the neighbouring
    device on its far end.

    With a ``program``, the answer follows the *balanced* egress tables for
    the given logical port: the port's packets enter the link its
    ``stream_key`` usage was dealt to, then forward codes are chased from
    link to link until a wire exit — exactly the journey a packet takes
    through the reference's CK_S chain (``cks.cl:55-71``). This is the
    TPU-side consumer of the routing layer: a logical port's preferred ICI
    direction is the neighbour its balanced route exits through.

    Without a ``program`` the plain shortest-path exit is returned. Pass
    precomputed ``tables`` (from :func:`egress_tables`) when querying many
    ports of one device — rebuilding them per call is O(devices² · ports).
    """
    if program is not None:
        if tables is None:
            tables = egress_tables(src, ctx, program)
        rank = ctx.rank_of(dst)
        usage = next(
            (
                (family, p, key)
                for (family, p, key) in program.allocation
                if p == port and key == stream_key
            ),
            None,
        )
        if usage is None:
            raise ValueError(
                f"port {port} has no {stream_key} usage in the program"
            )
        link = Link(src, program.allocation[usage])
        seen = set()
        while True:
            if link in seen:
                raise NoRouteFound(
                    f"forwarding cycle at {link} routing to {dst}"
                )
            seen.add(link)
            code = tables[link][rank, port]
            if code == EGRESS_WIRE:
                break
            if code == EGRESS_LOCAL:
                raise ValueError(f"{dst} is the local device")
            sib = code - 2
            nxt = sib if sib < link.index else sib + 1
            link = Link(src, nxt)
        if ctx.topology is None or (src, link.index) not in ctx.topology.connections:
            raise NoRouteFound(
                f"link {link} has no physical wire in the topology"
            )
        peer_dev, _peer_link = ctx.topology.connections[(src, link.index)]
        return link.index, peer_dev

    best: Optional[List[Link]] = None
    best_link: Optional[Link] = None
    for link in ctx.links(src):
        try:
            path = _paths_to_device(ctx, link, dst)[0]
        except NoRouteFound:
            continue
        if best is None or len(path) < len(best):
            best, best_link = path, _exit_link(link, path)
    if best is None or best_link is None:
        raise NoRouteFound(f"no route from {src} to {dst}")
    remote = next(l for l in best if l.device != src)
    return best_link.index, remote.device
