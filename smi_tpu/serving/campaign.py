"""Chaos under load: open-loop traffic cells and the seeded campaign.

Every robustness layer before this one ran against a single batch job;
these cells run the front-end against *sustained open-loop traffic* —
arrivals keep coming whether or not the system keeps up — and assert
the overload story end to end. Three cell shapes:

- **overload** — 2x the service capacity, no faults: admission must
  shed lowest-class-first (brownout ceilings), queue occupancy must
  stay inside the structural bound, interactive p99 admission latency
  must hold, and every accepted stream must still be delivered
  bit-identically;
- **kill** — a seeded kill-one-rank *during* the traffic: phi-accrual
  must confirm the death inside the watchdog budget, tenant routes
  must fail over to heirs, accepted in-flight streams must replay and
  complete bit-identically, straggler traffic from the dead
  incarnation must be rejected by epoch (counted; zero leaks);
- **backpressure** — one rank's consumer stalls (alive, heartbeating:
  the *saturated* half of the dead-vs-saturated distinction): the
  stall must propagate to the admission edge as named shedding, must
  NOT trigger any membership transition beyond a cleared suspicion,
  and every accepted stream must complete once the stall lifts.

Gates per cell (the campaign exit is nonzero if any fails):
zero silent corruption, zero lost-accepted, zero stale-epoch leaks,
bounded queue occupancy, lowest-class-first shedding (brownout sheds
ordered best_effort >= batch >= interactive, with zero interactive
brownout sheds), and interactive p99 admission wait <=
:data:`~smi_tpu.serving.qos.INTERACTIVE_P99_TICKS`. Deterministic per
seed — a red campaign reproduces from its JSON alone
(``smi-tpu chaos --load --seed N``).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from smi_tpu.parallel import faults as F
from smi_tpu.parallel.membership import WATCHDOG_TICKS, QuorumLostError
from smi_tpu.serving.admission import DEFAULT_POOL
from smi_tpu.serving.frontend import ServingFrontend
from smi_tpu.serving.qos import (
    CLASS_ADMISSION_WAIT_TICKS,
    INTERACTIVE_P99_TICKS,
    QOS_CLASSES,
    AdmissionRejected,
    percentile,
)

#: Traffic mix (weights) and chunks-per-request per class: interactive
#: requests are small and frequent, best_effort large and patient.
CLASS_MIX = {"interactive": 3, "batch": 3, "best_effort": 4}
CLASS_CHUNKS = {"interactive": 2, "batch": 4, "best_effort": 6}

#: Minimum campaign cell duration: every seeded fault the campaign can
#: draw (kill at tick 60, SlowConsumer from_tick <= 69) must land
#: INSIDE the traffic schedule with room for its effects to reach the
#: admission edge — a shorter run would report a misleading
#: "fault never fired" gate failure instead of a usage error.
MIN_CAMPAIGN_DURATION = 120


def _payload(tenant: str, stream_seq: int, chunk: int) -> str:
    """Deterministic, content-addressed chunk payload — bit-identity
    of delivery is checked against exactly this."""
    return f"{tenant}/s{stream_seq}/c{chunk}"


def campaign_recorder(duration: int, n: int):
    """A flight recorder sized to retain a WHOLE cell's event stream
    (the r15 span builder refuses a wrapped ring): generous per-tick
    estimate times the schedule, plus a drain cushion.
    ``$SMI_TPU_OBS_RING`` outranks the estimate — the operator's word
    stands, and an undersized override surfaces as a named
    span-exactness problem, never a silent truncation."""
    from smi_tpu.obs.events import FlightRecorder, ring_capacity

    estimate = duration * (n * 8 + 24) + 8192
    return FlightRecorder(capacity=ring_capacity(default=estimate))


def span_fields(fe, report: Dict, problems: List[str]) -> None:
    """Fold the span/blame payload into a cell report and extend the
    gate problems with any span-exactness failure (the bit-identity
    criterion: event-stream component sums == the front-end's own
    measured admission-to-delivery latencies)."""
    from smi_tpu.obs.spans import campaign_fields

    fields, span_problems = campaign_fields(fe)
    report.update(fields)
    problems.extend(span_problems)


def open_loop_traffic(
    seed: int,
    tenants: int,
    duration: int,
    requests_per_tick: float,
):
    """Seeded open-loop arrival schedule: a list per tick of
    ``(tenant, qos)`` submissions. Open-loop means the schedule never
    consults the system's state — arrivals continue regardless of
    shedding, which is what makes overload expressible at all."""
    rng = random.Random(f"traffic:{seed}")
    classes = [c for c in QOS_CLASSES for _ in range(CLASS_MIX[c])]
    schedule: List[List[Tuple[str, str]]] = []
    acc = 0.0
    for _ in range(duration):
        acc += requests_per_tick
        burst = []
        while acc >= 1.0:
            acc -= 1.0
            tenant = f"t{rng.randrange(tenants)}"
            burst.append((tenant, rng.choice(classes)))
        schedule.append(burst)
    return schedule


def run_load_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 240,
    overload: float = 1.0,
    kill_rank: Optional[int] = None,
    kill_at: int = 60,
    stall_rank: Optional[int] = None,
    stall_at: int = 40,
    stall_ticks: int = 60,
    tenants: int = 6,
    pool: int = DEFAULT_POOL,
    plan: Optional[F.FaultPlan] = None,
    return_frontend: bool = False,
):
    """One chaos-under-load cell: open-loop traffic, optional fault,
    full drain, gates evaluated. Deterministic per (shape, seed).

    Faults come either as explicit knobs (``kill_rank``/``kill_at``,
    ``stall_rank``/...) or as a :class:`~smi_tpu.parallel.faults.FaultPlan`
    carrying serving-level faults: each
    :class:`~smi_tpu.parallel.faults.SlowConsumer` maps onto a
    consumer stall in ticks (the seeded draw
    ``FaultPlan.random("slow_consumer", n, seed)`` is how the campaign
    sweeps the class). ``return_frontend=True`` returns
    ``(report, frontend)`` — the span/trace consumers need the live
    recorder, not just the report."""
    fe = ServingFrontend(n, seed=seed, pool=pool,
                         recorder=campaign_recorder(duration, n))
    if plan is not None:
        if plan.slow_consumers and stall_rank is not None:
            raise ValueError(
                "pass a stall either explicitly or via the plan, "
                "not both"
            )
        if len(plan.slow_consumers) > 1:
            raise ValueError(
                f"run_load_cell drives one SlowConsumer per cell; "
                f"the plan carries {len(plan.slow_consumers)} — "
                f"sweep more cells instead"
            )
        for f in plan.slow_consumers:
            stall_rank, stall_at = f.rank, f.from_tick
            stall_ticks = f.stall_ticks
    if kill_rank is not None and kill_at >= duration:
        raise ValueError(
            f"kill_at={kill_at} never fires inside a {duration}-tick "
            f"schedule — raise duration past the fault tick"
        )
    if stall_rank is not None and stall_at >= duration:
        raise ValueError(
            f"stall at tick {stall_at} never fires inside a "
            f"{duration}-tick schedule — raise duration past the "
            f"fault tick"
        )
    mean_chunks = (
        sum(CLASS_MIX[c] * CLASS_CHUNKS[c] for c in QOS_CLASSES)
        / sum(CLASS_MIX.values())
    )
    capacity = n * fe.consume_rate  # chunks/tick
    requests_per_tick = overload * capacity / mean_chunks
    schedule = open_loop_traffic(seed, tenants, duration,
                                 requests_per_tick)
    tenant_seq: Dict[str, int] = {}
    submitted = 0
    verdict = "ok"
    try:
        for tick, burst in enumerate(schedule):
            now = fe.clock.now()
            if kill_rank is not None and tick == kill_at:
                fe.kill(kill_rank)
            if stall_rank is not None and tick == stall_at:
                fe.stall_consumer(stall_rank, now + stall_ticks)
            for tenant, qos in burst:
                submitted += 1
                seq = tenant_seq.get(tenant, 0)
                tenant_seq[tenant] = seq + 1
                chunks = tuple(
                    _payload(tenant, seq, c)
                    for c in range(CLASS_CHUNKS[qos])
                )
                try:
                    fe.submit(tenant, qos, chunks)
                except AdmissionRejected:
                    pass  # named + recorded by the gate
            fe.step()
        fe.drain()
    except Exception as e:  # a watchdog/assert firing IS the verdict
        verdict = f"{type(e).__name__}: {e}"

    report = fe.report()
    report.update({
        "seed": seed,
        "duration": duration,
        "overload": overload,
        "kill_rank": kill_rank,
        "stall_rank": stall_rank,
        "plan": plan.describe() if plan is not None else [],
        "submitted_total": submitted,
        "offered_chunks_per_tick": round(
            requests_per_tick * mean_chunks, 3
        ),
        "capacity_chunks_per_tick": capacity,
        # the deterministic metrics snapshot (smi_tpu.obs): its
        # admitted/shed counters are incremented at the gate's own
        # accounting sites, so they EQUAL the report's bookkeeping —
        # tested, and the `--metrics` CLI surfaces quote it
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates ----------------------------------------------------------
    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    if report["silent_corruptions"]:
        problems.append(
            f"silent corruption: {report['silent_corruptions']} "
            f"stream(s) delivered wrong bits"
        )
    if report["lost_accepted"]:
        problems.append(
            f"lost accepted: {report['lost_accepted']} admitted "
            f"stream(s) never delivered"
        )
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    if report["max_queue_depth"] > report["queue_bound"]:
        problems.append(
            f"queue occupancy {report['max_queue_depth']} exceeded "
            f"bound {report['queue_bound']}"
        )
    brownout = {
        c: sum(v for k, v in report["shed"][c].items()
               if k.startswith("brownout") or k == "admission-timeout")
        for c in QOS_CLASSES
    }
    report["brownout_shed"] = brownout
    # destination-unavailability sheds (per-route backpressure) are a
    # separate, named category: class-blind by design, so they are
    # excluded from the lowest-class-first ordering gate
    report["backpressure_shed"] = {
        c: sum(v for k, v in report["shed"][c].items()
               if k.startswith("backpressure:"))
        for c in QOS_CLASSES
    }
    if kill_rank is None and brownout["interactive"] > 0:
        # fair weather and saturation: interactive never browns out.
        # During a kill's detection blackout the pool can genuinely
        # exhaust (stalled streams hold their credits by design), so
        # there the guarantee is ORDERING + the bounded wait cap.
        problems.append(
            f"interactive brownout-shed {brownout['interactive']} "
            f"(> 0): shedding is not lowest-class-first"
        )
    if (brownout["best_effort"] < brownout["batch"]
            or brownout["batch"] < brownout["interactive"]):
        problems.append(
            "shedding not lowest-class-first: best_effort "
            f"{brownout['best_effort']} / batch {brownout['batch']} / "
            f"interactive {brownout['interactive']}"
        )
    waits = report["admission_waits"]["interactive"]
    p99 = percentile(waits, 0.99)
    report["admission_latency"] = {
        c: {
            "p50": percentile(report["admission_waits"][c], 0.50),
            "p99": percentile(report["admission_waits"][c], 0.99),
        }
        for c in QOS_CLASSES
    }
    # the p99 bound: tight in fair weather, the structural wait cap
    # during a kill's detection blackout (bounded either way — the
    # admission edge sheds rather than queue past the cap)
    p99_bound = (INTERACTIVE_P99_TICKS if kill_rank is None
                 else CLASS_ADMISSION_WAIT_TICKS["interactive"])
    report["interactive_p99_bound"] = p99_bound
    if p99 is not None and p99 > p99_bound:
        problems.append(
            f"interactive p99 admission latency {p99:g} ticks "
            f"exceeds the {p99_bound}-tick bound"
        )
    if kill_rank is not None:
        if report["confirmed"] != [kill_rank]:
            problems.append(
                f"kill of rank {kill_rank} not confirmed "
                f"(confirmed: {report['confirmed']})"
            )
        elif report["detect_ticks"] is None or (
            report["detect_ticks"] > WATCHDOG_TICKS
        ):
            problems.append(
                f"detect latency {report['detect_ticks']} ticks "
                f"outside the {WATCHDOG_TICKS}-tick watchdog budget"
            )
        if not report["stale_epoch_rejections"]:
            problems.append("straggler from dead incarnation was "
                            "never presented/rejected")
    if stall_rank is not None:
        if report["confirmed"]:
            problems.append(
                f"stalled-but-alive consumer confirmed dead: "
                f"{report['confirmed']} (saturation mistaken for "
                f"death)"
            )
        if not any(report["backpressure_shed"].values()):
            problems.append(
                "consumer stall never propagated to the admission "
                "edge (zero backpressure sheds)"
            )
    # the r15 span layer: per-request span trees from the event
    # stream, the tail-latency blame verdict, and the bit-identity
    # exactness gate (span-component sums == measured latencies)
    span_fields(fe, report, problems)
    # drop the unhashed per-request wait lists from the shipped report
    # (the percentiles above carry the evidence)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


def load_campaign(
    seed: int = 0,
    n: int = 4,
    duration: int = 240,
    trials: int = 1,
    retune: bool = False,
    flash_crowd: bool = False,
) -> Dict:
    """The seeded chaos-under-load campaign: one overload cell, one
    kill-one-rank cell, and one backpressure cell per trial, each
    deterministic per seed. Exit gate: every cell ``ok``.

    ``duration`` below :data:`MIN_CAMPAIGN_DURATION` is a loud
    ``ValueError``: the seeded fault ticks would fall outside the
    schedule and report as (bogus) detection failures."""
    if duration < MIN_CAMPAIGN_DURATION:
        raise ValueError(
            f"campaign duration {duration} is below the "
            f"{MIN_CAMPAIGN_DURATION}-tick minimum: the seeded kill "
            f"(tick 60) and consumer-stall (from_tick <= 69) cells "
            f"need the fault inside the traffic schedule"
        )
    cells: List[Dict] = []
    for trial in range(trials):
        base = random.Random(f"load:{seed}:{trial}").randrange(1 << 30)
        kill = random.Random(f"kill:{seed}:{trial}").randrange(n)
        stall_plan = F.FaultPlan.random(
            "slow_consumer", n,
            random.Random(f"stall:{seed}:{trial}").randrange(1 << 30),
        )
        shapes = [
            ("overload", dict(overload=2.0)),
            ("kill", dict(overload=1.0, kill_rank=kill, kill_at=60)),
            ("backpressure", dict(overload=1.0, plan=stall_plan)),
        ]
        for name, kwargs in shapes:
            report = run_load_cell(
                n=n, seed=base, duration=duration, **kwargs
            )
            report["cell"] = name
            report["trial"] = trial
            cells.append(report)
        if retune:
            # the r14 cell: the payload distribution shifts mid-run
            # and the online tuner must hot-swap to the offline-sweep
            # pick with bit-identical delivery
            report = run_retune_cell(n=n, seed=base, duration=duration)
            report["cell"] = "retune-shift"
            report["trial"] = trial
            cells.append(report)
        if flash_crowd:
            # the r16 cell: one tenant 10x's its rate mid-run and
            # capacity must follow the load — scale-out, (blame-driven
            # migration when convicted), scale-in, loss-free
            report = run_flash_crowd_cell(
                n=n, seed=base,
                duration=max(duration, MIN_FLASH_CROWD_DURATION),
            )
            report["cell"] = "flash-crowd"
            report["trial"] = trial
            cells.append(report)
    failures = [c for c in cells if not c["ok"]]
    return {
        "seed": seed,
        "n": n,
        "duration": duration,
        "trials": trials,
        "cells": len(cells),
        "outcomes": {
            c["cell"]: ("ok" if c["ok"] else "failed") for c in cells
        },
        "failures": [
            {"cell": c["cell"], "trial": c["trial"],
             "verdict": c["verdict"]}
            for c in failures
        ],
        "silent_corruptions": sum(
            c["silent_corruptions"] for c in cells
        ),
        "lost_accepted": sum(c["lost_accepted"] for c in cells),
        "stale_epoch_leaks": sum(
            c["stale_epoch_leaks"] for c in cells
        ),
        "reports": cells,
        "ok": not failures,
    }


def run_retune_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 240,
    tenants: int = 4,
    pool: int = DEFAULT_POOL,
    slices: Optional[int] = None,
    small_kb: int = 64,
    large_kb: int = 4096,
    kill_rank: Optional[int] = None,
    kill_at: int = 60,
    return_frontend: bool = False,
):
    """The seeded payload-shift retuning cell (ROADMAP item 3's gate).

    A front-end runs with the online tuner wired
    (``ServingFrontend(retune=)``); every admitted request stands for
    one allreduce whose live timing is the Hockney pricing of the
    ACTIVE plan at that payload (the credits simulator's wire tiers)
    with seeded ±5% noise — exactly the measurement
    ``tracing.timed(sink=tuner)`` would stream on hardware, made
    deterministic. The tenants' payload distribution shifts mid-run
    (``small_kb`` → ``large_kb``), invalidating a STALE offline sweep
    entry that pinned the fused ring for the large bucket: the tuner
    must shadow-compare, propose, quiesce (drain the proposing
    tenant's in-flight streams), hot-swap the entry under a bumped
    plan epoch + revision, and converge to the plan a fresh offline
    sweep would pick for the new distribution (rs+ag flat,
    hierarchical on a ``slices``-pod) — with bit-identical delivery
    throughout, zero lost-accepted, zero stale-plan leaks, and zero
    swaps before the shift (the noise-can't-flip thresholds).
    """
    from smi_tpu.tuning import cost_model as cm
    from smi_tpu.tuning.cache import CacheEntry, PlanCache
    from smi_tpu.tuning.engine import _collective_topology
    from smi_tpu.tuning.online import OnlineTuner, priced_sample_us
    from smi_tpu.tuning.plan import PlanKey, payload_bucket

    if duration < MIN_CAMPAIGN_DURATION:
        raise ValueError(
            f"retune cell duration {duration} is below the "
            f"{MIN_CAMPAIGN_DURATION}-tick minimum: the payload shift "
            f"(mid-run) and the post-shift sample window both need "
            f"room inside the schedule"
        )
    if kill_rank is not None and kill_at >= duration:
        raise ValueError(
            f"kill_at={kill_at} never fires inside a {duration}-tick "
            f"schedule — raise duration past the fault tick"
        )
    if slices is not None:
        if slices < 2 or 8 % slices:
            raise ValueError(
                f"slices={slices} does not tier an 8-rank pod "
                f"(need a divisor >= 2)"
            )
        topo = cm.TopologySpec(n=8, inner=8 // slices, outer=slices)
    else:
        topo = cm.TopologySpec(n=8)
    device_kind = "live-sim"
    small_bytes, large_bytes = small_kb * 1024, large_kb * 1024
    if payload_bucket(small_bytes) == payload_bucket(large_bytes):
        raise ValueError(
            f"small_kb={small_kb} and large_kb={large_kb} land in the "
            f"same payload bucket — no distribution shift to retune on"
        )

    # the stale offline artifact: yesterday's sweep (run under the
    # small-payload mix this tenant no longer sends) pinned the fused
    # ring for the large bucket — the entry the live tuner must retire
    cache = PlanCache()
    topology = _collective_topology(topo)
    large_key = PlanKey("all_reduce", payload_bucket(large_bytes),
                        "float32", device_kind, topology)
    cache.put(large_key, CacheEntry(
        {"algorithm": "ring"},
        cost_us=round(priced_sample_us(
            "all_reduce", "ring", small_bytes, topo), 3),
        provenance="sweep:stale-offline",
    ))
    tuner = OnlineTuner(cache=cache, topo=topo,
                        device_kind=device_kind)
    fe = ServingFrontend(n, seed=seed, pool=pool, retune=tuner,
                         recorder=campaign_recorder(duration, n))

    # what a FRESH offline sweep would measure best for the new
    # distribution: the model's top candidate (samples are priced by
    # the same tables, so measurement and model agree here by
    # construction — the deterministic analog of the ATLAS claim)
    expected = cm.allreduce_candidates(large_bytes, topo)[0].name

    shift_at = duration // 2
    noise = random.Random(f"retune-noise:{seed}")
    mean_chunks = (
        sum(CLASS_MIX[c] * CLASS_CHUNKS[c] for c in QOS_CLASSES)
        / sum(CLASS_MIX.values())
    )
    capacity = n * fe.consume_rate
    requests_per_tick = capacity / mean_chunks
    schedule = open_loop_traffic(seed, tenants, duration,
                                 requests_per_tick)
    tenant_seq: Dict[str, int] = {}
    submitted = 0
    swap_tick = None
    early_swaps = 0
    verdict = "ok"
    try:
        for tick, burst in enumerate(schedule):
            if kill_rank is not None and tick == kill_at:
                fe.kill(kill_rank)
            payload = small_bytes if tick < shift_at else large_bytes
            for tenant, qos in burst:
                submitted += 1
                seq = tenant_seq.get(tenant, 0)
                tenant_seq[tenant] = seq + 1
                chunks = tuple(
                    _payload(tenant, seq, c)
                    for c in range(CLASS_CHUNKS[qos])
                )
                try:
                    fe.submit(tenant, qos, chunks)
                except AdmissionRejected:
                    # shed at the edge: the allreduce this request
                    # stood for never ran, so there is no timing to
                    # record — a rejected request must not inflate a
                    # cell's sample count toward the min_samples gate
                    continue
                # the live timing of the allreduce this request
                # drives, under whatever plan is ACTIVE right now
                entry = tuner.active_entry(
                    tuner.plan_key("all_reduce", payload)
                )
                algorithm = (
                    str(entry.knobs["algorithm"]) if entry is not None
                    else cm.allreduce_candidates(payload, topo)[0].name
                )
                us = priced_sample_us(
                    "all_reduce", algorithm, payload, topo
                ) * (1.0 + (noise.random() - 0.5) * 0.1)
                tuner.record("all_reduce", us * 1e-6,
                             payload_bytes=payload, tenant=tenant)
            fe.step()
            if tuner.swaps and swap_tick is None:
                swap_tick = tick
                if tick < shift_at:
                    early_swaps += 1
        fe.drain()
    except Exception as e:  # a watchdog/assert firing IS the verdict
        verdict = f"{type(e).__name__}: {e}"

    report = fe.report()
    final = tuner.active_entry(large_key)
    converged_algorithm = (
        str(final.knobs["algorithm"]) if final is not None else None
    )
    report.update({
        "seed": seed,
        "duration": duration,
        "shift_at": shift_at,
        "small_kb": small_kb,
        "large_kb": large_kb,
        "slices": slices,
        "kill_rank": kill_rank,
        "submitted_total": submitted,
        "expected_algorithm": expected,
        "converged_algorithm": converged_algorithm,
        "converged_revision": final.revision if final else None,
        "swap_tick": swap_tick,
        "convergence_ticks": (swap_tick - shift_at
                              if swap_tick is not None else None),
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates ----------------------------------------------------------
    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    if report["silent_corruptions"]:
        problems.append(
            f"silent corruption: {report['silent_corruptions']} "
            f"stream(s) delivered wrong bits"
        )
    if report["lost_accepted"]:
        problems.append(
            f"lost accepted: {report['lost_accepted']} admitted "
            f"stream(s) never delivered"
        )
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    rt = report["retune"]
    if rt["stale_plan_leaks"]:
        problems.append("stale-plan traffic accepted")
    if report["max_queue_depth"] > report["queue_bound"]:
        problems.append(
            f"queue occupancy {report['max_queue_depth']} exceeded "
            f"bound {report['queue_bound']}"
        )
    if early_swaps:
        problems.append(
            f"{early_swaps} swap(s) fired BEFORE the payload shift — "
            f"noise flipped a plan the thresholds should hold"
        )
    if rt["swaps"] < 1:
        problems.append(
            "the tuner never swapped: the stale offline entry "
            "survived the shifted distribution"
        )
    elif converged_algorithm != expected:
        problems.append(
            f"converged to {converged_algorithm!r} but a fresh "
            f"offline sweep of the shifted distribution picks "
            f"{expected!r}"
        )
    if rt["swaps"] >= 1 and not rt["stale_plan_rejections"]:
        problems.append(
            "post-swap straggler was never presented/rejected"
        )
    if rt["rollbacks"]:
        problems.append(
            f"{rt['rollbacks']} rollback(s) in the seeded cell — "
            f"quiesce did not drain inside its window"
        )
    if kill_rank is not None and report["confirmed"] != [kill_rank]:
        problems.append(
            f"kill of rank {kill_rank} not confirmed "
            f"(confirmed: {report['confirmed']})"
        )
    waits = report["admission_waits"]
    report["admission_latency"] = {
        c: {
            "p50": percentile(waits[c], 0.50),
            "p99": percentile(waits[c], 0.99),
        }
        for c in QOS_CLASSES
    }
    span_fields(fe, report, problems)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


def retune_selftest(seed: int = 0) -> Dict:
    """The ``smi-tpu serve --selftest --retune`` smoke: the seeded
    payload-shift cell at a fast shape — the tuner must converge to
    the offline-sweep pick with bit-identical delivery."""
    return run_retune_cell(n=4, seed=seed, duration=160)


# ---------------------------------------------------------------------------
# Demand elasticity (r16): flash-crowd, migration, migrate-under-kill
# ---------------------------------------------------------------------------

#: Minimum flash-crowd cell duration: the arc needs a fair-weather
#: lead-in, a crowd long enough to sustain scale-out past its
#: hysteresis, and a post-crowd tail long enough for the burn windows
#: to drain AND the scale-in sustain + cooldown to elapse.
MIN_FLASH_CROWD_DURATION = 240


def _delivery_digest(fe) -> Dict:
    """The bit-identity witness: every completed stream's DELIVERED
    payloads (what actually crossed the wire and was consumed, in
    sequence order), keyed by (tenant, stream seq). The migration
    cell diffs this against a no-migration control: any stream BOTH
    arms completed must carry identical bits. (The arms' accepted
    sets may lawfully diverge after the cutover — moving the tenant
    changes which rank later arrivals queue on, so backpressure may
    shed different requests — but delivery, for comparable work,
    must be bit-identical.)"""
    return {
        (st.request.tenant, st.request.stream_id[1]):
            tuple(st.delivered[k] for k in sorted(st.delivered))
        for st in fe.completed
    }


def _offer_live_blame(fe, ctrl, tenant: str) -> Dict:
    """Mid-run blame: build spans over the partial event stream,
    take the cell-level verdict, and offer it to the controller.
    Returns the audit dict the cell report carries; a span build
    failing mid-run is recorded, never raised (the end-of-run
    exactness gate still runs over the full stream)."""
    from smi_tpu.obs.spans import (
        SpanError,
        blame_report,
        blame_verdict,
        frontend_spans,
    )

    try:
        spans = frontend_spans(fe, allow_partial=True)
        verdict = blame_verdict(blame_report(spans))
    except SpanError as e:
        return {"verdict": None, "offered": False,
                "error": str(e)}
    return {
        "verdict": str(verdict),
        "kind": verdict.kind,
        "rank": verdict.rank,
        "offered": ctrl.offer_blame(verdict, tenant),
        "error": None,
    }


def run_flash_crowd_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 240,
    tenants: int = 6,
    pool: int = DEFAULT_POOL,
    spares: int = 1,
    crowd_factor: int = 10,
    return_frontend: bool = False,
):
    """The seeded flash-crowd cell (ROADMAP item 2's gate): one tenant
    ``crowd_factor``x's its arrival rate mid-run and capacity must
    FOLLOW the load, not just survive it.

    The controller parks ``spares`` ranks at bind (grow headroom), so
    fair weather runs on the reduced pod. The crowd (middle third of
    the schedule) drives sustained queue pressure + batch-class burn:
    the controller must scale OUT onto a parked rank (hysteresis +
    cooldown mean one bursty tick can never do it); at the crowd's
    midpoint the live blame verdict is offered — a ``wire:rank<r>``
    conviction of the hot tenant's rank turns into a live migration
    (gated loud, named, loss-free when it fires). After the crowd the
    burn windows drain, the cold sustain elapses, and the controller
    must scale back IN — ending with at least ``spares`` ranks
    parked. Throughout: interactive p99 admission wait holds the
    fair-weather cap, interactive is never brownout-shed (the crowd
    cannot break lowest-class-first), every SLO page is backed by
    recorded errors and unlatches once the crowd drains (zero false
    alarms, zero stuck alarms), and the standard zero-corruption /
    zero-lost / zero-stale-leak gates hold.
    """
    from smi_tpu.serving.elasticity import ElasticityController

    if duration < MIN_FLASH_CROWD_DURATION:
        raise ValueError(
            f"flash-crowd cell duration {duration} is below the "
            f"{MIN_FLASH_CROWD_DURATION}-tick minimum: the crowd, the "
            f"burn-window drain, and the scale-in sustain + cooldown "
            f"must all fit inside the schedule"
        )
    if crowd_factor < 2:
        raise ValueError(
            f"crowd_factor={crowd_factor} is not a flash crowd — "
            f"need >= 2 (the hot tenant must actually surge)"
        )
    if not 1 <= spares <= n - 2:
        raise ValueError(
            f"spares={spares} leaves no headroom arc for n={n}: need "
            f"1 <= spares <= n - 2 (park something, keep the floor)"
        )
    ctrl = ElasticityController(spares=spares)
    fe = ServingFrontend(n, seed=seed, pool=pool, elasticity=ctrl,
                         recorder=campaign_recorder(duration, n))
    mean_chunks = (
        sum(CLASS_MIX[c] * CLASS_CHUNKS[c] for c in QOS_CLASSES)
        / sum(CLASS_MIX.values())
    )
    # fair weather is sized to the REDUCED pod the spares leave
    capacity = len(fe.view.members) * fe.consume_rate
    requests_per_tick = 0.7 * capacity / mean_chunks
    schedule = open_loop_traffic(seed, tenants, duration,
                                 requests_per_tick)
    hot = "t0"
    # the crowd must land BEFORE a fair-weather cold sustain can
    # elapse (an error-free pod is always burn-cold, so the
    # controller would otherwise park toward the floor first and
    # spend the crowd inside the actuation cooldown)
    crowd_from = min(duration // 4, ctrl.sustain_in // 2)
    crowd_to = duration // 2
    # offer the blame verdict periodically through the crowd until
    # one lands: the migration is what relieves the hot tenant's
    # rank WHILE the crowd still rages (early offers may find the
    # tail not yet convicting it — keep asking, deterministically)
    blame_from = crowd_from + (crowd_to - crowd_from) // 4
    blame_every = 8
    # the hot tenant's own share of the open-loop rate, surged to
    # crowd_factor x: the extra arrivals ride on top of its base
    extra_rate = (crowd_factor - 1) * requests_per_tick / tenants
    tenant_seq: Dict[str, int] = {}
    submitted = 0
    crowd_submitted = 0
    crowd_acc = 0.0
    blame = {"verdict": None, "offered": False, "error": None}
    verdict = "ok"

    def _submit(tenant: str, qos: str) -> None:
        nonlocal submitted
        submitted += 1
        seq = tenant_seq.get(tenant, 0)
        tenant_seq[tenant] = seq + 1
        chunks = tuple(
            _payload(tenant, seq, c)
            for c in range(CLASS_CHUNKS[qos])
        )
        try:
            fe.submit(tenant, qos, chunks)
        except AdmissionRejected:
            pass  # named + recorded by the gate

    try:
        for tick, burst in enumerate(schedule):
            for tenant, qos in burst:
                _submit(tenant, qos)
            if crowd_from <= tick < crowd_to:
                crowd_acc += extra_rate
                while crowd_acc >= 1.0:
                    crowd_acc -= 1.0
                    crowd_submitted += 1
                    _submit(hot, "batch")
            fe.step()
            if (ctrl.migrations_requested == 0
                    and blame_from <= tick < crowd_to
                    and (tick - blame_from) % blame_every == 0):
                blame = _offer_live_blame(fe, ctrl, hot)
        fe.drain()
        # a quiet coda: the controller keeps stepping on an idle
        # system until the scale-in sustain + cooldown can elapse AND
        # every latched SLO page unlatches. Recovery needs the long
        # burn window to slide past the crowd's error era — an
        # under-populated window reads burn 0.0 ("insufficient
        # evidence"), so idle ticks DO drain it. The bound is
        # generous; an alarm still latched past it is genuinely stuck
        # and the gate below fires.
        coda_bound = (ctrl.sustain_in + ctrl.cooldown
                      + 2 * max(fe.slo.windows) + 64)
        for _ in range(coda_bound):
            if (len(ctrl.parked) >= spares
                    and not any(
                        cls["breached"]
                        for cls in fe.slo.health()["classes"].values()
                    )):
                break
            fe.step()
    except Exception as e:  # a watchdog/assert firing IS the verdict
        verdict = f"{type(e).__name__}: {e}"

    report = fe.report()
    report.update({
        "seed": seed,
        "duration": duration,
        "crowd_window": [crowd_from, crowd_to],
        "crowd_factor": crowd_factor,
        "crowd_submitted": crowd_submitted,
        "hot_tenant": hot,
        "spares": spares,
        "submitted_total": submitted,
        "blame_offer": blame,
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates ----------------------------------------------------------
    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    if report["silent_corruptions"]:
        problems.append(
            f"silent corruption: {report['silent_corruptions']} "
            f"stream(s) delivered wrong bits"
        )
    if report["lost_accepted"]:
        problems.append(
            f"lost accepted: {report['lost_accepted']} admitted "
            f"stream(s) never delivered"
        )
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    if report["max_queue_depth"] > report["queue_bound"]:
        problems.append(
            f"queue occupancy {report['max_queue_depth']} exceeded "
            f"bound {report['queue_bound']}"
        )
    el = report.get("elasticity", {})
    outs = [t for t, d, _r in el.get("events", ()) if d == "out"]
    ins = [t for t, d, _r in el.get("events", ()) if d == "in"]
    if not outs:
        problems.append(
            "the crowd never scaled the pod OUT: sustained pressure "
            "left the spare parked"
        )
    elif outs[0] < crowd_from:
        problems.append(
            f"scale-out at tick {outs[0]} PRECEDES the crowd "
            f"(tick {crowd_from}) — fair weather flapped capacity"
        )
    if not any(outs and t > outs[0] for t in ins):
        problems.append(
            "capacity never followed the load back down: no "
            "scale-in after the crowd's scale-out"
        )
    if len(el.get("parked", ())) < spares:
        problems.append(
            f"ended with {sorted(el.get('parked', ()))} parked — "
            f"capacity did not come back down to headroom"
        )
    for mig in el.get("migrations", ()):
        if mig["state"] != "committed":
            problems.append(
                f"migration of {mig['tenant']!r} ended "
                f"{mig['state']} ({mig.get('abort_reason', '?')})"
            )
        elif not mig["reason"].startswith("blame:wire:rank"):
            problems.append(
                f"migration of {mig['tenant']!r} carries reason "
                f"{mig['reason']!r} — not the blame verdict that "
                f"triggered it"
            )
    # SLO false alarms: a page with zero recorded errors, or one
    # still latched after the crowd drained, is spurious. (A page
    # DURING the crowd backed by real sheds is a true alarm — the
    # signal the controller scales on.)
    health = report["health"]["classes"]
    for qos in sorted(health):
        cls = health[qos]
        if cls["breaches"] and not cls["errors"]:
            problems.append(
                f"{qos} paged with zero recorded errors — an SLO "
                f"false alarm"
            )
        if cls["breached"]:
            problems.append(
                f"{qos} is still paging after the crowd drained — "
                f"a stuck alarm"
            )
    interactive_brownout = sum(
        v for k, v in report["shed"]["interactive"].items()
        if k.startswith("brownout") or k == "admission-timeout"
    )
    if interactive_brownout:
        problems.append(
            f"interactive brownout-shed {interactive_brownout} "
            f"(> 0): the crowd broke lowest-class-first shedding"
        )
    waits = report["admission_waits"]
    report["admission_latency"] = {
        c: {
            "p50": percentile(waits[c], 0.50),
            "p99": percentile(waits[c], 0.99),
        }
        for c in QOS_CLASSES
    }
    p99 = report["admission_latency"]["interactive"]["p99"]
    if p99 is not None and p99 > INTERACTIVE_P99_TICKS:
        problems.append(
            f"interactive p99 admission latency {p99:g} ticks "
            f"exceeds the {INTERACTIVE_P99_TICKS}-tick bound"
        )
    span_fields(fe, report, problems)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


def _distinct_home_tenants(n: int, count: int) -> List[str]:
    """``count`` deterministic tenant names with pairwise-distinct
    crc32 home ranks mod ``n`` — each rank hosts at most one tenant,
    so 'one hot tenant' means exactly one hot RANK (a crc32 collision
    would silently double-load a rank and shed)."""
    from smi_tpu.serving.placement import tenant_base_rank

    names: List[str] = []
    homes: set = set()
    i = 0
    while len(names) < count:
        cand = f"m{i}"
        home = tenant_base_rank(cand, n)
        if home not in homes:
            homes.add(home)
            names.append(cand)
        i += 1
    return names


def _run_migration_traffic(
    n: int,
    seed: int,
    duration: int,
    tenants: int,
    pool: int,
    migrate: bool,
):  # noqa: C901 — one seeded traffic arm, linear
    """One arm of the migration A/B: identical seeded traffic (the
    hot tenant surged until its rank runs just past saturation, so
    the tail concentrates on its wire lane), with or without the
    mid-run blame offer. Returns ``(frontend, blame_audit,
    hot_tenant)``. The controller carries no spares and an
    unreachable cold sustain: this cell isolates MIGRATION — a
    capacity change mid-A/B would let the two arms' admission
    decisions diverge for reasons unrelated to the cutover."""
    from smi_tpu.serving.elasticity import ElasticityController

    names = _distinct_home_tenants(n, tenants)
    remap = {f"t{j}": names[j] for j in range(tenants)}
    hot = names[0]
    ctrl = ElasticityController(spares=0, sustain_in=10 * duration)
    fe = ServingFrontend(n, seed=seed, pool=pool, elasticity=ctrl,
                         recorder=campaign_recorder(duration, n))
    mean_chunks = (
        sum(CLASS_MIX[c] * CLASS_CHUNKS[c] for c in QOS_CLASSES)
        / sum(CLASS_MIX.values())
    )
    capacity = n * fe.consume_rate
    requests_per_tick = 0.35 * capacity / mean_chunks
    schedule = open_loop_traffic(seed, tenants, duration,
                                 requests_per_tick)
    # the hot tenant's rank is driven to a FIXED utilization target,
    # independent of pod size: just past saturation, so the wire
    # queue on its lane builds and the tail verdict convicts
    # ``wire:rank<src>`` at any n. (Sizing the surge as a share of
    # the open-loop rate under-loads big pods — at n=8 the hot rank
    # sat below its consume rate and the verdict degraded to
    # ``consume.wait``, which migration rightly ignores.)
    base_chunks = requests_per_tick * mean_chunks / tenants
    hot_target = 1.15 * fe.consume_rate
    extra_rate = (
        max(0.0, hot_target - base_chunks) / CLASS_CHUNKS["batch"]
    )
    tenant_seq: Dict[str, int] = {}
    acc = 0.0
    blame = {"verdict": None, "offered": False, "error": None}
    migrate_at = duration // 2
    for tick, burst in enumerate(schedule):
        for tenant, qos in burst:
            tenant = remap[tenant]
            seq = tenant_seq.get(tenant, 0)
            tenant_seq[tenant] = seq + 1
            chunks = tuple(
                _payload(tenant, seq, c)
                for c in range(CLASS_CHUNKS[qos])
            )
            try:
                fe.submit(tenant, qos, chunks)
            except AdmissionRejected:
                pass
        acc += extra_rate
        while acc >= 1.0:
            acc -= 1.0
            seq = tenant_seq.get(hot, 0)
            tenant_seq[hot] = seq + 1
            chunks = tuple(
                _payload(hot, seq, c)
                for c in range(CLASS_CHUNKS["batch"])
            )
            try:
                fe.submit(hot, "batch", chunks)
            except AdmissionRejected:
                pass
        fe.step()
        # offer at the first post-midpoint tick where the hot tenant
        # actually has in-flight streams — an empty handoff shard
        # would prove nothing about the cutover
        if (migrate and tick >= migrate_at
                and ctrl.migrations_requested == 0
                and any(st.request.tenant == hot
                        for st in fe.active)):
            blame = _offer_live_blame(fe, ctrl, hot)
    fe.drain()
    return fe, blame, hot


def run_migration_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 200,
    tenants: Optional[int] = None,
    pool: int = DEFAULT_POOL,
    return_frontend: bool = False,
):
    """The zero-loss live-migration cell: the tentpole's bit-identity
    gate, run as an A/B against its own no-migration control.

    Both arms run IDENTICAL seeded traffic with the hot tenant
    surged past its rank's consume rate (so the tail-latency blame
    verdict convicts its wire rank). The subject arm offers the live
    verdict mid-run — the controller must turn ``wire:rank<src>``
    into a migration that drains, hands off (CRC-framed shard), cuts
    over under a bumped epoch (straggler rejected, counted), and
    commits. Gate: every stream BOTH arms completed — including the
    migrated tenant's — carries bit-identical delivered payloads,
    and the arms overlap on at least half their completions (the
    accepted sets may lawfully diverge after the cutover, because
    moving the tenant changes which lane later arrivals queue on).
    Migration moved the tenant; it changed nothing about what was
    delivered."""
    if duration < MIN_CAMPAIGN_DURATION:
        raise ValueError(
            f"migration cell duration {duration} is below the "
            f"{MIN_CAMPAIGN_DURATION}-tick minimum: the hot tenant "
            f"needs in-flight streams at the mid-run offer for the "
            f"handoff to carry anything"
        )
    if tenants is None:
        # one fewer tenant than ranks: load-aware placement leaves a
        # rank free, so the migration has somewhere to go without
        # overloading a resident
        tenants = n - 1
    if not 2 <= tenants < n:
        raise ValueError(
            f"migration cell needs 2 <= tenants < n (a free "
            f"destination rank), got tenants={tenants} n={n}"
        )
    fe, blame, hot = _run_migration_traffic(
        n, seed, duration, tenants, pool, migrate=True)
    control, _, _ = _run_migration_traffic(
        n, seed, duration, tenants, pool, migrate=False)

    report = fe.report()
    digest = _delivery_digest(fe)
    control_digest = _delivery_digest(control)
    common = sorted(set(digest) & set(control_digest))
    divergent = [k for k in common if digest[k] != control_digest[k]]
    control_report = control.report()
    report.update({
        "seed": seed,
        "duration": duration,
        "hot_tenant": hot,
        "blame_offer": blame,
        "digest_streams": len(digest),
        "control_digest_streams": len(control_digest),
        "digest_common": len(common),
        "digest_divergent": len(divergent),
        "digest_match": not divergent,
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates ----------------------------------------------------------
    problems: List[str] = []
    for name, rep in (("subject", report), ("control", control_report)):
        if rep["silent_corruptions"]:
            problems.append(f"{name}: silent corruption")
        if rep["lost_accepted"]:
            problems.append(
                f"{name}: lost accepted: {rep['lost_accepted']}"
            )
        if rep["stale_epoch_leaks"]:
            problems.append(f"{name}: stale-epoch traffic accepted")
    el = report.get("elasticity", {})
    migs = list(el.get("migrations", ()))
    if not blame["offered"]:
        problems.append(
            f"the live blame verdict ({blame['verdict']!r}) did not "
            f"trigger a migration — the hot tenant's rank was never "
            f"convicted as wire-bound"
        )
    elif len(migs) != 1 or migs[0]["state"] != "committed":
        problems.append(
            f"expected exactly one committed migration, got {migs}"
        )
    else:
        mig = migs[0]
        if not mig["reason"].startswith("blame:wire:rank"):
            problems.append(
                f"migration reason {mig['reason']!r} does not carry "
                f"the wire blame verdict"
            )
        if mig["streams"] < 1:
            problems.append(
                "the migration froze zero in-flight streams — the "
                "handoff shard carried nothing (raise the load)"
            )
    if not report["stale_epoch_rejections"]:
        problems.append(
            "post-migration straggler was never presented/rejected"
        )
    if control_report.get("elasticity", {}).get("migrations"):
        problems.append("the control arm migrated — A/B is broken")
    if divergent:
        problems.append(
            f"{len(divergent)} stream(s) delivered different bits "
            f"than the no-migration control (first: {divergent[0]}) "
            f"— migration changed the delivered payloads"
        )
    if len(common) < min(len(digest), len(control_digest)) // 2:
        problems.append(
            f"the A/B arms' completed sets barely overlap "
            f"({len(common)} common of {len(digest)} vs "
            f"{len(control_digest)}) — the bit-identity diff is "
            f"not comparing like work"
        )
    if not any(k[0] == hot for k in common):
        problems.append(
            f"no completed stream of the migrated tenant {hot!r} is "
            f"in both arms — the cutover's delivery was never "
            f"diffed against the control"
        )
    span_fields(fe, report, problems)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


def run_migrate_under_kill_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 200,
    tenants: int = 4,
    pool: int = DEFAULT_POOL,
    stall_at: int = 60,
    migrate_at: int = 70,
    kill_at: int = 90,
    return_frontend: bool = False,
):
    """The migration-abort cell: the source rank DIES mid-drain and
    the migration must abort loudly — never cut over onto state a
    failover already voided.

    The source's consumer is stalled first (so the drain cannot
    finish and the migration is still ``draining`` when the kill
    lands), then the source is killed. Failover confirms the death,
    reroutes and replays the frozen streams through the normal kill
    path, and the migration driver — seeing the source gone — aborts
    with ``membership-change``. Gates: exactly one ABORTED migration
    (named), the kill confirmed, zero lost-accepted (failover's
    replay delivers everything), zero corruption, stragglers
    rejected."""
    from smi_tpu.serving.elasticity import ElasticityController

    if not stall_at < migrate_at < kill_at < duration:
        raise ValueError(
            f"migrate-under-kill needs stall_at < migrate_at < "
            f"kill_at < duration, got {stall_at}/{migrate_at}/"
            f"{kill_at}/{duration}"
        )
    # no spares, unreachable cold sustain: this cell isolates the
    # migration-vs-failover race, not autoscaling
    ctrl = ElasticityController(spares=0, sustain_in=10 * duration)
    fe = ServingFrontend(n, seed=seed, pool=pool, elasticity=ctrl,
                         recorder=campaign_recorder(duration, n))
    mean_chunks = (
        sum(CLASS_MIX[c] * CLASS_CHUNKS[c] for c in QOS_CLASSES)
        / sum(CLASS_MIX.values())
    )
    capacity = n * fe.consume_rate
    requests_per_tick = 0.6 * capacity / mean_chunks
    schedule = open_loop_traffic(seed, tenants, duration,
                                 requests_per_tick)
    hot = "t0"
    tenant_seq: Dict[str, int] = {}
    src = None
    verdict = "ok"
    migration_error = None
    try:
        for tick, burst in enumerate(schedule):
            now = fe.clock.now()
            if tick == stall_at:
                src = fe.placement.base_of(hot)
                if src is None:
                    src = fe._route_new(hot, record=False)
                fe.stall_consumer(src, now + (kill_at - stall_at) * 4)
            if tick == migrate_at:
                others = sorted(
                    r for r in fe.view.members if r != src
                )
                dst = min(others,
                          key=lambda r: (fe._rank_load(r), r))
                try:
                    fe.request_migration(hot, dst, reason="demand")
                except ValueError as e:
                    migration_error = str(e)
            if tick == kill_at:
                fe.kill(src)
            for tenant, qos in burst:
                seq = tenant_seq.get(tenant, 0)
                tenant_seq[tenant] = seq + 1
                chunks = tuple(
                    _payload(tenant, seq, c)
                    for c in range(CLASS_CHUNKS[qos])
                )
                try:
                    fe.submit(tenant, qos, chunks)
                except AdmissionRejected:
                    pass
            fe.step()
        fe.drain()
    except Exception as e:  # a watchdog/assert firing IS the verdict
        verdict = f"{type(e).__name__}: {e}"

    report = fe.report()
    report.update({
        "seed": seed,
        "duration": duration,
        "hot_tenant": hot,
        "src": src,
        "stall_at": stall_at,
        "migrate_at": migrate_at,
        "kill_at": kill_at,
        "migration_error": migration_error,
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates ----------------------------------------------------------
    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    if migration_error is not None:
        problems.append(
            f"migration request failed: {migration_error}"
        )
    if report["silent_corruptions"]:
        problems.append(
            f"silent corruption: {report['silent_corruptions']} "
            f"stream(s) delivered wrong bits"
        )
    if report["lost_accepted"]:
        problems.append(
            f"lost accepted: {report['lost_accepted']} admitted "
            f"stream(s) never delivered"
        )
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    migs = list(report.get("elasticity", {}).get("migrations", ()))
    aborted = [m for m in migs if m["state"] == "aborted"]
    if [m["state"] for m in migs] != ["aborted"]:
        problems.append(
            f"expected exactly one aborted migration, got "
            f"{[m['state'] for m in migs]} — a cutover against a "
            f"dead source would resurrect voided state"
        )
    elif aborted[0]["abort_reason"] != "membership-change":
        problems.append(
            f"abort reason {aborted[0]['abort_reason']!r} — the "
            f"membership change was not what aborted it"
        )
    if report["confirmed"] != [src]:
        problems.append(
            f"kill of rank {src} not confirmed "
            f"(confirmed: {report['confirmed']})"
        )
    if not report["stale_epoch_rejections"]:
        problems.append(
            "straggler from dead incarnation was never "
            "presented/rejected"
        )
    span_fields(fe, report, problems)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


def autoscale_selftest(seed: int = 0) -> Dict:
    """The ``smi-tpu serve --selftest --autoscale`` smoke: the seeded
    flash-crowd cell at its minimum shape — capacity must follow the
    load out AND back in, loss-free."""
    return run_flash_crowd_cell(n=4, seed=seed,
                                duration=MIN_FLASH_CROWD_DURATION)


# -- the r17 partition-tolerance cells ----------------------------------

#: Minimum ticks a cut must stay open: the quorum lease (phi evidence
#: on the ack round trip, ConfirmedDead at a 2x-heartbeat grace) needs
#: several missed beat periods to lapse before the heal arrives.
MIN_PARTITION_WINDOW = 60


def _partition_victim(n: int, tenants: int):
    """The cell's tenant names plus the cut victim: the first tenant
    whose crc32 home is NOT the control-plane home (the lowest rank —
    cutting the sink itself would cut everyone and prove nothing
    about minority fencing). Returns ``(names, victim_tenant,
    victim_rank)``."""
    from smi_tpu.serving.placement import tenant_base_rank

    names = _distinct_home_tenants(n, tenants)
    for name in names:
        home = tenant_base_rank(name, n)
        if home != 0:
            return names, name, home
    raise RuntimeError(  # pragma: no cover — homes are distinct
        "every distinct-home tenant landed on rank 0"
    )


def _run_partition_traffic(
    n: int,
    seed: int,
    duration: int,
    tenants: int,
    pool: int,
    fenced: bool,
    fault_kind: Optional[str],
    partition_at: int,
    window: int,
    flap_seed: Optional[int] = None,
):
    """One arm of the partition A/B: identical seeded traffic, with or
    without a control-plane cut injected at ``partition_at``. Returns
    ``(frontend, victim_tenant, victim_rank, quorum_rejected)`` —
    the last is the count of submits the caller saw refused LOUDLY
    (:class:`~smi_tpu.parallel.membership.QuorumLostError`), which
    must match the front-end's own census."""
    names, victim_tenant, victim = _partition_victim(n, tenants)
    remap = {f"t{j}": names[j] for j in range(tenants)}
    fe = ServingFrontend(n, seed=seed, pool=pool,
                         quorum_fencing=fenced,
                         recorder=campaign_recorder(duration, n))
    mean_chunks = (
        sum(CLASS_MIX[c] * CLASS_CHUNKS[c] for c in QOS_CLASSES)
        / sum(CLASS_MIX.values())
    )
    capacity = n * fe.consume_rate
    requests_per_tick = 0.35 * capacity / mean_chunks
    schedule = open_loop_traffic(seed, tenants, duration,
                                 requests_per_tick)
    tenant_seq: Dict[str, int] = {}
    quorum_rejected = 0
    for tick, burst in enumerate(schedule):
        if fault_kind is not None and tick == partition_at:
            now = fe.clock.now()
            if fault_kind == "partition":
                fault = F.PartitionFault(
                    minority=frozenset({victim}),
                    from_tick=now, until_tick=now + window,
                )
            elif fault_kind == "asymmetric":
                # the victim's OUTBOUND dies; it still hears the
                # majority — exactly the cut one-way beat evidence
                # cannot see, and the round-trip lease must
                fault = F.AsymmetricLinkFault(
                    src=victim, dst=0,
                    from_tick=now, until_tick=now + window,
                )
            else:
                fault = F.FlappingLink(
                    a=0, b=victim,
                    from_tick=now, until_tick=now + window,
                    seed=seed if flap_seed is None else flap_seed,
                )
            fe.inject_partition(fault)
        for tenant, qos in burst:
            tenant = remap[tenant]
            seq = tenant_seq.get(tenant, 0)
            tenant_seq[tenant] = seq + 1
            chunks = tuple(
                _payload(tenant, seq, c)
                for c in range(CLASS_CHUNKS[qos])
            )
            try:
                fe.submit(tenant, qos, chunks)
            except QuorumLostError:
                quorum_rejected += 1  # the loud minority-park refusal
            except AdmissionRejected:
                pass
        fe.step()
    fe.drain()
    return fe, victim_tenant, victim, quorum_rejected


def run_partition_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 240,
    tenants: Optional[int] = None,
    pool: int = DEFAULT_POOL,
    partition_at: int = 60,
    window: int = 100,
    return_frontend: bool = False,
):
    """The clean partition/heal cell: a symmetric cut isolates one
    rank mid-traffic, run as an A/B against its own no-partition
    control.

    The minority rank's quorum lease lapses (phi evidence on the ack
    round trip), it parks, and every new stream homed there is
    refused LOUDLY (``QuorumLostError``, counted — the caller-visible
    count must match the front-end's census). The majority — a
    quorate side — confirms the unreachable rank and fails its
    tenants over under a fenced epoch bump; at the heal the parked
    rank presents its stale epoch once (rejected, counted) and
    rejoins through the real regrow actuator. Gates: zero
    lost-accepted, zero split-brain incidents, zero corruption,
    membership restored to full strength, and every stream BOTH arms
    completed delivered bit-identical to the control."""
    if duration < MIN_CAMPAIGN_DURATION:
        raise ValueError(
            f"partition cell duration {duration} is below the "
            f"{MIN_CAMPAIGN_DURATION}-tick minimum"
        )
    if window < MIN_PARTITION_WINDOW:
        raise ValueError(
            f"partition window {window} is below the "
            f"{MIN_PARTITION_WINDOW}-tick minimum: the quorum lease "
            f"cannot lapse before the heal"
        )
    if duration - (partition_at + window) < 40:
        raise ValueError(
            f"partition cell needs >= 40 post-heal ticks "
            f"(partition_at={partition_at} + window={window} vs "
            f"duration={duration}) for the rejoin to prove itself"
        )
    if tenants is None:
        tenants = max(2, n - 1)
    fe, victim_tenant, victim, quorum_rejected = (
        _run_partition_traffic(n, seed, duration, tenants, pool,
                               fenced=True, fault_kind="partition",
                               partition_at=partition_at,
                               window=window))
    control, _, _, _ = _run_partition_traffic(
        n, seed, duration, tenants, pool,
        fenced=True, fault_kind=None,
        partition_at=partition_at, window=window)

    report = fe.report()
    control_report = control.report()
    digest = _delivery_digest(fe)
    control_digest = _delivery_digest(control)
    common = sorted(set(digest) & set(control_digest))
    divergent = [k for k in common if digest[k] != control_digest[k]]
    report.update({
        "seed": seed,
        "duration": duration,
        "victim_tenant": victim_tenant,
        "victim_rank": victim,
        "partition_at": partition_at,
        "window": window,
        "quorum_rejected_seen": quorum_rejected,
        "digest_streams": len(digest),
        "control_digest_streams": len(control_digest),
        "digest_common": len(common),
        "digest_divergent": len(divergent),
        "digest_match": not divergent,
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates ----------------------------------------------------------
    problems: List[str] = []
    for name, rep in (("subject", report),
                      ("control", control_report)):
        if rep["silent_corruptions"]:
            problems.append(f"{name}: silent corruption")
        if rep["lost_accepted"]:
            problems.append(
                f"{name}: lost accepted: {rep['lost_accepted']}"
            )
        if rep["stale_epoch_leaks"]:
            problems.append(f"{name}: stale-epoch traffic accepted")
    if "partition" in control_report:
        problems.append("the control arm saw a partition — A/B is "
                        "broken")
    part = report.get("partition")
    if part is None:
        problems.append("the subject arm never injected a partition")
    else:
        if part["split_brain_incidents"]:
            problems.append(
                f"split brain: {part['split_brain_incidents']} "
                f"stream(s) accepted by a rank the majority no "
                f"longer trusts"
            )
        if part["quorum_losses"] < 1:
            problems.append(
                "the minority never detected its quorum loss — the "
                "lease did not lapse inside the cut window"
            )
        if part["quorum_rejections"] < 1:
            problems.append(
                "no new stream was refused during the park — the "
                "fencing gate never engaged"
            )
        if part["quorum_rejections"] != quorum_rejected:
            problems.append(
                f"the front-end counted "
                f"{part['quorum_rejections']} quorum rejection(s) "
                f"but the caller saw {quorum_rejected} "
                f"QuorumLostError(s) — refusals are not loud"
            )
        if part["heal_rejoins"] < 1:
            problems.append(
                "the parked rank never rejoined at the heal"
            )
        if part["parked"]:
            problems.append(
                f"rank(s) {part['parked']} still parked after the "
                f"heal"
            )
    if report["members"] != list(range(n)):
        problems.append(
            f"membership not restored after the heal "
            f"(members: {report['members']})"
        )
    if not report["stale_epoch_rejections"]:
        problems.append(
            "the healed rank's stale epoch was never "
            "presented/rejected"
        )
    if divergent:
        problems.append(
            f"{len(divergent)} stream(s) delivered different bits "
            f"than the no-partition control (first: {divergent[0]})"
        )
    if len(common) < min(len(digest), len(control_digest)) // 2:
        problems.append(
            f"the A/B arms' completed sets barely overlap "
            f"({len(common)} common of {len(digest)} vs "
            f"{len(control_digest)})"
        )
    if not any(k[0] == victim_tenant for k in common):
        problems.append(
            f"no completed stream of the victim tenant "
            f"{victim_tenant!r} is in both arms — the cut rank's "
            f"delivery was never diffed against the control"
        )
    waits = report["admission_waits"]
    report["admission_latency"] = {
        c: {
            "p50": percentile(waits[c], 0.50),
            "p99": percentile(waits[c], 0.99),
        }
        for c in QOS_CLASSES
    }
    span_fields(fe, report, problems)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


def run_partition_migration_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 240,
    tenants: int = 4,
    pool: int = DEFAULT_POOL,
    stall_at: int = 50,
    migrate_at: int = 60,
    partition_at: int = 70,
    window: int = 120,
    return_frontend: bool = False,
):
    """The asymmetric-partition-during-migration cell: the migration
    source's OUTBOUND link dies mid-drain (it still hears the
    majority — the one-way cut only round-trip lease evidence can
    see) and the migration must abort loudly, loss-free.

    The source's consumer is stalled first so the drain cannot finish
    before the cut's phi evidence lands (deadline checking is off for
    the same reason: the stall must outlive the confirm grace, and
    the watchdog's own conduct is the backpressure cell's gate, not
    this one's). The majority — quorate — confirms the silent source,
    fails its tenants over through the normal replay path, and the
    migration driver aborts with a NAMED reason. Gates: exactly one
    aborted migration (``membership-change`` or ``quorum-lost``),
    zero lost-accepted, zero split-brain, stragglers rejected, and
    the source rejoined at the heal."""
    from smi_tpu.serving.elasticity import ElasticityController

    if not stall_at < migrate_at < partition_at < duration:
        raise ValueError(
            f"partition-migration cell needs stall_at < migrate_at "
            f"< partition_at < duration, got {stall_at}/{migrate_at}"
            f"/{partition_at}/{duration}"
        )
    if window < MIN_PARTITION_WINDOW:
        raise ValueError(
            f"partition window {window} is below the "
            f"{MIN_PARTITION_WINDOW}-tick minimum"
        )
    names, hot, src = _partition_victim(n, tenants)
    remap = {f"t{j}": names[j] for j in range(tenants)}
    ctrl = ElasticityController(spares=0, sustain_in=10 * duration)
    fe = ServingFrontend(n, seed=seed, pool=pool, elasticity=ctrl,
                         check_deadlines=False,
                         recorder=campaign_recorder(duration, n))
    mean_chunks = (
        sum(CLASS_MIX[c] * CLASS_CHUNKS[c] for c in QOS_CLASSES)
        / sum(CLASS_MIX.values())
    )
    capacity = n * fe.consume_rate
    requests_per_tick = 0.6 * capacity / mean_chunks
    schedule = open_loop_traffic(seed, tenants, duration,
                                 requests_per_tick)
    tenant_seq: Dict[str, int] = {}
    migration_error = None
    verdict = "ok"
    try:
        for tick, burst in enumerate(schedule):
            now = fe.clock.now()
            if tick == stall_at:
                # freeze the source FIRST, then pin a few hot streams
                # on it: the drain must still be open when the cut's
                # phi evidence lands, even on seeds where the
                # open-loop schedule left the hot tenant idle
                fe.stall_consumer(src, now + window + 60)
                for _ in range(3):
                    seq = tenant_seq.get(hot, 0)
                    tenant_seq[hot] = seq + 1
                    chunks = tuple(
                        _payload(hot, seq, c)
                        for c in range(CLASS_CHUNKS["batch"])
                    )
                    try:
                        fe.submit(hot, "batch", chunks)
                    except AdmissionRejected:
                        pass
            if tick == migrate_at:
                others = sorted(
                    r for r in fe.view.members if r != src
                )
                dst = min(others,
                          key=lambda r: (fe._rank_load(r), r))
                try:
                    fe.request_migration(hot, dst, reason="demand")
                except ValueError as e:
                    migration_error = str(e)
            if tick == partition_at:
                fe.inject_partition(F.AsymmetricLinkFault(
                    src=src, dst=0,
                    from_tick=now, until_tick=now + window,
                ))
            for tenant, qos in burst:
                tenant = remap[tenant]
                seq = tenant_seq.get(tenant, 0)
                tenant_seq[tenant] = seq + 1
                chunks = tuple(
                    _payload(tenant, seq, c)
                    for c in range(CLASS_CHUNKS[qos])
                )
                try:
                    fe.submit(tenant, qos, chunks)
                except (AdmissionRejected, QuorumLostError):
                    pass
            fe.step()
        fe.drain()
    except Exception as e:  # a watchdog/assert firing IS the verdict
        verdict = f"{type(e).__name__}: {e}"

    report = fe.report()
    report.update({
        "seed": seed,
        "duration": duration,
        "hot_tenant": hot,
        "src": src,
        "stall_at": stall_at,
        "migrate_at": migrate_at,
        "partition_at": partition_at,
        "window": window,
        "migration_error": migration_error,
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates ----------------------------------------------------------
    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    if migration_error is not None:
        problems.append(
            f"migration request failed: {migration_error}"
        )
    if report["silent_corruptions"]:
        problems.append("silent corruption")
    if report["lost_accepted"]:
        problems.append(
            f"lost accepted: {report['lost_accepted']}"
        )
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    migs = list(report.get("elasticity", {}).get("migrations", ()))
    aborted = [m for m in migs if m["state"] == "aborted"]
    if [m["state"] for m in migs] != ["aborted"]:
        problems.append(
            f"expected exactly one aborted migration, got "
            f"{[m['state'] for m in migs]} — cutting over across a "
            f"partition would resurrect state the failover voided"
        )
    elif aborted[0]["abort_reason"] not in ("membership-change",
                                            "quorum-lost"):
        problems.append(
            f"abort reason {aborted[0]['abort_reason']!r} — neither "
            f"the membership change nor the quorum loss is what "
            f"aborted it"
        )
    part = report.get("partition")
    if part is None:
        problems.append("the asymmetric cut was never injected")
    else:
        if part["split_brain_incidents"]:
            problems.append(
                f"split brain: {part['split_brain_incidents']}"
            )
        if part["heal_rejoins"] < 1:
            problems.append(
                "the cut source never rejoined at the heal"
            )
        if part["parked"]:
            problems.append(
                f"rank(s) {part['parked']} still parked after the "
                f"heal"
            )
    if report["confirmed"] != [src]:
        problems.append(
            f"the silent source {src} was not confirmed "
            f"(confirmed: {report['confirmed']})"
        )
    if report["members"] != list(range(n)):
        problems.append(
            f"membership not restored after the heal "
            f"(members: {report['members']})"
        )
    if not report["stale_epoch_rejections"]:
        problems.append(
            "straggler from the cut incarnation was never "
            "presented/rejected"
        )
    waits = report["admission_waits"]
    report["admission_latency"] = {
        c: {
            "p50": percentile(waits[c], 0.50),
            "p99": percentile(waits[c], 0.99),
        }
        for c in QOS_CLASSES
    }
    span_fields(fe, report, problems)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


#: How many seeded flap vectors the soak may try before declaring the
#: hysteresis broken. The duty cycle's per-window offsets are random:
#: an unlucky vector can blank enough CONSECUTIVE beats that the
#: silence exceeds the lease's confirm grace — and that vector IS a
#: cut (parking on it is the contract), while a too-lucky vector
#: never blocks a beat at all and exercises nothing. The soak's claim
#: is about vectors BETWEEN those: silences long enough to suspect,
#: short enough that the lease must absorb them.
FLAP_VECTOR_ATTEMPTS = 5


def run_flapping_link_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 240,
    tenants: Optional[int] = None,
    pool: int = DEFAULT_POOL,
    flap_at: int = 60,
    window: int = 120,
    return_frontend: bool = False,
):
    """The flapping-link soak: one control link duty-cycles up/down
    for ``window`` ticks and the membership must NOT oscillate.

    A flap whose silences stay under the lease's confirm grace must
    ride suspect/clear cycles WITHOUT ever confirming a death: zero
    confirms, zero parks, zero epoch changes, zero refused streams,
    zero loss. Because the fault's per-window offsets are seeded
    random, the cell searches up to :data:`FLAP_VECTOR_ATTEMPTS`
    vectors for one inside the hysteresis margin — a vector that
    blanks 3+ consecutive beats is indistinguishable from a cut
    (the lease LAPSING there is correct, and the partition cell
    owns that flow), and one that never blocks a beat proves
    nothing. Discarded vectors are reported; loss/corruption/
    split-brain are hard gates on EVERY vector, kept or not. If
    every vector parks, the grace is not absorbing sub-confirm
    silences — that is the failure this cell exists to catch."""
    if duration < MIN_CAMPAIGN_DURATION:
        raise ValueError(
            f"flapping cell duration {duration} is below the "
            f"{MIN_CAMPAIGN_DURATION}-tick minimum"
        )
    if tenants is None:
        tenants = max(2, n - 1)
    problems: List[str] = []
    discarded: List[Dict] = []
    flap_seed = seed
    for attempt in range(FLAP_VECTOR_ATTEMPTS):
        flap_seed = seed * FLAP_VECTOR_ATTEMPTS + attempt
        fe, victim_tenant, victim, quorum_rejected = (
            _run_partition_traffic(n, seed, duration, tenants, pool,
                                   fenced=True,
                                   fault_kind="flapping",
                                   partition_at=flap_at,
                                   window=window,
                                   flap_seed=flap_seed))
        report = fe.report()
        part = report.get("partition") or {}
        # hard invariants bind EVERY vector, kept or discarded: even
        # a cut-equivalent flap may only park and heal, never lose
        if report["silent_corruptions"]:
            problems.append(f"vector {flap_seed}: silent corruption")
        if report["lost_accepted"]:
            problems.append(
                f"vector {flap_seed}: lost accepted: "
                f"{report['lost_accepted']}"
            )
        if report["stale_epoch_leaks"]:
            problems.append(
                f"vector {flap_seed}: stale-epoch traffic accepted"
            )
        if part.get("split_brain_incidents"):
            problems.append(
                f"vector {flap_seed}: split brain: "
                f"{part['split_brain_incidents']}"
            )
        if problems:
            break  # no vector rescues a safety violation
        if report["confirmed"] or part.get("quorum_losses"):
            discarded.append({
                "flap_seed": flap_seed,
                "why": "cut-equivalent silence: the lease lapsed",
            })
            continue
        if not report["suspected"]:
            discarded.append({
                "flap_seed": flap_seed,
                "why": "no beat blocked: suspicion never tripped",
            })
            continue
        break  # a vector inside the hysteresis margin
    else:
        problems.append(
            f"no seeded flap vector stayed inside the hysteresis "
            f"margin in {FLAP_VECTOR_ATTEMPTS} attempts "
            f"({[d['why'] for d in discarded]}) — if every vector "
            f"parked, the confirm grace is not absorbing "
            f"sub-confirm silences"
        )

    report.update({
        "seed": seed,
        "duration": duration,
        "victim_tenant": victim_tenant,
        "victim_rank": victim,
        "flap_at": flap_at,
        "window": window,
        "flap_seed": flap_seed,
        "discarded_vectors": discarded,
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates on the kept vector ---------------------------------------
    if not problems:
        part = report.get("partition")
        if part is None:
            problems.append("the flap was never injected")
        else:
            if part["parked"]:
                problems.append(
                    f"rank(s) {part['parked']} left parked by a "
                    f"mere flap"
                )
            if part["quorum_rejections"] or quorum_rejected:
                problems.append(
                    f"{part['quorum_rejections']} stream(s) were "
                    f"refused under a mere flap"
                )
            if not part["healed"]:
                problems.append("the flap window never closed")
        if report["epoch"] != 0:
            problems.append(
                f"the epoch moved to {report['epoch']} under a "
                f"mere flap — an actuator fired"
            )
        if len(report["cleared"]) != len(report["suspected"]):
            problems.append(
                f"{len(report['suspected'])} suspicion(s) but only "
                f"{len(report['cleared'])} cleared — a flap left a "
                f"suspicion standing"
            )
    waits = report["admission_waits"]
    report["admission_latency"] = {
        c: {
            "p50": percentile(waits[c], 0.50),
            "p99": percentile(waits[c], 0.99),
        }
        for c in QOS_CLASSES
    }
    span_fields(fe, report, problems)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


#: The partition campaign's menu, keyed the way cell reports name
#: themselves; ``only=`` narrows the campaign to one entry.
PARTITION_CELLS = (
    ("partition-heal", run_partition_cell),
    ("partition-migration-abort", run_partition_migration_cell),
    ("flapping-link", run_flapping_link_cell),
)


def partition_campaign(
    seed: int = 0,
    n: int = 4,
    duration: int = 240,
    trials: int = 1,
    only: Optional[str] = None,
) -> Dict:
    """The seeded partition-tolerance campaign: one clean
    partition/heal A/B, one asymmetric-cut-during-migration abort,
    and one flapping-link soak per trial (``only=`` narrows to a
    single named cell). Exit gate: every cell ``ok``."""
    if duration < MIN_CAMPAIGN_DURATION:
        raise ValueError(
            f"campaign duration {duration} is below the "
            f"{MIN_CAMPAIGN_DURATION}-tick minimum"
        )
    menu = PARTITION_CELLS
    if only is not None:
        menu = tuple((nm, fn) for nm, fn in menu if nm == only)
        if not menu:
            raise ValueError(
                f"unknown partition cell {only!r}; known: "
                f"{[nm for nm, _ in PARTITION_CELLS]}"
            )
    cells: List[Dict] = []
    for trial in range(trials):
        base = random.Random(
            f"partition:{seed}:{trial}").randrange(1 << 30)
        for name, runner in menu:
            report = runner(n=n, seed=base,
                            duration=max(duration, 240))
            report["cell"] = name
            report["trial"] = trial
            cells.append(report)
    failures = [c for c in cells if not c["ok"]]
    return {
        "seed": seed,
        "n": n,
        "duration": duration,
        "trials": trials,
        "cells": len(cells),
        "outcomes": {
            c["cell"]: ("ok" if c["ok"] else "failed") for c in cells
        },
        "failures": [
            {"cell": c["cell"], "trial": c["trial"],
             "verdict": c["verdict"]}
            for c in failures
        ],
        "silent_corruptions": sum(
            c["silent_corruptions"] for c in cells
        ),
        "lost_accepted": sum(c["lost_accepted"] for c in cells),
        "stale_epoch_leaks": sum(
            c["stale_epoch_leaks"] for c in cells
        ),
        "split_brain_incidents": sum(
            c.get("partition", {}).get("split_brain_incidents", 0)
            for c in cells
        ),
        "reports": cells,
        "ok": not failures,
    }


def partition_selftest(seed: int = 0) -> Dict:
    """The ``smi-tpu serve --selftest --partition`` smoke: the clean
    partition/heal cell at its default shape — park, fence, fail
    over, heal, rejoin, bit-identical to the no-partition control."""
    return run_partition_cell(n=4, seed=seed, duration=240)


#: Model-checker property -> the campaign gate it instantiates. The
#: model tier (:mod:`smi_tpu.analysis.model`) checks these same gates
#: exhaustively at small scope; a counterexample trace replayed here
#: must fail with the matching campaign verdict — differential
#: soundness in both directions (tests/test_serving.py pins it).
MODEL_GATES = {
    "queue-bound": "queue occupancy exceeded bound",
    "stream-credit": "stream-credit conservation violated",
    "starvation": "ready stream starved past the aging bound",
    "epoch-safety": "stale-epoch traffic accepted",
    "lost-accepted": "lost accepted",
    "plan-epoch-safety": "stale-plan traffic accepted",
    "swap-lost-accepted": "plan swap lost the active plan",
    "migration-lost-accepted": "migration lost delivered state",
    "placement-epoch-safety": "capacity change stranded residents",
    "no-split-brain": "two primaries for one tenant",
    "fenced-actuation": "actuation fired without a quorum",
    "kv-shard-safety": "KV shards stranded off the serving route",
    "generation-lost-accepted": "KV handoff rolled back accepted tokens",
}


def replay_model_trace(scope, trace, mutant: Optional[str] = None) -> Dict:
    """Re-execute a model-checker counterexample as a campaign cell.

    ``scope`` is an :class:`~smi_tpu.analysis.model.Scope`, a scope
    dict (the JSON report's ``scope`` field), or a ``--scope`` spec
    string; ``trace`` the finding's action list (tuples or the JSON
    report's lists); ``mutant`` the control-plane mutant the trace was
    found under (None replays against the clean world). The trace is
    driven through a fresh :class:`~smi_tpu.analysis.model.World` —
    the same real gate/scheduler/membership/WAL objects — and the
    cell's gates are evaluated on the resulting state. A
    counterexample must come back ``ok=False`` with the matching
    :data:`MODEL_GATES` verdict; any trace of a clean world must come
    back ``ok=True``.
    """
    from smi_tpu.analysis import model as M
    from smi_tpu.analysis import model_mutant_world
    from smi_tpu.analysis.properties import check_state, check_terminal

    if isinstance(scope, str):
        scope = M.parse_scope(scope)
    elif isinstance(scope, dict):
        scope = M.Scope(**scope)
    factory = M.World if mutant is None else model_mutant_world(mutant)
    world = factory(scope)
    for action in trace:
        action = tuple(action)
        enabled = world.enabled_actions()
        if action not in enabled:
            raise ValueError(
                f"trace step {action!r} is not enabled in the replayed "
                f"state (enabled: {enabled}) — the trace does not "
                f"belong to this scope/mutant"
            )
        world.apply(action)
    violations = check_state(world)
    if not violations and not world.enabled_actions():
        violations = check_terminal(world)
    report = world.report()
    problems = [
        f"{MODEL_GATES[prop]}: {message}"
        for prop, message in violations
    ]
    report.update({
        "cell": "model-replay",
        "mutant": mutant,
        "trace_steps": len(list(trace)),
        "verdict": "; ".join(problems) if problems else "ok",
        "ok": not problems,
    })
    return report


def serve_selftest(seed: int = 0, return_frontend: bool = False):
    """The ``smi-tpu serve --selftest`` smoke: a deterministic CPU
    admit -> stream -> shed -> drain pass (overload cell at a fast
    shape) whose gates must all hold. Returns the cell report
    (``ok=False`` on any gate failure); ``return_frontend=True``
    returns ``(report, frontend)`` — the ONE selftest shape, shared
    with ``trace --serve`` so the exported trace can never drift from
    the run the selftest gates."""
    return run_load_cell(
        n=4, seed=seed, duration=160, overload=2.0,
        return_frontend=return_frontend,
    )


def bench_fields(seed: int = 0) -> Dict:
    """The additive ``serving`` field for ``bench.py``: a small
    deterministic front-end smoke (pure Python, milliseconds) whose
    offered load, per-class accept/shed counts, and admission-latency
    percentiles ride next to the headline number — the serving regime
    the build would sustain, measured, not asserted."""
    rep = run_load_cell(n=4, seed=seed, duration=120, overload=2.0)
    return {
        "offered_chunks_per_tick": rep["offered_chunks_per_tick"],
        "capacity_chunks_per_tick": rep["capacity_chunks_per_tick"],
        "accepted": rep["accepted"],
        "shed": {c: sum(rep["shed"][c].values())
                 for c in QOS_CLASSES},
        "admission_latency": rep["admission_latency"],
        "ok": rep["ok"],
    }


# -- streaming inference (r20) -------------------------------------------

#: Minimum inference cell duration: the kill/saturation windows below
#: must land while generations are resident, with room for the
#: failover/handoff arc and the delivery drain.
MIN_INFER_DURATION = 80

#: Generation length the chaos cells pin: long enough that the seeded
#: fault always lands mid-generation (the zero-loss window under
#: test), short enough that the cell drains in bounded ticks.
INFER_GEN_LEN = 24


def _run_infer_cell(
    n: int,
    seed: int,
    duration: int,
    tenants: int,
    gen_len: int,
    pool: int,
    hook=None,
    elasticity=None,
    decode_ranks=None,
    arrivals_per_tick: float = 0.12,
):
    """The shared inference-cell chassis: ONE front-end + ONE engine,
    open-loop request arrivals (deterministic per seed), an optional
    per-tick chaos hook, engine drain, and the cell report. Every
    inference cell — including each fault cell's no-fault CONTROL arm
    — runs through this exact loop, so an A/B digest comparison can
    only differ where the fault made it differ."""
    from smi_tpu.serving.inference import InferenceEngine

    fe = ServingFrontend(n, seed=seed, pool=pool,
                         check_deadlines=False,
                         elasticity=elasticity,
                         recorder=campaign_recorder(duration, n))
    eng = InferenceEngine(fe, decode_ranks=decode_ranks, seed=seed)
    rng = random.Random(f"infer-cell:{n}:{seed}")
    verdict = "ok"
    acc = 0.0
    try:
        for tick in range(duration):
            if hook is not None:
                hook(tick, fe, eng)
            acc += arrivals_per_tick
            while acc >= 1.0:
                acc -= 1.0
                tenant = f"t{rng.randrange(tenants)}"
                eng.submit(tenant, "interactive", gen_len=gen_len)
            eng.step()
        eng.drain()
    except Exception as e:  # a watchdog/assert firing IS the verdict
        verdict = f"{type(e).__name__}: {e}"
    report = fe.report()
    report["inference"] = eng.report()
    report["seed"] = seed
    report["duration"] = duration
    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    return fe, eng, report, problems


def _infer_common_gates(report: Dict, problems: List[str]) -> None:
    """The gates every inference cell shares: the front-end's
    zero-corruption/zero-loss invariants plus the engine's
    zero-lost-accepted-TOKENS invariant (the accept-time WAL's
    contract — one rolled-back token anywhere fails the cell)."""
    if report["silent_corruptions"]:
        problems.append("silent corruption")
    if report["lost_accepted"]:
        problems.append(f"lost accepted: {report['lost_accepted']}")
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    inf = report["inference"]
    if inf["lost_accepted_tokens"]:
        problems.append(
            f"generation lost accepted tokens: "
            f"{inf['lost_accepted_tokens']} — the KV handoff rolled "
            f"back an accepted prefix"
        )
    if inf["states"]["generating"] or inf["states"]["kv-transport"]:
        problems.append(
            f"requests stranded mid-lifecycle after drain: "
            f"{inf['states']}"
        )


def _infer_digest_gate(eng, control_digest: Dict,
                       problems: List[str]) -> int:
    """Bit-identity on the intersection: every request BOTH arms
    completed must have delivered the exact same token tuple. Returns
    the intersection size (a zero intersection is its own failure —
    an identity gate over nothing proves nothing)."""
    digest = eng.generation_digest()
    inter = sorted(set(digest) & set(control_digest))
    if not inter:
        problems.append(
            "empty digest intersection with the no-fault control arm "
            "— the bit-identity gate compared nothing"
        )
    diverged = [k for k in inter if digest[k] != control_digest[k]]
    if diverged:
        problems.append(
            f"generation digest diverged from the no-fault control "
            f"on {len(diverged)} request(s) (first: {diverged[0]}) — "
            f"recovery did not resume bit-identically"
        )
    return len(inter)


def run_infer_smoke_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 160,
    tenants: int = 4,
    gen_len: int = INFER_GEN_LEN,
    pool: int = DEFAULT_POOL,
) -> Dict:
    """The no-fault inference cell: disaggregated prefill/decode under
    open-loop arrivals, every request prefilled, transported,
    generated, and delivered — zero handoffs, zero replays, every
    terminal state ``done`` or a loudly-named shed."""
    if duration < MIN_INFER_DURATION:
        raise ValueError(
            f"inference cell duration {duration} is below the "
            f"{MIN_INFER_DURATION}-tick minimum"
        )
    fe, eng, report, problems = _run_infer_cell(
        n, seed, duration, tenants, gen_len, pool)
    _infer_common_gates(report, problems)
    inf = report["inference"]
    if inf["kv_handoffs_committed"] or inf["kv_handoffs_aborted"]:
        problems.append(
            f"no-fault cell minted handoffs: "
            f"{inf['kv_handoffs_committed']} committed / "
            f"{inf['kv_handoffs_aborted']} aborted"
        )
    if inf["replayed_prefills"]:
        problems.append(
            f"no-fault cell replayed {inf['replayed_prefills']} "
            f"prefill(s)"
        )
    if not inf["states"]["done"]:
        problems.append("no request completed")
    report["cell"] = "infer-smoke"
    span_fields(fe, report, problems)
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    return report


def run_infer_kill_decode_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 200,
    tenants: int = 4,
    gen_len: int = INFER_GEN_LEN,
    pool: int = DEFAULT_POOL,
    kill_at: int = 40,
) -> Dict:
    """Kill a decode rank mid-generation. The STATEFUL path, gated
    hard: delivery bit-identical to the no-fault control arm on the
    intersection, zero lost accepted tokens, zero stale-epoch leaks,
    and EXACTLY ONE committed KV handoff whose failover attribution
    names the dead rank — never a prefill replay (the stateless path
    must not fire for a decode death)."""
    if not 0 < kill_at < duration:
        raise ValueError(
            f"kill_at={kill_at} outside 1..{duration - 1}"
        )
    from smi_tpu.serving.inference import decode_ranks_for

    victim = decode_ranks_for(n)[0]
    _, ctl, _ctl_report, ctl_problems = _run_infer_cell(
        n, seed, duration, tenants, gen_len, pool)

    def hook(tick, fe, eng):
        if tick == kill_at:
            fe.kill(victim)

    fe, eng, report, problems = _run_infer_cell(
        n, seed, duration, tenants, gen_len, pool, hook=hook)
    problems.extend(
        f"control arm: {p}" for p in ctl_problems
    )
    _infer_common_gates(report, problems)
    inter = _infer_digest_gate(eng, ctl.generation_digest(), problems)
    inf = report["inference"]
    committed = [h for h in inf["handoffs"]
                 if h["state"] == "committed"]
    if len(committed) != 1:
        problems.append(
            f"expected exactly one committed KV handoff, got "
            f"{[(h['kind'], h['reason']) for h in committed]}"
        )
    elif committed[0]["kind"] != "failover" or (
            committed[0]["reason"] != f"failover:rank{victim}"):
        problems.append(
            f"the committed handoff does not attribute the dead "
            f"decode rank: kind={committed[0]['kind']!r} "
            f"reason={committed[0]['reason']!r}"
        )
    if inf["replayed_prefills"]:
        problems.append(
            f"a decode death triggered {inf['replayed_prefills']} "
            f"prefill replay(s) — the stateless path fired for the "
            f"stateful failure"
        )
    if report["confirmed"] != [victim]:
        problems.append(
            f"the dead decode rank was not confirmed "
            f"(confirmed: {report['confirmed']})"
        )
    report.update({
        "cell": "infer-kill-decode", "victim": victim,
        "kill_at": kill_at, "digest_intersection": inter,
    })
    span_fields(fe, report, problems)
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    return report


def run_infer_kill_prefill_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 200,
    tenants: int = 4,
    gen_len: int = INFER_GEN_LEN,
    pool: int = DEFAULT_POOL,
    kill_at: int = 40,
    arrivals_per_tick: float = 0.08,
) -> Dict:
    """Kill a prefill rank mid-prompt. The STATELESS path, gated to
    stay stateless: prompts in flight on the dead rank re-prefill
    from the WAL'd request on a survivor (>= 1 replay), ZERO KV
    handoffs are minted (a prefill death moves no residency), and
    delivery stays bit-identical to the no-fault control. Arrivals
    run BELOW the half-prefill-capacity knee: the cell proves the
    replay path, so the post-kill queue spike must never dress the
    stateless failure up as decode backpressure (a blame handoff
    here would be exactly the path confusion the gate forbids)."""
    if not 0 < kill_at < duration:
        raise ValueError(
            f"kill_at={kill_at} outside 1..{duration - 1}"
        )
    _, ctl, _ctl_report, ctl_problems = _run_infer_cell(
        n, seed, duration, tenants, gen_len, pool,
        arrivals_per_tick=arrivals_per_tick)

    state = {"victim": None}

    def hook(tick, fe, eng):
        if tick == kill_at:
            # kill the prefill rank with prompts IN FLIGHT (falling
            # back to the first prefill rank keeps the cell
            # deterministic when no prompt is mid-prefill this tick)
            busy = [r.prefill_rank for r in eng.requests
                    if r.state == "prefill"]
            state["victim"] = (busy[0] if busy
                               else eng.prefill_ranks[0])
            fe.kill(state["victim"])

    fe, eng, report, problems = _run_infer_cell(
        n, seed, duration, tenants, gen_len, pool, hook=hook,
        arrivals_per_tick=arrivals_per_tick)
    problems.extend(
        f"control arm: {p}" for p in ctl_problems
    )
    _infer_common_gates(report, problems)
    inter = _infer_digest_gate(eng, ctl.generation_digest(), problems)
    inf = report["inference"]
    if inf["replayed_prefills"] < 1:
        problems.append(
            "the dead prefill rank's prompts were never replayed"
        )
    # the path-confusion gate, precisely: the DEATH must recover by
    # replay alone — no failover-kind handoff anywhere (only a death
    # can mint one, and the only death here is the prefill rank's),
    # and no handoff of any kind naming or touching the dead rank
    # (a prefill rank holds no residency to move). An unrelated
    # blame handoff between two busy decode ranks is the engine
    # doing its job under load, not a confused recovery.
    victim = state["victim"]
    confused = [
        h for h in inf["handoffs"]
        if h["kind"] == "failover"
        or f"rank{victim}" in h["reason"]
        or victim in (h["src"], h["dst"])
    ]
    if confused:
        problems.append(
            f"a prefill death minted KV handoffs: "
            f"{[(h['kind'], h['reason'], h['state']) for h in confused]}"
            f" — the stateful path fired for the stateless failure"
        )
    if report["confirmed"] != [state["victim"]]:
        problems.append(
            f"the dead prefill rank was not confirmed "
            f"(confirmed: {report['confirmed']})"
        )
    report.update({
        "cell": "infer-kill-prefill", "victim": state["victim"],
        "kill_at": kill_at, "digest_intersection": inter,
    })
    span_fields(fe, report, problems)
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    return report


def run_infer_saturate_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 320,
    tenants: int = 4,
    gen_len: int = 40,
    pool: int = DEFAULT_POOL,
    stall_at: int = 30,
    stall_ticks: int = 60,
    flood_ticks: int = 50,
) -> Dict:
    """Saturate a decode rank (stalled consumer + a noisy co-tenant
    flooding its lane): the named ``backpressure:rank<r>`` blame
    verdict must trigger the KV handoff arc — draining, handoff,
    cutover, committed — moving the resident generations to the
    least-loaded surviving decode rank, with ZERO membership events
    (saturation is not death) and zero lost tokens."""
    from smi_tpu.serving.inference import decode_ranks_for

    sat = decode_ranks_for(n)[0]
    _, ctl, _ctl_report, ctl_problems = _run_infer_cell(
        n, seed, duration, tenants, gen_len, pool)

    def hook(tick, fe, eng):
        now = fe.clock.now()
        if tick == stall_at:
            fe.stall_consumer(sat, now + stall_ticks)
        if stall_at <= tick < stall_at + flood_ticks:
            try:
                fe.submit(
                    "noisy", "batch",
                    tuple(f"noise/{tick}/{c}" for c in range(4)),
                    base_rank=sat,
                )
            except (AdmissionRejected, QuorumLostError):
                pass

    fe, eng, report, problems = _run_infer_cell(
        n, seed, duration, tenants, gen_len, pool, hook=hook)
    problems.extend(
        f"control arm: {p}" for p in ctl_problems
    )
    _infer_common_gates(report, problems)
    inter = _infer_digest_gate(eng, ctl.generation_digest(), problems)
    inf = report["inference"]
    blame = f"backpressure:rank{sat}"
    if not any(b["reason"] == blame for b in inf["blame_triggers"]):
        problems.append(
            f"the saturated decode rank never drew the named "
            f"{blame!r} blame verdict"
        )
    committed = [h for h in inf["handoffs"]
                 if h["state"] == "committed"]
    if not committed:
        problems.append("saturation never committed a KV handoff")
    elif committed[0]["kind"] != "handoff" or (
            committed[0]["reason"] != f"blame:{blame}"):
        problems.append(
            f"the first handoff was not blame-triggered off the "
            f"saturated rank: kind={committed[0]['kind']!r} "
            f"reason={committed[0]['reason']!r}"
        )
    confused = [h for h in committed if h["kind"] != "handoff"
                or not h["reason"].startswith("blame:")]
    if confused:
        problems.append(
            f"non-blame handoff(s) under pure saturation: "
            f"{[(h['kind'], h['reason']) for h in confused]} — "
            f"saturation took the failover path"
        )
    if report["confirmed"]:
        problems.append(
            f"saturation confirmed a death: {report['confirmed']} — "
            f"the handoff must ride the blame verdict, never a "
            f"membership event"
        )
    report.update({
        "cell": "infer-saturate", "saturated": sat,
        "stall_at": stall_at, "digest_intersection": inter,
    })
    span_fields(fe, report, problems)
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    return report


def run_infer_partition_handoff_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 420,
    tenants: int = 4,
    gen_len: int = INFER_GEN_LEN,
    pool: int = DEFAULT_POOL,
    stall_at: int = 30,
    partition_at: int = 90,
    window: int = 120,
    pinned: int = 2,
    pinned_gen_len: int = 180,
    arrivals_per_tick: float = 0.02,
) -> Dict:
    """An asymmetric cut lands on the handoff arc's SOURCE while the
    arc is still draining (its wire held open by the stall): the arc
    must abort LOUDLY — ``membership-change`` or ``quorum-lost``,
    never a cutover across the partition — while the confirm-driven
    failover path moves the resident generations loss-free, the cut
    rank rejoins at the heal, and delivery stays bit-identical to the
    no-fault control. Zero split-brain, zero parked ranks after.

    Load shape matters here: ``pinned`` LONG generations are placed
    on the arc's source (fault arm only — they never enter the A/B
    intersection) so real residents span the confirm, while the
    open-loop background stays far below a single decode rank's
    ceiling — the survivor absorbs the whole pod during the stall
    without drawing its own blame verdict, which would smuggle a
    second, committed handoff into the window the gate must keep
    abort-only."""
    if not stall_at < partition_at < duration:
        raise ValueError(
            f"partition cell needs stall_at < partition_at < "
            f"duration, got {stall_at}/{partition_at}/{duration}"
        )
    if window < MIN_PARTITION_WINDOW:
        raise ValueError(
            f"partition window {window} is below the "
            f"{MIN_PARTITION_WINDOW}-tick minimum"
        )
    from smi_tpu.serving.inference import decode_ranks_for

    sat = decode_ranks_for(n)[0]
    _, ctl, _ctl_report, ctl_problems = _run_infer_cell(
        n, seed, duration, tenants, gen_len, pool,
        arrivals_per_tick=arrivals_per_tick)

    def hook(tick, fe, eng):
        now = fe.clock.now()
        if tick == 1:
            # the residents the failover must move: long generations
            # pinned to the arc's source, still mid-stream when the
            # confirm lands (fault arm only, so the digest gate
            # compares the shared open-loop traffic, not these)
            for _ in range(pinned):
                eng.submit("pin", "interactive",
                           gen_len=pinned_gen_len, decode_rank=sat)
        if tick == stall_at:
            # hold the arc's drain open past the cut: frames parked
            # on the source wire keep the arc in ``draining`` until
            # the confirm aborts it
            fe.stall_consumer(sat, now + window + 90)
        if stall_at <= tick < stall_at + 50:
            try:
                fe.submit(
                    "noisy", "batch",
                    tuple(f"noise/{tick}/{c}" for c in range(4)),
                    base_rank=sat,
                )
            except (AdmissionRejected, QuorumLostError):
                pass
        if tick == partition_at:
            fe.inject_partition(F.AsymmetricLinkFault(
                src=sat, dst=0,
                from_tick=now, until_tick=now + window,
            ))

    fe, eng, report, problems = _run_infer_cell(
        n, seed, duration, tenants, gen_len, pool, hook=hook,
        arrivals_per_tick=arrivals_per_tick)
    problems.extend(
        f"control arm: {p}" for p in ctl_problems
    )
    _infer_common_gates(report, problems)
    inter = _infer_digest_gate(eng, ctl.generation_digest(), problems)
    inf = report["inference"]
    aborted = [h for h in inf["handoffs"]
               if h["kind"] == "handoff" and h["state"] == "aborted"]
    blame_committed = [
        h for h in inf["handoffs"]
        if h["kind"] == "handoff" and h["state"] == "committed"
    ]
    if len(aborted) != 1:
        problems.append(
            f"expected exactly one aborted KV handoff, got "
            f"{[(h['state'], h.get('abort_reason')) for h in inf['handoffs'] if h['kind'] == 'handoff']} "
            f"— cutting over across a partition would resurrect "
            f"state the failover moved"
        )
    elif aborted[0]["abort_reason"] not in ("membership-change",
                                            "quorum-lost"):
        problems.append(
            f"abort reason {aborted[0]['abort_reason']!r} — neither "
            f"the membership change nor the quorum loss aborted it"
        )
    if blame_committed:
        problems.append(
            f"a blame handoff committed across the partition window: "
            f"{[(h['reason']) for h in blame_committed]}"
        )
    failed_over = [h for h in inf["handoffs"]
                   if h["kind"] == "failover"
                   and h["state"] == "committed"]
    if not any(h["reason"] == f"failover:rank{sat}"
               for h in failed_over):
        problems.append(
            f"the cut rank's resident generations were never failed "
            f"over at the confirm (failovers: "
            f"{[(h['reason'], h['state']) for h in failed_over]})"
        )
    part = report.get("partition")
    if part is None:
        problems.append("the asymmetric cut was never injected")
    else:
        if part["split_brain_incidents"]:
            problems.append(
                f"split brain: {part['split_brain_incidents']}"
            )
        if part["heal_rejoins"] < 1:
            problems.append(
                "the cut decode rank never rejoined at the heal"
            )
        if part["parked"]:
            problems.append(
                f"rank(s) {part['parked']} still parked after the "
                f"heal"
            )
    if report["members"] != list(range(n)):
        problems.append(
            f"membership not restored after the heal "
            f"(members: {report['members']})"
        )
    report.update({
        "cell": "infer-partition-handoff", "saturated": sat,
        "partition_at": partition_at, "window": window,
        "digest_intersection": inter,
    })
    span_fields(fe, report, problems)
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    return report


def run_infer_scale_in_cell(
    n: int = 5,
    seed: int = 0,
    duration: int = 200,
    gen_len: int = 160,
    pool: int = DEFAULT_POOL,
) -> Dict:
    """Scale-in during generation: the elasticity controller's cold
    signal wants ranks back, but a decode rank holding RESIDENT KV
    shards must never be the victim (its transport streams all
    completed — the active-stream census is blind to the residency;
    the controller reads the engine's published inventory instead).
    Gate: at least one scale-in actually happens (the discipline is
    exercised, not vacuous) and no scaled-in rank ever held
    residents."""
    from smi_tpu.serving.elasticity import ElasticityController
    from smi_tpu.serving.inference import InferenceEngine

    # min_ranks = n - 1 caps the cold signal at ONE scale-in: the cell
    # proves victim selection, and a second eviction on this little
    # ring would cut decode routes for reasons that have nothing to
    # do with residency
    ctrl = ElasticityController(spares=0, sustain_in=30,
                                min_ranks=n - 1)
    fe = ServingFrontend(n, seed=seed, pool=pool,
                         check_deadlines=False,
                         elasticity=ctrl,
                         recorder=campaign_recorder(duration, n))
    # decode on the two HIGHEST ranks — exactly the ranks the scale-in
    # victim scan prefers — so only the inventory read can save them
    eng = InferenceEngine(fe, decode_ranks=(n - 2, n - 1), seed=seed)
    verdict = "ok"
    resident_scale_ins: List[Tuple[int, int]] = []
    try:
        # one long generation RESIDENT on each decode rank — pinned,
        # because a least-loaded pick can double up on one rank and
        # leave the other a legitimate (empty-inventory) victim
        for tenant, rank in (("t0", n - 2), ("t1", n - 1)):
            eng.submit(tenant, "interactive", gen_len=gen_len,
                       decode_rank=rank)
        for _tick in range(duration):
            eng.step()
            for when, direction, rank in ctrl.scale_events:
                if (direction == "in"
                        and eng.residents.get(rank)
                        and (when, rank) not in resident_scale_ins):
                    resident_scale_ins.append((when, rank))
        eng.drain()
    except Exception as e:
        verdict = f"{type(e).__name__}: {e}"
    report = fe.report()
    report["inference"] = eng.report()
    report["seed"] = seed
    report["duration"] = duration
    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    _infer_common_gates(report, problems)
    scale_ins = [e for e in ctrl.scale_events if e[1] == "in"]
    if not scale_ins:
        problems.append(
            "the cold signal never scaled in — the victim "
            "discipline was not exercised"
        )
    if resident_scale_ins:
        problems.append(
            f"scale-in took rank(s) holding resident KV shards: "
            f"{resident_scale_ins}"
        )
    victims = {r for _, d, r in ctrl.scale_events if d == "in"}
    if victims & set(eng.decode_ranks):
        problems.append(
            f"scale-in took decode rank(s) {sorted(victims & set(eng.decode_ranks))} "
            f"while their generations were resident"
        )
    if not report["inference"]["states"]["done"]:
        problems.append("no generation completed")
    report.update({
        "cell": "infer-scale-in",
        "scale_ins": [list(e) for e in scale_ins],
    })
    span_fields(fe, report, problems)
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    return report


INFER_CELLS = (
    ("infer-smoke", run_infer_smoke_cell),
    ("infer-kill-decode", run_infer_kill_decode_cell),
    ("infer-kill-prefill", run_infer_kill_prefill_cell),
    ("infer-saturate", run_infer_saturate_cell),
    ("infer-partition-handoff", run_infer_partition_handoff_cell),
    ("infer-scale-in", run_infer_scale_in_cell),
)


def infer_campaign(
    seed: int = 0,
    n: int = 4,
    duration: int = 200,
    trials: int = 1,
    only: Optional[str] = None,
) -> Dict:
    """The seeded streaming-inference campaign: the no-fault smoke,
    both kill cells (decode = stateful handoff, prefill = stateless
    replay), the saturation blame handoff, the partition-during-
    handoff abort, and the scale-in victim discipline, per trial
    (``only=`` narrows to a single named cell). Exit gate: every cell
    ``ok``."""
    if duration < MIN_INFER_DURATION:
        raise ValueError(
            f"campaign duration {duration} is below the "
            f"{MIN_INFER_DURATION}-tick minimum"
        )
    menu = INFER_CELLS
    if only is not None:
        menu = tuple((nm, fn) for nm, fn in menu if nm == only)
        if not menu:
            raise ValueError(
                f"unknown inference cell {only!r}; known: "
                f"{[nm for nm, _ in INFER_CELLS]}"
            )
    cells: List[Dict] = []
    for trial in range(trials):
        base = random.Random(
            f"infer:{seed}:{trial}").randrange(1 << 30)
        for name, runner in menu:
            kwargs = {"n": n, "seed": base}
            if name == "infer-scale-in":
                # the victim scan needs a spare-able pod: one more
                # rank than the smallest disaggregated shape
                kwargs["n"] = max(n + 1, 5)
            elif name == "infer-saturate":
                kwargs["duration"] = max(duration, 320)
            elif name == "infer-partition-handoff":
                kwargs["duration"] = max(duration, 420)
            else:
                kwargs["duration"] = max(duration,
                                         MIN_INFER_DURATION)
            report = runner(**kwargs)
            report["cell"] = name
            report["trial"] = trial
            cells.append(report)
    failures = [c for c in cells if not c["ok"]]
    return {
        "seed": seed,
        "n": n,
        "duration": duration,
        "trials": trials,
        "cells": len(cells),
        "outcomes": {
            c["cell"]: ("ok" if c["ok"] else "failed") for c in cells
        },
        "failures": [
            {"cell": c["cell"], "trial": c["trial"],
             "verdict": c["verdict"]}
            for c in failures
        ],
        "silent_corruptions": sum(
            c["silent_corruptions"] for c in cells
        ),
        "lost_accepted": sum(c["lost_accepted"] for c in cells),
        "lost_accepted_tokens": sum(
            c["inference"]["lost_accepted_tokens"] for c in cells
        ),
        "stale_epoch_leaks": sum(
            c["stale_epoch_leaks"] for c in cells
        ),
        "kv_handoffs_committed": sum(
            c["inference"]["kv_handoffs_committed"] for c in cells
        ),
        "replayed_prefills": sum(
            c["inference"]["replayed_prefills"] for c in cells
        ),
        "reports": cells,
        "ok": not failures,
    }


def infer_selftest(seed: int = 0) -> Dict:
    """The ``smi-tpu serve --selftest --infer`` smoke: the kill-decode
    cell at its default shape — prefill, transport, generate, kill,
    fail over through the KV handoff, deliver bit-identically."""
    return run_infer_kill_decode_cell(n=4, seed=seed, duration=200)


def inference_fields(seed: int = 0) -> Dict:
    """The additive ``inference`` field for ``bench.py``: a small
    deterministic disaggregated-serving smoke whose prefill/decode
    rates, handoff counts, and interactive TTFT p99 ride next to the
    headline number — the streaming-inference regime the build would
    sustain, measured, not asserted."""
    rep = run_infer_smoke_cell(n=4, seed=seed, duration=160)
    inf = rep["inference"]
    ttft = inf["ttft"]
    duration = rep["duration"]
    return {
        "requests": inf["requests"],
        "done": inf["states"]["done"],
        "prefill_chunks_per_tick": round(
            sum(rep["delivered"].values()) / max(duration, 1), 4
        ),
        "tokens_per_tick": round(
            inf["tokens_emitted"] / max(duration, 1), 4
        ),
        "kv_handoffs_committed": inf["kv_handoffs_committed"],
        "kv_handoffs_aborted": inf["kv_handoffs_aborted"],
        "replayed_prefills": inf["replayed_prefills"],
        "lost_accepted_tokens": inf["lost_accepted_tokens"],
        "ttft_p99": percentile(ttft, 0.99),
        "ok": rep["ok"],
    }
