"""Communication microbenchmarks.

Reference parity: ``microbenchmarks/`` — bandwidth, latency, injection
rate, the four collectives, multi-collective overlap, and the rank
pipeline (``microbenchmarks/CMakeLists.txt:8-27``). Each reference host
follows one pattern: parse args → init → timed kernel runs → mean/stddev/
99% CI → ``.dat`` file (``host/bandwidth_benchmark.cpp``); this package
keeps the pattern and the metric formulas (SURVEY §6) on the JAX data
plane.

Run ``python -m smi_tpu.benchmarks <name>`` (see ``--help``). On the CPU
fake mesh the numbers exercise the full code path (the reference's
emulator benchmarks are equally not performance-meaningful); on real
multi-chip hardware the same code measures ICI.
"""

from smi_tpu.benchmarks.micro import BENCHMARKS, run_benchmark

__all__ = ["BENCHMARKS", "run_benchmark"]
