"""User-facing kernel context: the TPU analog of ``include/smi.h``.

A reference SMI kernel receives an ``SMI_Comm`` and calls the channel API;
here a user function decorated with :func:`smi_kernel` runs per-shard under
``jax.shard_map`` and receives an :class:`SmiContext` exposing the same
surface: rank/size, open+push/pop channels, and rooted collectives.

Example (the bandwidth microbenchmark's shape,
``microbenchmarks/kernels/bandwidth_0.cl:11-33``)::

    comm = smi.make_communicator(8)

    @smi.smi_kernel(comm, in_specs=P("smi"), out_specs=P("smi"))
    def app(ctx, x):
        ch = ctx.open_channel(port=0, src=0, dst=1, count=N, dtype="float")
        received = ctx.transfer(ch, x)       # Push at src, Pop at dst
        return jnp.where(ctx.rank() == 1, received, x)

MPMD under SPMD: the reference runs different bitstreams per rank
(``bandwidth.json``'s program map); here rank divergence is expressed with
``jnp.where``/``lax.cond`` on ``ctx.rank()`` inside one SPMD program — the
collectives themselves are traced unconditionally by every rank, which is
what makes them legal under SPMD.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from smi_tpu.ops.program import Program
from smi_tpu.ops.types import SmiDtype, SmiOp
from smi_tpu.parallel import collectives as _coll
from smi_tpu.parallel.channels import P2PChannel, ring_shift
from smi_tpu.parallel.mesh import Communicator
from smi_tpu.utils.watchdog import Deadline


@dataclasses.dataclass(frozen=True)
class SmiContext:
    """Per-shard handle passed to smi kernels.

    Carries the communicator and optionally the validated program metadata
    (port allocation, rendezvous flag — ``codegen/program.py``); channel
    opens consult the program when present so tuning knobs declared in
    program JSON apply without repeating them at call sites.
    """

    comm: Communicator
    program: Optional[Program] = None
    #: Default collective implementation tier: ``"xla"`` (XLA collectives)
    #: or ``"ring"`` (explicit credit-controlled neighbour RDMA,
    #: :mod:`smi_tpu.kernels.ring`).
    backend: str = "xla"
    #: Watchdog deadline applied to every channel transfer/stream and
    #: every ring-tier collective dispatched through this context: an
    #: expired deadline raises ``WatchdogTimeout`` with the protocol's
    #: per-rank state mirror instead of hanging the job. The checks are
    #: host-side (dispatch/trace time — compiled re-executions are not
    #: re-checked); hard-bound blocking execution with
    #: ``watchdog.run_with_deadline`` (:mod:`smi_tpu.utils.watchdog`).
    deadline: Optional[Deadline] = None

    # -- communicator (include/smi/communicator.h) ---------------------
    def rank(self) -> jax.Array:
        return self.comm.rank()

    @property
    def size(self) -> int:
        return self.comm.size

    # -- P2P channels (include/smi/{push,pop}.h) ------------------------
    def open_channel(
        self,
        port: int,
        src: int,
        dst: int,
        count: int,
        dtype: Union[str, SmiDtype] = "float",
        buffer_size: Optional[int] = None,
    ) -> P2PChannel:
        """Open a transient P2P channel (both endpoints' open in one).

        Replaces the ``SMI_Open_send_channel``/``SMI_Open_receive_channel``
        pair (``push.h:19-48``/``pop.h:20-39``): under SPMD a single
        descriptor serves both ends. ``buffer_size`` is the asynchronicity
        degree (``_ad`` variants) in elements.
        """
        kwargs = {}
        if self.program is not None:
            # program-declared tuning knobs override the dataclass defaults
            kwargs["rendezvous"] = self.program.p2p_rendezvous
            kwargs["consecutive_reads"] = self.program.consecutive_reads
            declared = self.program.find("push", port) or self.program.find("pop", port)
            if declared is not None and buffer_size is None:
                buffer_size = declared.buffer_size
        return P2PChannel(
            comm=self.comm,
            port=port,
            src=src,
            dst=dst,
            count=count,
            dtype=dtype,
            buffer_size=buffer_size,
            **kwargs,
        )

    def transfer(self, channel: P2PChannel, data: jax.Array,
                 backend: Optional[str] = None) -> jax.Array:
        """Fused Push(all elements)+Pop: message at dst, zeros elsewhere."""
        return channel.transfer(data, backend=self._backend(backend),
                                deadline=self.deadline)

    def stream(self, channel: P2PChannel, data: jax.Array,
               consumer: Optional[Callable] = None, init_carry=None,
               backend: Optional[str] = None):
        """Chunked streaming transfer with optional per-chunk consumer."""
        return channel.stream(data, consumer=consumer, init_carry=init_carry,
                              backend=self._backend(backend),
                              deadline=self.deadline)

    def stream_reduce(self, channel: P2PChannel, data: jax.Array,
                      op="add", lanes: Optional[int] = None,
                      backend: Optional[str] = None):
        """Streamed reduction with ``lanes`` partial accumulators
        (``Reduce.accumulation_lanes`` by default)."""
        return channel.stream_reduce(data, op=op, lanes=lanes,
                                     backend=self._backend(backend),
                                     deadline=self.deadline)

    def ring_shift(self, x: jax.Array, offset: int = 1,
                   axis_name: Optional[str] = None) -> jax.Array:
        return ring_shift(x, self.comm, offset=offset, axis_name=axis_name)

    # -- collectives (include/smi/{bcast,reduce,scatter,gather}.h) -----
    # ``backend=None`` inherits the context default (``smi_kernel(...,
    # backend=...)``), letting one program switch wholesale between the
    # XLA tier and the explicit credit-controlled ring tier.
    def _backend(self, backend: Optional[str]) -> str:
        from smi_tpu.parallel.backend import check_backend

        return self.backend if backend is None else check_backend(backend)

    # ``chunks`` is the per-call asynchronicity degree: >1 splits the
    # payload into a software pipeline of independent per-chunk
    # collectives (bit-identical reassembly; see parallel/collectives).
    # The default ``None`` consults the plan engine (smi_tpu.tuning):
    # measured cache entry, else one collective — today's behavior.
    def bcast(self, x, root: int = 0, port: Optional[int] = None,
              backend: Optional[str] = None, chunks: Optional[int] = None,
              hierarchical: Optional[bool] = None):
        return _coll.bcast(x, self.comm, root=root, port=port,
                           backend=self._backend(backend),
                           program=self.program, deadline=self.deadline,
                           chunks=chunks, hierarchical=hierarchical)

    def reduce(self, x, op: Union[str, SmiOp] = SmiOp.ADD, root: int = 0,
               port: Optional[int] = None, all_ranks: bool = False,
               backend: Optional[str] = None, chunks: Optional[int] = None,
               hierarchical: Optional[bool] = None):
        return _coll.reduce(x, self.comm, op=op, root=root, port=port,
                            all_ranks=all_ranks,
                            backend=self._backend(backend),
                            program=self.program, deadline=self.deadline,
                            chunks=chunks, hierarchical=hierarchical)

    def allreduce(self, x, op: Union[str, SmiOp] = SmiOp.ADD,
                  backend: Optional[str] = None,
                  chunks: Optional[int] = None,
                  rs_ag: Optional[bool] = None,
                  hierarchical: Optional[bool] = None,
                  precision: Optional[str] = None):
        return _coll.allreduce(x, self.comm, op=op,
                               backend=self._backend(backend),
                               program=self.program,
                               deadline=self.deadline,
                               chunks=chunks, rs_ag=rs_ag,
                               hierarchical=hierarchical,
                               precision=precision)

    def scatter(self, x, root: int = 0, port: Optional[int] = None,
                backend: Optional[str] = None, chunks: Optional[int] = None):
        return _coll.scatter(x, self.comm, root=root, port=port,
                             backend=self._backend(backend),
                             program=self.program, deadline=self.deadline,
                             chunks=chunks)

    def gather(self, x, root: int = 0, port: Optional[int] = None,
               all_ranks: bool = False, backend: Optional[str] = None,
               chunks: Optional[int] = None):
        return _coll.gather(x, self.comm, root=root, port=port,
                            all_ranks=all_ranks,
                            backend=self._backend(backend),
                            program=self.program, deadline=self.deadline,
                            chunks=chunks)

    # ``algorithm`` resolves env -> cache -> model -> pairwise (the
    # fused lax.all_to_all) — see parallel/collectives.all_to_all.
    def all_to_all(self, x, algorithm: Optional[str] = None,
                   port: Optional[int] = None,
                   backend: Optional[str] = None):
        return _coll.all_to_all(x, self.comm, algorithm=algorithm,
                                port=port,
                                backend=self._backend(backend),
                                program=self.program)

    # -- tuning --------------------------------------------------------
    def explain_plan(self, op: str = "all_reduce",
                     dtype: str = "float32") -> str:
        """The plan engine's candidate table for this communicator:
        which knob values a collective dispatched through this context
        would run with, which layer (cache / model / heuristic) decided
        each, and the modeled vs measured costs behind the choice —
        the API twin of ``smi-tpu tune --explain`` (ISSUE 4: every
        silent default is an inspectable decision). On a hybrid
        multi-slice communicator the allreduce table prices all three
        candidates — flat ring, rs+ag, and the two-tier hierarchical
        form — and names the two-tier gate's deciding layer."""
        from smi_tpu.tuning import cost_model as cm
        from smi_tpu.tuning.engine import get_engine

        topo = cm.topology_from_comm(self.comm)
        return get_engine().explain_text(
            op, n=self.size, dtype=dtype,
            slices=topo.outer if topo.hierarchical_eligible else None,
        )

    # -- degraded mode -------------------------------------------------
    def shrink(self, excluded_ranks) -> "SmiContext":
        """Rebuild this context over the healthy-subset mesh.

        ULFM-style shrinking communicator: after a failure is detected
        (watchdog timeout, unroutable cut), the job continues on the
        surviving ranks — see :meth:`Communicator.shrink` for the mesh
        semantics (survivors keep rank order; the shrunk mesh is 1-D).
        The program metadata and backend tier carry over; the deadline
        is NOT carried (a new recovery phase deserves a fresh budget).
        """
        return dataclasses.replace(
            self, comm=self.comm.shrink(excluded_ranks), deadline=None
        )

    # -- MPMD: per-rank divergent local compute ------------------------
    def select(self, branches, operand):
        """Run ``branches[rank]`` on ``operand`` (rank ≥ len: last one).

        The MPMD primitive: the reference runs a different program per
        rank via the routing file's program map
        (``microbenchmarks/kernels/bandwidth.json``); under SPMD the
        divergence is a ``lax.switch`` on the axis index. Branches must
        be *communication-free* — collectives and channel transfers are
        collective operations every rank must execute, so they belong in
        the shared code around the select (see
        ``smi_tpu.ops.program.combined_program`` for merging the
        per-rank programs into the one traced program).
        """
        from jax import lax as _lax

        idx = jnp.clip(self.rank(), 0, len(branches) - 1)
        return _lax.switch(idx, list(branches), operand)


def smi_kernel(
    comm: Communicator,
    in_specs=None,
    out_specs=None,
    program: Optional[Program] = None,
    check_vma: bool = False,
    backend: str = "xla",
    deadline: Optional[Deadline] = None,
):
    """Decorator: run ``fn(ctx, *args)`` per-shard over the communicator.

    The TPU analog of launching an SMI kernel with its communicator arg
    (``templates/host_hlslib.cl:87-89`` hands ``SMI_Comm`` to app kernels).
    ``in_specs``/``out_specs`` are ``PartitionSpec``s as for
    ``jax.shard_map``; defaults replicate. ``deadline`` arms the
    runtime watchdog on every channel/collective the kernel dispatches
    (:mod:`smi_tpu.utils.watchdog`).
    """
    from jax.sharding import PartitionSpec as P

    if in_specs is None:
        in_specs = P()
    if out_specs is None:
        out_specs = P()

    from smi_tpu.parallel.backend import check_backend

    ctx = SmiContext(comm=comm, program=program,
                     backend=check_backend(backend), deadline=deadline)

    def decorator(fn: Callable) -> Callable:
        def shard_fn(*args):
            return fn(ctx, *args)

        mapped = jax.shard_map(
            shard_fn,
            mesh=comm.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
        return jax.jit(mapped)

    return decorator
