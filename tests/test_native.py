"""Native-layer tests: manifest tool goldens + runtime library bindings.

Reference: ``codegen/tests/test_rewriter.py`` drives the compiled Clang
tool as a subprocess over fixture sources and asserts the emitted op
list; here the same tier drives ``smi-manifest``. The runtime tests
round-trip binary routing tables through the C library and cross-check
against the Python routing writer.
"""

import os
import subprocess

import pytest

import smi_tpu as smi
from smi_tpu.ops.operations import Push, Pop, Reduce
from smi_tpu.utils import native

NATIVE = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")

pytestmark = pytest.mark.skipif(
    not (native.native_available() and native.manifest_tool_available()),
    reason="native components not built (run `make -C native`)",
)


# ---------------------------------------------------------------- tool --


def run_manifest(tmp_path, source, extra_args=()):
    src = tmp_path / "prog.py"
    src.write_text(source)
    bin_path = os.path.join(NATIVE, "build", "smi-manifest")
    return subprocess.run(
        [bin_path, *extra_args, str(src)], capture_output=True, text=True
    )


def test_manifest_never_crashes_on_garbage(tmp_path):
    """Robustness fuzz: the scanner must terminate cleanly (no signal,
    no hang) on arbitrary byte soup — truncated sources, pathological
    nesting, stray quotes, NUL-free binary-ish text, unicode. Exit 0
    with a (possibly empty) manifest or nonzero with a diagnostic are
    both fine; dying on a signal or timing out is a bug. The reference
    leans on Clang for this hardening (``source-rewriter``); our
    hand-written lexer has to prove it alone."""
    import random

    rng = random.Random(1234)
    tokens = [
        "open_channel", "ctx.", "port=", "0", "1", "999999999999",
        "(", ")", "[", "]", "{", "}", ":", ",", "=", ".", "@",
        "def ", "class ", "import ", "from ", "smi_tpu", "as ",
        "'", '"', "'''", '"""', "#", "\\", "\n", "\t", "    ",
        "dtype=", '"float"', "lambda", "*", "**", "->", "...",
        "é", "世", "\U0001f600",
    ]
    cases = []
    for i in range(40):
        n = rng.randint(1, 120)
        cases.append("".join(rng.choice(tokens) for _ in range(n)))
    # structured edge cases
    cases += [
        "",                                   # empty file
        "(" * 5000,                           # deep nesting
        "def f(:\n" * 200,                    # malformed defs
        "ctx.open_channel(" ,                 # truncated call
        "from smi_tpu import " ,              # truncated import
        "x = '" ,                             # unterminated string
        '"""' ,                               # unterminated docstring
        "open_channel(port=" + "9" * 1000 + ")",  # huge literal
        "\n".join("import a" for _ in range(5000)),  # many lines
    ]
    # raw byte soup too — truncated multibyte sequences and 0x80-0xFF
    # noise are the likeliest crash class for a hand-written lexer
    byte_cases = [
        bytes([rng.randrange(256) for _ in range(rng.randint(1, 400))])
        for _ in range(10)
    ] + [b"\xff\xfe", b"open_channel(\x80\x81\x82)", b"\xe4\xb8"]
    bin_path = os.path.join(NATIVE, "build", "smi-manifest")
    for i, source in enumerate(cases + byte_cases):
        src = tmp_path / f"fuzz_{i}.py"
        if isinstance(source, bytes):
            src.write_bytes(source)
        else:
            src.write_text(source, encoding="utf-8")
        proc = subprocess.run(
            [bin_path, str(src)], capture_output=True, text=True,
            errors="replace", timeout=10,
        )
        assert proc.returncode >= 0, (
            f"scanner died on signal {-proc.returncode} for case "
            f"{i}: {source[:80]!r}"  # noqa: E501
        )
        if proc.returncode != 0:
            # failures must carry a diagnostic, not die silently
            assert proc.stderr.strip(), (
                f"silent nonzero exit for case {i}: {source[:80]!r}"
            )


def test_manifest_extracts_ops(tmp_path):
    proc = run_manifest(
        tmp_path,
        """
import smi_tpu as smi
ops = [smi.Push(0, "float", 2048), smi.Pop(0, "float", 2048),
       smi.Reduce(2, "double", op="max")]
def app(ctx, x):
    ch = ctx.open_channel(port=1, src=0, dst=3, count=64, dtype="int")
    y = ctx.transfer(ch, x)
    return ctx.bcast(y, root=0, port=3)
""",
    )
    assert proc.returncode == 0, proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    kinds = [l.split('"')[3] for l in lines]
    assert kinds == ["push", "pop", "reduce", "push", "pop", "broadcast"]
    assert '"op_type": "max"' in lines[2]


def test_manifest_via_python_wrapper(tmp_path):
    src = tmp_path / "prog.py"
    src.write_text('ops = [Push(0, "float"), Pop(0, "float")]\n')
    ops = native.extract_manifest([str(src)])
    assert ops == [Push(0, "float"), Pop(0, "float")]
    # the extracted ops build a valid Program directly
    prog = smi.Program(ops)
    assert prog.logical_port_count == 1


def test_manifest_rejects_duplicate_port(tmp_path):
    proc = run_manifest(
        tmp_path, 'a = Push(0, "float")\nb = Push(0, "int")\n'
    )
    assert proc.returncode == 1
    assert "claimed twice" in proc.stderr


def test_manifest_resolves_constant_port(tmp_path):
    """Ports bound once to an integer literal resolve, as the reference
    resolves const ints through variable declarations
    (``source-rewriter/src/ops/utils.cpp:5-48``, golden case
    ``codegen/tests/data/constant-variable.cl``)."""
    proc = run_manifest(tmp_path, "p = 3\nx = Push(p)\n")
    assert proc.returncode == 0, proc.stderr
    assert '"port": 3' in proc.stdout


def test_manifest_rejects_computed_port(tmp_path):
    """A computed port is rejected with a file:line diagnostic."""
    proc = run_manifest(tmp_path, "p = 3 + 1\nx = Push(p)\n")
    assert proc.returncode == 1
    assert "not a compile-time integer constant" in proc.stderr
    assert "prog.py:2" in proc.stderr


def test_manifest_rejects_unknown_name_port(tmp_path):
    proc = run_manifest(tmp_path, "x = Push(mystery_port)\n")
    assert proc.returncode == 1
    assert "prog.py:1" in proc.stderr
    assert "not a compile-time integer constant" in proc.stderr


def test_manifest_aliased_imports(tmp_path):
    """`from smi_tpu import Push as P` binds the local alias
    (reference: the rewriter matches bound SMI_* symbols regardless of
    spelling at the call site)."""
    proc = run_manifest(
        tmp_path,
        "from smi_tpu import Push as P, Pop as Q\n"
        "from smi_tpu.ops.operations import Reduce\n"
        'a = P(0, "float")\nb = Q(0, "float")\nc = Reduce(1, "int")\n',
    )
    assert proc.returncode == 0, proc.stderr
    kinds = [l.split('"')[3] for l in proc.stdout.splitlines() if l.strip()]
    assert kinds == ["push", "pop", "reduce"]


def test_manifest_parenthesized_import_list(tmp_path):
    proc = run_manifest(
        tmp_path,
        "from smi_tpu import (\n    Push as Send,\n    Pop,\n)\n"
        'a = Send(2, "int")\nb = Pop(2, "int")\n',
    )
    assert proc.returncode == 0, proc.stderr
    kinds = [l.split('"')[3] for l in proc.stdout.splitlines() if l.strip()]
    assert kinds == ["push", "pop"]


def test_manifest_attribute_qualified_calls(tmp_path):
    """Attribute-qualified call sites (`smi.Push`, `smi_tpu.ops.Push`)
    match on the final name segment."""
    proc = run_manifest(
        tmp_path,
        "import smi_tpu as smi\n"
        'a = smi.Push(0, "float")\n'
        'b = smi.ops.operations.Pop(0, "float")\n',
    )
    assert proc.returncode == 0, proc.stderr
    kinds = [l.split('"')[3] for l in proc.stdout.splitlines() if l.strip()]
    assert kinds == ["push", "pop"]


def test_manifest_alias_does_not_leak_to_unrelated_names(tmp_path):
    """Only recognized op names may be aliased; other imports stay inert,
    and a reassigned constant stops being one."""
    proc = run_manifest(
        tmp_path,
        "from functools import partial as Push_like\n"
        "p = 3\np = q\nx = Push(p)\n",
    )
    assert proc.returncode == 1  # p lost its binding -> computed port
    assert "not a compile-time integer constant" in proc.stderr


def test_manifest_keyword_args_do_not_become_constants(tmp_path):
    """`foo(port=9)` in an unrelated call must not bind `port` as a
    module constant."""
    proc = run_manifest(
        tmp_path,
        "configure(port=9)\nx = Push(port)\n",
    )
    assert proc.returncode == 1
    assert "prog.py:2" in proc.stderr


def test_manifest_rejects_unknown_dtype(tmp_path):
    proc = run_manifest(tmp_path, 'x = Push(0, "quaternion")\n')
    assert proc.returncode == 1
    assert "unknown dtype" in proc.stderr


def test_manifest_eager_mode_relaxes_ctrl_conflicts(tmp_path):
    # Push(0) + Pop-credit collision only exists under rendezvous; two
    # pushes on distinct ports plus pops are fine either way, but a
    # Broadcast(0)+Push(0) clash is caught in both modes.
    proc = run_manifest(
        tmp_path, 'a = Push(0, "float")\nb = Broadcast(0, "float")\n'
    )
    assert proc.returncode == 1


def test_manifest_skips_comments_and_strings(tmp_path):
    proc = run_manifest(
        tmp_path,
        '# Push(9, "float")\ns = "Pop(8)"\nx = Push(1, "short")\n',
    )
    assert proc.returncode == 0
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1 and '"port": 1' in lines[0]


# ------------------------------------------------------------- runtime --


def test_runtime_version():
    assert native.runtime_version().startswith("smi_tpu-runtime")


def test_runtime_timers_monotonic():
    a = native.time_usecs()
    b = native.time_usecs()
    assert b >= a
    assert native.time_nsecs() > 0


def test_routing_table_round_trip(tmp_path):
    entries = [0, 1, 2, 250, 7, 7, 0, 1]
    native.store_routing_table(str(tmp_path), "cks", 3, 1, entries)
    loaded = native.load_routing_table(str(tmp_path), "cks", 3, 1)
    assert loaded == entries


def test_load_missing_table_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        native.load_routing_table(str(tmp_path), "cks", 0, 0)


def test_bootstrap_against_python_writer(tmp_path):
    """The C library bootstraps from tables written by the Python routing
    layer — the cross-language format contract."""
    from smi_tpu.parallel.routing import write_routing_tables
    from tests.test_routing import make_topology

    program = smi.Program([Push(0), Pop(0), Push(1), Pop(1)])
    topo = make_topology({("NA:0", 1): ("NB:0", 1)}, program)
    write_routing_tables(tmp_path, topo)

    for rank in (0, 1):
        ports = native.bootstrap_rank(
            str(tmp_path), rank, channels=4, max_ranks=2
        )
        assert ports == 2


def test_bootstrap_missing_rank_fails(tmp_path):
    with pytest.raises(ValueError):
        native.bootstrap_rank(str(tmp_path), 5, channels=4, max_ranks=2)


def test_manifest_rebound_literal_is_poisoned(tmp_path):
    """A name assigned twice — even to literals both times — stops being
    a constant: the scanner cannot know which binding a call site sees
    (docs/manifest.md 'bound once')."""
    proc = run_manifest(tmp_path, "p = 0\nx = Push(p)\np = 1\n")
    assert proc.returncode == 1
    assert "not a compile-time integer constant" in proc.stderr


def test_manifest_conditional_literal_rejected(tmp_path):
    """`p = 3 if fast else 4` must not bind p=3 (same-line expression
    continuation after the literal)."""
    proc = run_manifest(tmp_path, "p = 3 if fast else 4\nx = Push(p)\n")
    assert proc.returncode == 1
    assert "not a compile-time integer constant" in proc.stderr


def test_manifest_tuple_and_comparison_not_constants(tmp_path):
    proc = run_manifest(tmp_path, "p = 3, 4\nx = Push(p)\n")
    assert proc.returncode == 1
    proc = run_manifest(tmp_path, "ok = 3 < limit\nx = Push(ok)\n")
    assert proc.returncode == 1


def test_manifest_semicolon_statement_is_constant(tmp_path):
    proc = run_manifest(tmp_path, "p = 3; q = 5\nx = Push(p); y = Pop(q)\n")
    assert proc.returncode == 0, proc.stderr
    assert '"port": 3' in proc.stdout
    assert '"port": 5' in proc.stdout
