"""MoE expert dispatch: the all-to-all traffic shape through serving.

The first serving workload whose traffic matrix is DATA-DEPENDENT: a
tenant's token batch is routed per token to experts (a seeded router —
the gating network's verdict), the per-expert splits scatter to the
experts' home ranks as ordinary admitted streams, and the batch
gathers back by inverse permutation once every split delivered. The
wire-level executable spec of this shape is the all-to-all protocol
family (``credits.all_to_all_rank`` and friends); this module is its
workload-level consumer, run entirely under the EXISTING serving
machinery — per-tenant token buckets, QoS brownout ceilings,
end-to-end stream credits, per-destination backpressure caps,
phi-accrual failover — none of which is bypassed or special-cased:

- an expert's home rank is ``expert % n`` (``expert_home``); a stream
  reaches it through :meth:`ServingFrontend.submit`'s explicit
  ``base_rank`` (failover to heirs still rides
  ``membership.route_owner`` on top, so a dead expert host replays
  its in-flight splits to the heir like any tenant stream);
- a token routed NOWHERE near capacity is the hot-expert regime: the
  seeded campaign's skew cell gives ONE expert ``hot_factor`` (8x)
  the routing weight, its home rank's backlog cap trips, and the
  admission edge must shed with the named ``backpressure:rank<h>``
  error — never a queue, never a membership transition (the
  exhaustive small-scope counterpart is the model checker's
  ``hot_rank`` scope);
- empty per-expert splits (a batch routing zero tokens to an expert)
  simply submit no stream — the degenerate all-to-all block the
  protocol tests pin.

Gates (the campaign exit is nonzero if any fails): **zero silent
corruption** — every fully-accepted batch reassembles bit-identically
to its submitted tokens under the inverse routing permutation; **zero
lost-accepted** — every admitted split stream is delivered (the
front-end's own invariant, re-asserted here); **lowest-class-first
shedding** — brownout/timeout sheds ordered best_effort >= batch >=
interactive with zero interactive brownout (per-destination
backpressure sheds are class-blind by design and gated separately on
NAMING the hot rank); bounded queue occupancy; and zero false
membership transitions under pure skew (saturation is not death).
Deterministic per seed — ``tests/test_moe.py`` pins the campaign.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Sequence, Tuple

from smi_tpu.serving.admission import DEFAULT_POOL
from smi_tpu.serving.frontend import ServingFrontend
from smi_tpu.serving.qos import QOS_CLASSES, AdmissionRejected, check_qos

#: Tokens per batch per QoS class (interactive batches are small and
#: latency-sensitive; best_effort large and patient) — the MoE analog
#: of campaign.CLASS_CHUNKS.
CLASS_TOKENS = {"interactive": 4, "batch": 8, "best_effort": 12}

#: Traffic mix weights per class (campaign.CLASS_MIX's shape).
CLASS_MIX = {"interactive": 3, "batch": 3, "best_effort": 4}

#: The hot-expert skew the seeded campaign applies: one expert draws
#: this multiple of every other expert's routing weight.
HOT_FACTOR = 8

#: Minimum MoE campaign cell duration (ticks): long enough that a
#: hot-expert cell's backlog provably reaches the admission edge.
MIN_MOE_DURATION = 60


def expert_home(expert: int, n: int) -> int:
    """The rank that serves ``expert`` — deterministic, stable across
    runs; failover rides ``membership.route_owner`` on top."""
    if n < 1:
        raise ValueError(f"need n >= 1 ranks, got {n}")
    if expert < 0:
        raise ValueError(f"expert ids are >= 0, got {expert}")
    return expert % n


def token_payload(tenant: str, batch: int, position: int) -> str:
    """Content-addressed token payload: reassembly is checked against
    exactly this, so wrong routing OR wrong bits both fail the
    bit-identity gate."""
    return f"{tenant}/b{batch}/t{position}"


def route_tokens(
    tenant: str,
    batch: int,
    seed: int,
    n_tokens: int,
    experts: int,
    hot_expert: Optional[int] = None,
    hot_factor: int = HOT_FACTOR,
) -> List[int]:
    """The seeded gating decision: token position -> expert id.

    Deterministic per (tenant, batch, seed) — the data-dependent
    traffic matrix the all-to-all family exists for. ``hot_expert``
    (the skew cell) draws with ``hot_factor`` x every other expert's
    weight; ``None`` is the uniform router.
    """
    if experts < 1:
        raise ValueError(f"need >= 1 experts, got {experts}")
    if hot_expert is not None and not 0 <= hot_expert < experts:
        raise ValueError(
            f"hot_expert={hot_expert} outside 0..{experts - 1}"
        )
    if hot_factor < 1:
        raise ValueError(f"hot_factor must be >= 1, got {hot_factor}")
    rng = random.Random(f"moe:{tenant}:{batch}:{seed}")
    pool = list(range(experts))
    if hot_expert is not None:
        pool += [hot_expert] * (hot_factor - 1)
    return [rng.choice(pool) for _ in range(n_tokens)]


def split_by_expert(assignment: Sequence[int],
                    experts: int) -> Dict[int, List[int]]:
    """Per-expert token POSITIONS, experts with zero tokens omitted —
    the empty split is the absence of a stream, never an empty one
    (a request must carry at least one chunk)."""
    splits: Dict[int, List[int]] = {}
    for pos, e in enumerate(assignment):
        if not 0 <= e < experts:
            raise ValueError(
                f"token {pos} routed to unknown expert {e} "
                f"(experts=0..{experts - 1})"
            )
        splits.setdefault(e, []).append(pos)
    return splits


@dataclasses.dataclass
class MoeBatch:
    """One dispatched token batch's bookkeeping."""

    tenant: str
    qos: str
    batch: int
    tokens: Tuple[str, ...]
    assignment: Tuple[int, ...]
    #: expert -> (stream_id, token positions) for each submitted split
    streams: Dict[int, Tuple[Tuple[str, int], Tuple[int, ...]]]
    #: the shed that aborted the batch: at dispatch (a split refused
    #: on the spot) or DEFERRED (a parked split shed at pump time —
    #: admission-timeout / sustained brownout, wired through the
    #: gate's on_shed hook). None = every split admitted.
    shed: Optional[AdmissionRejected] = None
    #: sibling splits already holding credits when the shed landed
    #: (they still deliver — named in the report, never silently
    #: dropped)
    orphaned: int = 0

    @property
    def accepted(self) -> bool:
        return self.shed is None


class MoeDispatcher:
    """Scatter token batches to experts, gather them back.

    A thin layer over ONE :class:`ServingFrontend`: each non-empty
    per-expert split is an ordinary admitted stream to the expert's
    home rank, so admission, QoS, backpressure, and failover all apply
    unchanged. ``dispatch`` returns the batch bookkeeping; ``gather``
    (after the front-end drains) reassembles the token sequence by
    inverse permutation and verifies bit-identity.
    """

    def __init__(self, frontend: ServingFrontend, experts: int,
                 hot_expert: Optional[int] = None,
                 hot_factor: int = HOT_FACTOR, seed: int = 0):
        if experts < 1:
            raise ValueError(f"need >= 1 experts, got {experts}")
        self.fe = frontend
        self.experts = experts
        self.hot_expert = hot_expert
        self.hot_factor = hot_factor
        self.seed = seed
        self.batches: List[MoeBatch] = []
        self._batch_seq: Dict[str, int] = {}
        #: stream_id -> owning batch, for DEFERRED sheds: a split
        #: parked at submit time can still be shed at pump time
        #: (admission-timeout / sustained brownout) — the gate's
        #: on_shed hook marks the owning batch shed so a loudly-shed
        #: stream can never be misread as silent corruption at gather
        self._stream_to_batch: Dict[Tuple[str, int], MoeBatch] = {}
        prev_on_shed = frontend.gate.on_shed

        def _on_deferred_shed(rejection, request):
            if prev_on_shed is not None:
                prev_on_shed(rejection, request)
            batch = self._stream_to_batch.get(request.stream_id)
            if batch is not None and batch.shed is None:
                batch.shed = rejection
                batch.orphaned = sum(
                    1 for sid, _pos in batch.streams.values()
                    if sid != request.stream_id
                )

        frontend.gate.on_shed = _on_deferred_shed

    def dispatch(self, tenant: str, qos: str, n_tokens: int) -> MoeBatch:
        """Route one batch and submit its per-expert splits.

        A shed on ANY split aborts the batch loudly (recorded on the
        returned :class:`MoeBatch`; splits already admitted are
        counted as ``orphaned`` — they hold credits and WILL deliver,
        the accounting just names them instead of letting a partial
        batch read as accepted).
        """
        check_qos(qos)
        if n_tokens < 1:
            raise ValueError(f"need >= 1 tokens, got {n_tokens}")
        batch_no = self._batch_seq.get(tenant, 0)
        self._batch_seq[tenant] = batch_no + 1
        tokens = tuple(
            token_payload(tenant, batch_no, p) for p in range(n_tokens)
        )
        assignment = tuple(route_tokens(
            tenant, batch_no, self.seed, n_tokens, self.experts,
            hot_expert=self.hot_expert, hot_factor=self.hot_factor,
        ))
        batch = MoeBatch(
            tenant=tenant, qos=qos, batch=batch_no, tokens=tokens,
            assignment=assignment, streams={},
        )
        self.batches.append(batch)
        for expert, positions in sorted(
            split_by_expert(assignment, self.experts).items()
        ):
            chunks = tuple(tokens[p] for p in positions)
            try:
                request = self.fe.submit(
                    tenant, qos, chunks,
                    base_rank=expert_home(expert, self.fe.n),
                )
            except AdmissionRejected as e:
                batch.shed = e
                batch.orphaned = len(batch.streams)
                break
            batch.streams[expert] = (
                request.stream_id, tuple(positions)
            )
            self._stream_to_batch[request.stream_id] = batch
        return batch

    def _delivered_chunks(self) -> Dict[Tuple[str, int], Tuple]:
        """stream_id -> delivered chunk tuple, for completed streams."""
        out = {}
        for st in self.fe.completed:
            out[st.request.stream_id] = tuple(
                st.delivered[i] for i in range(st.total_chunks)
            )
        return out

    def gather(self, batch: MoeBatch) -> Optional[Tuple[str, ...]]:
        """Reassemble one fully-accepted batch after the front-end
        drained: inverse-permute the delivered per-expert splits back
        into token order. Returns the reassembled tuple (compare
        against ``batch.tokens`` for the bit-identity gate), or
        ``None`` for a shed batch (nothing to reassemble)."""
        if not batch.accepted:
            return None
        delivered = self._delivered_chunks()
        out: List[Optional[str]] = [None] * len(batch.tokens)
        for expert, (stream_id, positions) in batch.streams.items():
            chunks = delivered.get(stream_id)
            if chunks is None or len(chunks) != len(positions):
                return tuple("<missing>" for _ in batch.tokens)
            for p, payload in zip(positions, chunks):
                out[p] = payload
        return tuple("<missing>" if t is None else t for t in out)


def run_moe_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 120,
    experts: int = 4,
    tenants: int = 4,
    hot_expert: Optional[int] = None,
    hot_factor: int = HOT_FACTOR,
    batches_per_tick: float = 0.5,
    pool: int = DEFAULT_POOL,
) -> Dict:
    """One seeded MoE expert-dispatch cell: open-loop batch arrivals,
    scatter/gather through the serving front-end, gates evaluated.
    Deterministic per (shape, seed)."""
    if duration < MIN_MOE_DURATION:
        raise ValueError(
            f"MoE cell duration {duration} is below the "
            f"{MIN_MOE_DURATION}-tick minimum (a hot-expert backlog "
            f"needs the schedule to reach the admission edge)"
        )
    from smi_tpu.serving.campaign import campaign_recorder

    fe = ServingFrontend(n, seed=seed, pool=pool,
                         recorder=campaign_recorder(duration, n))
    dispatcher = MoeDispatcher(
        fe, experts, hot_expert=hot_expert, hot_factor=hot_factor,
        seed=seed,
    )
    rng = random.Random(f"moe-cell:{n}:{seed}")
    classes = [c for c in QOS_CLASSES for _ in range(CLASS_MIX[c])]
    verdict = "ok"
    acc = 0.0
    try:
        for _tick in range(duration):
            acc += batches_per_tick
            while acc >= 1.0:
                acc -= 1.0
                tenant = f"t{rng.randrange(tenants)}"
                qos = rng.choice(classes)
                dispatcher.dispatch(tenant, qos, CLASS_TOKENS[qos])
            fe.step()
        fe.drain()
    except Exception as e:  # a watchdog/assert firing IS the verdict
        verdict = f"{type(e).__name__}: {e}"

    report = fe.report()
    accepted_batches = [b for b in dispatcher.batches if b.accepted]
    shed_batches = [b for b in dispatcher.batches if not b.accepted]
    corrupt = 0
    for b in accepted_batches:
        if dispatcher.gather(b) != b.tokens:
            corrupt += 1
    hot_rank = (expert_home(hot_expert, n)
                if hot_expert is not None else None)
    report.update({
        "cell": "moe-hot-expert" if hot_expert is not None else "moe",
        "seed": seed,
        "duration": duration,
        "experts": experts,
        "hot_expert": hot_expert,
        "hot_rank": hot_rank,
        "hot_factor": hot_factor if hot_expert is not None else 1,
        "batches": len(dispatcher.batches),
        "batches_accepted": len(accepted_batches),
        "batches_shed": len(shed_batches),
        "batch_shed_reasons": sorted(
            {b.shed.reason for b in shed_batches}
        ),
        "orphaned_streams": sum(b.orphaned for b in shed_batches),
        "reassembly_corruptions": corrupt,
    })

    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    if corrupt:
        problems.append(
            f"silent corruption: {corrupt} batch(es) reassembled "
            f"wrong bits"
        )
    if report["silent_corruptions"]:
        problems.append(
            f"silent corruption: {report['silent_corruptions']} "
            f"stream(s) delivered wrong bits"
        )
    if report["lost_accepted"]:
        problems.append(
            f"lost accepted: {report['lost_accepted']} admitted "
            f"stream(s) never delivered"
        )
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    if report["max_queue_depth"] > report["queue_bound"]:
        problems.append(
            f"queue occupancy {report['max_queue_depth']} exceeded "
            f"bound {report['queue_bound']}"
        )
    brownout = {
        c: sum(v for k, v in report["shed"][c].items()
               if k.startswith("brownout") or k == "admission-timeout")
        for c in QOS_CLASSES
    }
    report["brownout_shed"] = brownout
    report["backpressure_shed"] = {
        c: sum(v for k, v in report["shed"][c].items()
               if k.startswith("backpressure:"))
        for c in QOS_CLASSES
    }
    if brownout["interactive"] > 0:
        problems.append(
            f"interactive brownout-shed {brownout['interactive']} "
            f"(> 0): shedding is not lowest-class-first"
        )
    if (brownout["best_effort"] < brownout["batch"]
            or brownout["batch"] < brownout["interactive"]):
        problems.append(
            "shedding not lowest-class-first: best_effort "
            f"{brownout['best_effort']} / batch {brownout['batch']} / "
            f"interactive {brownout['interactive']}"
        )
    if hot_expert is not None:
        hot_reason = f"backpressure:rank{hot_rank}"
        all_reasons = {
            k for c in QOS_CLASSES for k in report["shed"][c]
        }
        if hot_reason not in all_reasons:
            problems.append(
                f"hot expert {hot_expert} (rank {hot_rank}) at "
                f"{hot_factor}x skew never tripped the per-route "
                f"backpressure edge (no {hot_reason!r} shed)"
            )
        if report["confirmed"]:
            problems.append(
                f"hot-expert saturation confirmed a death: "
                f"{report['confirmed']} (skew mistaken for failure)"
            )
    # the r15 span layer: expert-dispatch streams get the same span
    # trees, blame verdict, and bit-identity exactness gate as every
    # other serving cell
    from smi_tpu.serving.campaign import span_fields

    span_fields(fe, report, problems)
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    return report


def moe_campaign(
    seed: int = 0,
    n: int = 4,
    duration: int = 120,
    experts: int = 4,
    trials: int = 1,
) -> Dict:
    """The seeded MoE campaign: one uniform-routing cell and one
    hot-expert cell (a seeded expert at :data:`HOT_FACTOR` x weight)
    per trial, each deterministic per seed. Exit gate: every cell
    ``ok``."""
    cells: List[Dict] = []
    for trial in range(trials):
        base = random.Random(f"moe:{seed}:{trial}").randrange(1 << 30)
        hot = random.Random(f"moe-hot:{seed}:{trial}").randrange(experts)
        for kwargs in (
            dict(hot_expert=None),
            dict(hot_expert=hot, batches_per_tick=0.75),
        ):
            report = run_moe_cell(
                n=n, seed=base, duration=duration, experts=experts,
                **kwargs,
            )
            report["trial"] = trial
            cells.append(report)
    failures = [c for c in cells if not c["ok"]]
    return {
        "seed": seed,
        "n": n,
        "experts": experts,
        "duration": duration,
        "trials": trials,
        "cells": len(cells),
        "outcomes": {
            c["cell"]: ("ok" if c["ok"] else "failed") for c in cells
        },
        "failures": [
            {"cell": c["cell"], "trial": c["trial"],
             "verdict": c["verdict"]}
            for c in failures
        ],
        "silent_corruptions": sum(
            c["silent_corruptions"] + c["reassembly_corruptions"]
            for c in cells
        ),
        "lost_accepted": sum(c["lost_accepted"] for c in cells),
        "stale_epoch_leaks": sum(
            c["stale_epoch_leaks"] for c in cells
        ),
        "reports": cells,
        "ok": not failures,
    }
