"""Admission gate: token buckets, stream credits, bounded queues.

The gate is the serving edge of the credit discipline the wire already
enforces (:mod:`smi_tpu.parallel.credits`): a bounded pool of **stream
credits** plays the role the receiver's buffer slots play on the wire.
A stream holds its credit from acceptance until its LAST chunk is
consumed and verified at the destination — not merely sent — so the
credit chain runs end to end: a stalled consumer keeps wire credits
held, which keeps its streams incomplete, which keeps stream credits
held, which drives pool occupancy to the brownout ceilings, which sheds
new requests *at the admission edge* with a named error. Queue growth
is bounded by construction (pool + per-class pending caps) and the gate
asserts the bound on every transition.

Three decision layers, in order:

1. **per-tenant token bucket** — isolation between tenants, class-blind
   (reason ``tenant-rate``);
2. **brownout ceilings** (:data:`~smi_tpu.serving.qos.CLASS_POOL_CEILING`)
   — occupancy-triggered, lowest class first. A short burst above the
   ceiling parks in the class's bounded pending queue; sustained
   overload (a full pool's worth of the class already waiting) sheds
   immediately with reason ``brownout:<class>``;
3. **bounded pending wait** — a parked request waits at most its
   class's admission cap for a credit to free (priority classes drain
   first), then is shed (reason ``admission-timeout``).

Every shed is recorded as a full :class:`~smi_tpu.serving.qos.AdmissionRejected`
instance; nothing is dropped silently.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from smi_tpu.serving.qos import (
    CLASS_ADMISSION_WAIT_TICKS,
    CLASS_POOL_CEILING,
    QOS_CLASSES,
    AdmissionRejected,
    Request,
)

#: Default stream-credit pool: concurrent accepted streams across all
#: classes. The serving queue-occupancy bound (asserted, and quoted by
#: docs/robustness.md).
DEFAULT_POOL = 12

#: Pending-queue bound per class: one pool's worth. A class with a
#: full pool of requests already parked is in *sustained* brownout —
#: new arrivals would only time out behind the waiters, so they are
#: shed immediately (``brownout:<class>``) instead of buffered. This
#: is what keeps the admission edge a bounded buffer: queue depth can
#: never exceed ``pool * (1 + len(QOS_CLASSES))``.

#: Default per-tenant token bucket: sustained streams/tick and burst.
DEFAULT_TENANT_RATE = 0.25
DEFAULT_TENANT_BURST = 6.0


class TokenBucket:
    """Deterministic token bucket on the step clock (no wall time)."""

    def __init__(self, rate_per_tick: float, burst: float):
        if rate_per_tick <= 0 or burst < 1:
            raise ValueError(
                f"need rate > 0 and burst >= 1, got rate="
                f"{rate_per_tick}, burst={burst}"
            )
        self.rate = float(rate_per_tick)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = 0

    def _refill(self, now: int) -> None:
        if now > self._last:
            self.tokens = min(
                self.burst, self.tokens + (now - self._last) * self.rate
            )
            self._last = now

    def try_take(self, now: int) -> bool:
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


@dataclasses.dataclass
class _Pending:
    request: Request
    since: int


class AdmissionGate:
    """Bounded multi-class admission with end-to-end credit chaining.

    ``on_admit(request, waited_ticks)`` is called for every admission
    (immediate or from the pending queue); ``on_shed(rejection,
    request)`` for every shed. Credits return via :meth:`release`
    (call when the stream's last chunk is consumed and verified —
    NOT when it is sent), which immediately drains the pending
    queues highest-class-first.
    """

    def __init__(
        self,
        pool: int = DEFAULT_POOL,
        tenant_rate: float = DEFAULT_TENANT_RATE,
        tenant_burst: float = DEFAULT_TENANT_BURST,
        ceilings: Optional[Dict[str, float]] = None,
        wait_caps: Optional[Dict[str, int]] = None,
        recorder=None,
        metrics=None,
    ):
        if pool < 1:
            raise ValueError(f"pool must be >= 1, got {pool}")
        self.pool = pool
        #: observability hooks (both optional, zero-cost when None):
        #: ``recorder`` — a flight recorder
        #: (:class:`smi_tpu.obs.events.FlightRecorder`) receiving one
        #: ``serve.admit`` / ``serve.park`` / ``serve.shed`` event per
        #: decision, and whose bounded tail rides every
        #: :class:`AdmissionRejected`; ``metrics`` — a
        #: :class:`smi_tpu.obs.metrics.MetricsRegistry` fed the
        #: admitted/shed/parked counters, the per-(tenant, class)
        #: admission-wait histogram, and the queue-depth gauge. The
        #: counters are incremented at the SAME sites as the gate's
        #: own accounting, so a metrics snapshot can never disagree
        #: with the campaign report's bookkeeping.
        self.recorder = recorder
        self.metrics = metrics
        self._now = 0
        self.tenant_rate = tenant_rate
        self.tenant_burst = tenant_burst
        self.ceilings = dict(ceilings or CLASS_POOL_CEILING)
        self.wait_caps = dict(wait_caps or CLASS_ADMISSION_WAIT_TICKS)
        self._buckets: Dict[str, TokenBucket] = {}
        self.held: Dict[str, int] = {c: 0 for c in QOS_CLASSES}
        self.pending: Dict[str, Deque[_Pending]] = {
            c: deque() for c in QOS_CLASSES
        }
        self.pending_bound = pool
        # accounting (the campaign report reads these)
        self.admitted: Dict[str, int] = {c: 0 for c in QOS_CLASSES}
        self.shed: Dict[str, Dict[str, int]] = {
            c: {} for c in QOS_CLASSES
        }
        self.rejections: List[AdmissionRejected] = []
        self.admission_waits: Dict[str, List[int]] = {
            c: [] for c in QOS_CLASSES
        }
        self.max_queue_depth = 0
        self.on_admit: Optional[Callable[[Request, int], None]] = None
        self.on_shed: Optional[
            Callable[[AdmissionRejected, Request], None]
        ] = None
        #: Optional caller predicate consulted before any PENDING
        #: request is admitted (the front-end's per-destination
        #: backlog cap): False keeps it parked — it may admit on a
        #: later pump or time out with a named shed. Immediate
        #: admissions in :meth:`offer` are the caller's own
        #: responsibility (it can check before offering).
        self.admit_filter: Optional[Callable[[Request], bool]] = None

    # -- bookkeeping ----------------------------------------------------

    def occupancy(self) -> int:
        """Stream credits currently held (accepted, incomplete)."""
        return sum(self.held.values())

    def queue_depth(self) -> int:
        """Held credits + pending requests: the serving queue the
        bound covers."""
        return self.occupancy() + sum(
            len(q) for q in self.pending.values()
        )

    def assert_bounded(self) -> None:
        """The structural occupancy bound, asserted on every
        transition: held <= pool and each pending queue <= its cap.
        A violation is a front-end bug, not an overload symptom —
        overload must surface as shedding, never as growth."""
        occ = self.occupancy()
        if occ > self.pool:
            raise AssertionError(
                f"stream-credit occupancy {occ} exceeds pool {self.pool}"
            )
        for c, q in self.pending.items():
            if len(q) > self.pending_bound:
                raise AssertionError(
                    f"pending queue for {c} grew to {len(q)} "
                    f"(bound {self.pending_bound})"
                )
        self.max_queue_depth = max(self.max_queue_depth,
                                   self.queue_depth())
        if self.metrics is not None:
            self.metrics.gauge("queue_depth").set(self.queue_depth())
            self.metrics.gauge("pool_occupancy").set(occ)

    def _ceiling_slots(self, qos: str) -> int:
        return math.ceil(self.ceilings[qos] * self.pool)

    def _can_admit(self, qos: str) -> bool:
        return self.occupancy() < self._ceiling_slots(qos)

    def shed_named(self, request: Request, reason: str
                   ) -> AdmissionRejected:
        """Record an externally-decided shed (e.g. the front-end's
        per-destination backpressure cap) under the gate's accounting,
        so every rejection in the system flows through one audited
        path. Returns the named error for the caller to raise."""
        return self._record_shed(request, reason)

    def _record_shed(self, request: Request, reason: str
                     ) -> AdmissionRejected:
        rejection = AdmissionRejected(
            request.tenant, request.qos, self.queue_depth(), reason
        )
        self.shed[request.qos][reason] = (
            self.shed[request.qos].get(reason, 0) + 1
        )
        self.rejections.append(rejection)
        if self.recorder is not None:
            from smi_tpu.obs.events import attach_tail

            self.recorder.emit(
                "serve.shed", self._now, tenant=request.tenant,
                qos=request.qos, reason=reason,
                stream_seq=request.stream_id[1],
            )
            # a shed names its causal history, not just its reason
            attach_tail(rejection, self.recorder)
        if self.metrics is not None:
            self.metrics.counter("shed_total", qos=request.qos,
                                 reason=reason).inc()
        if self.on_shed is not None:
            self.on_shed(rejection, request)
        return rejection

    def _admit(self, request: Request, now: int) -> None:
        self.held[request.qos] += 1
        self.admitted[request.qos] += 1
        waited = now - request.arrived_at
        self.admission_waits[request.qos].append(waited)
        if self.recorder is not None:
            self.recorder.emit(
                "serve.admit", now, tenant=request.tenant,
                qos=request.qos, waited=waited,
                stream_seq=request.stream_id[1],
            )
        if self.metrics is not None:
            self.metrics.counter("admitted_total",
                                 qos=request.qos).inc()
            self.metrics.histogram(
                "admission_wait_ticks", tenant=request.tenant,
                qos=request.qos,
            ).observe(waited)
        if self.on_admit is not None:
            self.on_admit(request, waited)
        self.assert_bounded()

    # -- the gate -------------------------------------------------------

    def offer(self, request: Request, now: int) -> bool:
        """One request at the admission edge.

        Returns True when admitted immediately, False when parked in
        the (bounded) pending queue; raises
        :class:`~smi_tpu.serving.qos.AdmissionRejected` when shed on
        the spot. Deferred sheds (admission-timeout) surface through
        ``on_shed``/``rejections`` — every outcome is named either way.
        """
        self._now = now
        bucket = self._buckets.get(request.tenant)
        if bucket is None:
            bucket = self._buckets[request.tenant] = TokenBucket(
                self.tenant_rate, self.tenant_burst
            )
        if not bucket.try_take(now):
            raise self._record_shed(request, "tenant-rate")
        if self._can_admit(request.qos):
            self._admit(request, now)
            return True
        if len(self.pending[request.qos]) >= self.pending_bound:
            # sustained brownout: a full pool of this class already
            # waits; buffering more would only convert the shed into
            # a slower admission-timeout
            raise self._record_shed(request, f"brownout:{request.qos}")
        # a short burst above the ceiling parks: a credit may free
        # within the class's wait cap
        self.pending[request.qos].append(_Pending(request, now))
        if self.recorder is not None:
            self.recorder.emit("serve.park", now, tenant=request.tenant,
                               qos=request.qos,
                               stream_seq=request.stream_id[1])
        if self.metrics is not None:
            self.metrics.counter("parked_total", qos=request.qos).inc()
        self.assert_bounded()
        return False

    def pump(self, now: int) -> List[Request]:
        """Drain the pending tier: shed requests that waited out their
        class cap, then admit in strict class-priority order while
        ceilings allow. Returns the newly admitted requests."""
        self._now = now
        admitted: List[Request] = []
        for qos in QOS_CLASSES:
            queue = self.pending[qos]
            keep: Deque[_Pending] = deque()
            while queue:
                p = queue.popleft()
                if now - p.since > self.wait_caps[qos]:
                    self._record_shed(p.request, "admission-timeout")
                elif self._can_admit(qos) and (
                    self.admit_filter is None
                    or self.admit_filter(p.request)
                ):
                    self._admit(p.request, now)
                    admitted.append(p.request)
                else:
                    keep.append(p)
            self.pending[qos] = keep
        self.assert_bounded()
        return admitted

    def release(self, qos: str, now: int) -> List[Request]:
        """Return one stream credit (the stream's last chunk consumed
        and verified) and immediately re-pump the pending tier — the
        end-to-end chain's upstream edge."""
        if self.held[qos] <= 0:
            raise AssertionError(
                f"release of a credit class {qos} never held"
            )
        self.held[qos] -= 1
        return self.pump(now)

    # -- report material ------------------------------------------------

    def shed_total(self, qos: str) -> int:
        return sum(self.shed[qos].values())

    def brownout_shed(self, qos: str) -> int:
        """Sheds attributable to overload policy (ceilings/pending),
        i.e. everything except per-tenant isolation."""
        return sum(v for k, v in self.shed[qos].items()
                   if k != "tenant-rate")
