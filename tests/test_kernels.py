"""Pallas kernel tests: interpreter mode on the CPU fake mesh.

The fused stencil kernel is additionally compiled for real TPU by
bench.py; here interpret mode checks numerics on the same code path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import smi_tpu as smi
from smi_tpu.kernels import ring as kring
from smi_tpu.kernels import stencil as kstencil
from smi_tpu.models import stencil


def test_fused_stencil_matches_reference_interpret(eight_devices):
    comm = smi.make_communicator(
        shape=(2, 2), axis_names=("sx", "sy"), devices=eight_devices
    )
    g = stencil.initial_grid(32, 256)
    g[:, -1] = 2.0
    fn = kstencil.make_fused_stencil_fn(comm, 4, 32, 256, interpret=True)
    out = np.asarray(fn(jnp.asarray(g)))
    ref = stencil.reference_stencil(g, 4)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_fused_stencil_single_rank_interpret(eight_devices):
    comm = smi.make_communicator(
        shape=(1, 1), axis_names=("sx", "sy"), devices=eight_devices
    )
    g = stencil.initial_grid(16, 128)
    fn = kstencil.make_fused_stencil_fn(comm, 3, 16, 128, interpret=True)
    out = np.asarray(fn(jnp.asarray(g)))
    ref = stencil.reference_stencil(g, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_pallas_supported_gating():
    assert kstencil.pallas_supported(512, 1024, jnp.float32)
    assert not kstencil.pallas_supported(512, 1000, jnp.float32)  # lanes
    assert not kstencil.pallas_supported(7, 128, jnp.float32)     # rows
    assert not kstencil.pallas_supported(512, 1024, jnp.float64)  # dtype


@pytest.mark.parametrize("n", [4, 8])
def test_ring_all_gather_interpret(eight_devices, n):
    comm = smi.make_communicator(n, devices=eight_devices)
    fn = kring.make_ring_all_gather(comm, interpret=True)
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    out = np.asarray(fn(x))
    np.testing.assert_array_equal(out, np.asarray(x))


def test_ring_all_reduce_interpret(eight_devices):
    n = 4
    comm = smi.make_communicator(n, devices=eight_devices)
    fn = kring.make_ring_all_reduce(comm, interpret=True)
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n, 8, 128)
    out = np.asarray(fn(x))
    expected = np.asarray(x).sum(axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5)
