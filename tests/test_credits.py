"""Credit flow-control protocol: schedule-fuzzed state-machine tests.

Reference: the SMI NoC's credit protocols (``templates/push.cl:21-31``,
``pop.cl:35-51``, ``reduce.cl:13-32``) are exercised by the strict
channel-depth emulator; here the equivalent protocol that guards the ring
kernels' RDMA slots (:mod:`smi_tpu.kernels.ring`) is specified in
:mod:`smi_tpu.parallel.credits` and driven through random, adversarial,
and (for tiny configurations) exhaustive schedules.

These tests are pure Python — no JAX — and they are the evidence that
``flow_control=True`` in the kernels implements a sound protocol: no
clobber, no deadlock, no credit leak, correct delivery, under every
explored interleaving. The companion mutation tests show the harness
*can* see the race: with credits disabled, adversarial schedules corrupt
data.
"""

import pytest

from smi_tpu.parallel import credits as C

NS = [2, 3, 5, 8]
SEEDS = range(12)


@pytest.mark.parametrize("n", NS)
@pytest.mark.parametrize("seed", SEEDS)
def test_all_gather_random_schedules(n, seed):
    C.simulate_all_gather(n, C.Strategy(seed))


@pytest.mark.parametrize("n", NS)
@pytest.mark.parametrize("seed", SEEDS)
def test_all_reduce_random_schedules(n, seed):
    C.simulate_all_reduce(n, C.Strategy(seed))


@pytest.mark.parametrize("n", NS)
@pytest.mark.parametrize("seed", SEEDS)
def test_reduce_scatter_random_schedules(n, seed):
    C.simulate_reduce_scatter(n, C.Strategy(seed))


@pytest.mark.parametrize("n", NS)
@pytest.mark.parametrize("direction", [1, -1])
@pytest.mark.parametrize("seed", SEEDS)
def test_neighbour_stream_random_schedules(n, direction, seed):
    C.simulate_neighbour_stream(n, 5, C.Strategy(seed), direction=direction)


@pytest.mark.parametrize("n", [3, 5])
@pytest.mark.parametrize("seed", SEEDS)
def test_adversarial_delayed_dmas(n, seed):
    """DMAs land as late as possible — maximal clobber window."""
    C.simulate_all_gather(n, C.DelayDmaStrategy(seed))
    C.simulate_all_reduce(n, C.DelayDmaStrategy(seed))
    C.simulate_reduce_scatter(n, C.DelayDmaStrategy(seed))
    C.simulate_neighbour_stream(n, 6, C.DelayDmaStrategy(seed))


@pytest.mark.parametrize("n", [3, 5])
@pytest.mark.parametrize("seed", SEEDS)
def test_adversarial_favoured_rank(n, seed):
    """One rank races ahead while the others lag — the fast-writer /
    slow-consumer scenario the credits exist for."""
    for fav in range(n):
        C.simulate_all_gather(n, C.FavourRankStrategy(fav, seed))
        C.simulate_neighbour_stream(n, 6, C.FavourRankStrategy(fav, seed))


@pytest.mark.parametrize("name,make", [
    ("neighbour_stream_n2c2", lambda: [
        C.neighbour_stream_rank(r, 2, [(r, c) for c in range(2)])
        for r in range(2)
    ]),
    ("neighbour_stream_n2c3", lambda: [
        C.neighbour_stream_rank(r, 2, [(r, c) for c in range(3)])
        for r in range(2)
    ]),
    ("all_gather_n2", lambda: [
        C.all_gather_rank(r, 2, f"c{r}") for r in range(2)
    ]),
    ("all_reduce_n2", lambda: [
        C.all_reduce_rank(r, 2, frozenset([r]), lambda a, b: a | b)
        for r in range(2)
    ]),
    ("reduce_scatter_n2", lambda: [
        C.reduce_scatter_rank(
            r, 2, [frozenset([(r, b)]) for b in range(2)], lambda a, b: a | b
        )
        for r in range(2)
    ]),
])
def test_exhaustive_tiny_configs(name, make):
    """Every scheduler interleaving (communication-boundary granularity)
    of the two-rank protocols passes all invariants."""
    explored = C.explore_all_schedules(make, max_schedules=500_000)
    assert explored > 50  # genuinely many distinct schedules


def test_mutation_no_credits_is_caught_fuzzed():
    """Disabling flow control must produce a detectable violation under
    adversarial schedules — proof the harness can see the race."""
    caught = 0
    for seed in range(60):
        for fav in range(3):
            try:
                C.simulate_neighbour_stream(
                    3, 8, C.FavourRankStrategy(fav, seed), flow_control=False
                )
            except C.ProtocolError:
                caught += 1
    assert caught > 0


def test_mutation_no_credits_all_gather_corrupts():
    """all_gather without credits: an overtaking landing corrupts the
    gathered payload (caught as clobber or as wrong output)."""
    caught = 0
    for seed in range(60):
        for fav in range(3):
            try:
                C.simulate_all_gather(
                    3, C.FavourRankStrategy(fav, seed), flow_control=False
                )
            except C.ProtocolError:
                caught += 1
    assert caught > 0


def test_deadlock_detection_works():
    """A rank waiting on a credit nobody grants must be reported as a
    deadlock, not an infinite loop."""

    def stuck_rank():
        yield ("wait", C.SEM_CREDIT, 0, 1)

    with pytest.raises(C.DeadlockError):
        C.RingSimulator([stuck_rank()], C.Strategy(0)).run()


def test_credit_leak_detection_works():
    """A dangling semaphore count at exit must be reported."""

    def leaky_rank():
        yield ("signal", 0, C.SEM_CREDIT, 0, 1)

    with pytest.raises(C.CreditLeakError):
        C.RingSimulator([leaky_rank()], C.Strategy(0)).run()


# ---------------------------------------------------------------------------
# Concurrent multi-stream composites: the 4-direction ring halo exchange
# and the burst-interleaved stream_concurrent schedule (the configs
# __graft_entry__.dryrun_multichip executes), fuzzed as composite
# per-rank programs with shared scratch and per-stream barrier domains.
# Reference: the strict-depth emulator exercising interacting channels
# (test/mixed/mixed.cl:15-27, multi_collectives.cl:1-12).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mesh", [(2, 2), (2, 4), (3, 3)])
@pytest.mark.parametrize("seed", range(8))
def test_halo_4dir_random_schedules(mesh, seed):
    """Four ring-tier shifts on distinct barrier domains: no clobber, no
    deadlock, no leak, correct per-stream delivery."""
    C.simulate_halo_exchange(*mesh, C.Strategy(seed))


@pytest.mark.parametrize("seed", range(8))
def test_halo_4dir_adversarial(seed):
    C.simulate_halo_exchange(3, 3, C.DelayDmaStrategy(seed), chunks=2)
    for grp in ([0, 1, 2], [2, 4, 6], [3, 4, 5]):
        C.simulate_halo_exchange(
            3, 3, C.FavourSetStrategy(grp, seed), chunks=2
        )


@pytest.mark.parametrize("n", [4, 5, 8])
@pytest.mark.parametrize("seed", range(8))
def test_stream_concurrent_random_schedules(n, seed):
    """Burst-interleaved opposite-direction streams on distinct port
    domains (the stream_concurrent(backend='ring') schedule)."""
    C.simulate_stream_concurrent(n, C.Strategy(seed))


@pytest.mark.parametrize("seed", range(6))
def test_stream_concurrent_adversarial(seed):
    C.simulate_stream_concurrent(5, C.DelayDmaStrategy(seed), bursts=3)
    for lag in range(5):
        grp = [r for r in range(5) if r != lag]
        C.simulate_stream_concurrent(
            5, C.FavourSetStrategy(grp, seed), bursts=3
        )


def test_mutation_halo_shared_cross_axis_domain_clobbers():
    """A row-ring stream SHARING a barrier domain with a column-ring
    stream lets a rank satisfy its barrier with the other ring's
    signals, enter early, and clobber scratch a neighbour is still
    consuming — the exact hazard the per-direction domains
    (halo.py streams 0-3) exist to prevent."""
    caught = 0
    for seed in range(30):
        strats = [C.Strategy(seed), C.DelayDmaStrategy(seed)] + [
            C.FavourRankStrategy(f, seed) for f in range(9)
        ]
        for strat in strats:
            try:
                C.simulate_halo_exchange(
                    3, 3, strat, chunks=3, domains=(0, 1, 1, 3)
                )
            except C.ProtocolError:
                caught += 1
    assert caught > 0


def test_same_ring_shared_domain_is_counting_safe():
    """Negative result, pinned deliberately: instances that all ride
    ONE ring (same neighbour set) may share a barrier domain without
    violating any invariant — the pooled counter still bounds
    inter-rank skew to less than one instance, because entering
    instance k needs 2(k+1) cumulative signals and the two neighbours
    have sent at most their own entry counts. The distinct domains the
    runtime still assigns (channels.py::_ring_stream) are required by
    Mosaic's collective_id contract and by CROSS-ring composites (see
    the cross-axis mutation above), not by this schedule semantics."""
    for seed in range(10):
        C.simulate_stream_concurrent(
            5, C.Strategy(seed), bursts=3, domains=(0, 0)
        )
        for lag in range(5):
            grp = [r for r in range(5) if r != lag]
            C.simulate_stream_concurrent(
                5, C.FavourSetStrategy(grp, seed), bursts=3,
                domains=(0, 0),
            )


def test_mutation_misordered_program_deadlocks_loudly():
    """One rank running its burst's channels in swapped order (the
    divergent-MPMD ordering bug): with DISTINCT domains the misordered
    barrier deadlocks loudly on every schedule."""
    for seed in range(10):
        with pytest.raises(C.DeadlockError):
            C.simulate_stream_concurrent(
                4, C.Strategy(seed), swap_order_rank=1
            )


def test_mutation_misordered_program_shared_domain_clobbers():
    """The same ordering bug with a SHARED domain: the pooled barrier
    lets the misordered rank through, and the failure degrades to the
    silent-on-hardware scratch clobber — which the fuzzer still sees."""
    kinds = set()
    for seed in range(20):
        try:
            C.simulate_stream_concurrent(
                4, C.Strategy(seed), domains=(0, 0), swap_order_rank=1
            )
        except C.ProtocolError as e:
            kinds.add(type(e).__name__)
    assert "ClobberError" in kinds


def test_mutation_wrong_logical_ids_is_caught():
    """The round-3 subset-axis bug, reinstated: identity device ids on
    rings spanning a SUBSET of the mesh axes cross-signal other rings'
    ranks. The fuzzer sees it as clobbers and deadlocks — the same
    failure the interpret tier reported as semaphore corruption."""
    kinds = set()
    caught = 0
    for seed in range(10):
        strats = [C.Strategy(seed), C.DelayDmaStrategy(seed)] + [
            C.FavourRankStrategy(f, seed) for f in range(8)
        ]
        for strat in strats:
            try:
                C.simulate_halo_exchange(2, 4, strat, wrong_ids=True)
            except C.ProtocolError as e:
                kinds.add(type(e).__name__)
                caught += 1
    assert caught > 0
    assert "ClobberError" in kinds


def test_mutation_overgranting_leaks():
    """Dropping the kernels' final-grant suppression (``c + 2 < total``,
    ring.py) leaves surplus credits at exit — the composite harness
    reports the leak on every schedule."""

    def overgrant_rank(me, n, chunks, direction=1):
        dst = (me + direction) % n
        upstream = (me - direction) % n
        yield from C._barrier_steps(me, n)
        for c, chunk in enumerate(chunks):
            slot = c % 2
            if c >= 2:
                yield ("wait", C.SEM_CREDIT, slot, 1)
            yield ("dma", dst, slot, chunk, slot, slot)
            yield ("wait", C.SEM_RECV, slot, 1)
            arrived = yield ("read_slot", slot)
            yield ("output", c, arrived)
            yield ("signal", upstream, C.SEM_CREDIT, slot, 1)
            yield ("wait", C.SEM_SEND, slot, 1)

    for seed in range(10):
        gens = [
            C.chain_programs(
                C.instance_steps(
                    overgrant_rank(g, 4, [(g, k) for k in range(4)]),
                    domain=0, instance=0,
                )
            )
            for g in range(4)
        ]
        with pytest.raises(C.CreditLeakError):
            C.RingSimulator(gens, C.Strategy(seed)).run()


def test_exhaustive_tiny_concurrent_composite():
    """EVERY scheduler interleaving (communication-boundary granularity)
    of the smallest concurrent composite — a 2-rank ring running two
    back-to-back streams on distinct barrier domains with shared
    scratch — passes all invariants. (The 2x2 halo's 4-instance
    composite is beyond exhaustive reach; the random/adversarial
    sweeps above cover it.)"""

    def make():
        progs = []
        for g in range(2):
            subs = []
            for stream, direction in ((0, 1), (1, -1)):
                labels = [((g, stream), k) for k in range(2)]
                subs.append(C.instance_steps(
                    C.neighbour_stream_rank(
                        g, 2, labels, direction=direction
                    ),
                    domain=stream, instance=stream,
                ))
            progs.append(C.chain_programs(*subs))
        return progs

    explored = C.explore_all_schedules(make, max_schedules=400_000)
    assert explored > 1000  # genuinely many distinct schedules
