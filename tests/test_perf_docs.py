"""Perf tables in README/docs must quote the committed measurements.

The satellite guard behind the PERF.json -> docs regeneration: every
headline number the prose quotes is re-derived here from the committed
measurement and string-matched against the documents, so a re-measure
that edits `PERF.json` without regenerating the tables fails loudly
instead of drifting (the r5 state quoted 124.6 TF/s against a
committed 124.8957, and 131.6 Gcell/s against 131.7385).

Pure text checks — no JAX, no devices.
"""

import json
import os
from decimal import ROUND_HALF_UP, Decimal

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _load():
    with open(os.path.join(ROOT, "PERF.json")) as f:
        perf = json.load(f)
    return {m["metric"]: m for m in perf["metrics"]}


def _read(name):
    with open(os.path.join(ROOT, name)) as f:
        return f.read()


def _round(value, places: int) -> str:
    """Round-half-up to the doc's quoted precision (Python's round()
    is banker's rounding — 1.275 must quote as 1.28, not 1.27)."""
    q = Decimal(1).scaleb(-places)
    return str(Decimal(str(value)).quantize(q, rounding=ROUND_HALF_UP))


#: (metric, decimals, files the quote must appear in). Decimals follow
#: the tables' own precision: 1 for TF/s / Gcell/s rates, 2 for
#: Mtoken/s throughputs.
HEADLINES = [
    ("stencil_temporal_gcells", 1,
     ("README.md", "docs/perf_notes.md", "docs/tuning.md")),
    ("stencil_fused_gcells", 1, ("README.md",)),
    ("stencil_temporal_vs_fused", 1, ("README.md",)),
    ("flash_attn_fwd_s32768_bf16_causal", 1,
     ("README.md", "docs/perf_notes.md")),
    ("flash_attn_fwd_s8192_bf16", 1, ("README.md", "docs/tuning.md")),
    ("flash_attn_fwd_s16384_bf16", 1, ("README.md",)),
    ("flash_attn_fwd_s32768_bf16_window4096", 1,
     ("README.md", "docs/tuning.md")),
    ("flash_attn_train_tflops_bf16", 1, ("README.md",)),
    ("flash_attn_train_tokens_s32768_window4096_bf16", 2, ("README.md",)),
    ("flash_attn_train_tokens_s65536_window4096_bf16", 2, ("README.md",)),
    ("flash_attn_train_tokens_s131072_window4096_bf16", 2, ("README.md",)),
    ("flash_attn_train_tokens_s262144_gqa8_window4096_bf16", 2,
     ("README.md",)),
    ("flash_attn_train_tokens_s524288_gqa8_window4096_bf16", 2,
     ("README.md",)),
    ("flash_vs_stock_default", 1,
     ("README.md", "docs/perf_notes.md", "docs/tuning.md")),
    ("flash_vs_stock_swept", 2, ("README.md", "docs/tuning.md")),
    ("transformer_train_tokens_s32768_window4096_bf16", 2, ("README.md",)),
    ("transformer_train_tokens_s8192_window4096_l4_bf16", 3,
     ("README.md",)),
    ("transformer_train_tokens_s32768_window4096_l4_bf16", 3,
     ("README.md",)),
]


@pytest.mark.parametrize("metric,places,files", HEADLINES,
                         ids=[m for m, _, _ in HEADLINES])
def test_doc_quotes_committed_measurement(metric, places, files):
    metrics = _load()
    assert metric in metrics, f"{metric} missing from PERF.json"
    want = _round(metrics[metric]["value"], places)
    for name in files:
        text = _read(name)
        assert want in text, (
            f"{name} does not quote {metric} = {want} "
            f"(committed value {metrics[metric]['value']}); the perf "
            f"table drifted from PERF.json — regenerate the quoted "
            f"number"
        )


def test_no_known_stale_values_left():
    """The two drifts this PR fixed must not reappear verbatim."""
    readme = _read("README.md")
    notes = _read("docs/perf_notes.md")
    assert "124.6 TFLOP/s" not in readme + notes
    assert "131.6 Gcell/s" not in readme


def test_seeded_plan_cache_matches_perf_json_measured_best():
    """The shipped plan-cache seeds (tuning-PR satellite) quote the
    committed measurements: the bf16 forward tiles must equal the
    hand-swept blocks recorded in ``flash_vs_stock_swept`` and the r5
    bq=1024 forward tile, and the temporal depth must equal the
    measured knee of ``stencil_temporal_gcells``. A re-measure that
    edits PERF.json without re-seeding fails here, the same discipline
    as the doc tables. (Imports the tuning package — pure Python paths,
    no devices.)"""
    from smi_tpu.tuning import seeded

    metrics = _load()
    swept = metrics["flash_vs_stock_swept"]["config"]["block_q_kmajor_k"]
    assert seeded.SEEDED_FLASH_BF16_BLOCKS == (swept[0], swept[2]), (
        "seeded bf16 flash blocks drifted from the measured sweep in "
        "PERF.json (flash_vs_stock_swept block_q_kmajor_k)"
    )
    assert (metrics["stencil_temporal_gcells"]["config"]["depth"]
            == seeded.SEEDED_STENCIL_DEPTH), (
        "seeded temporal depth drifted from the measured knee in "
        "PERF.json (stencil_temporal_gcells)"
    )
    # the windowed seed narrows bk: the PERF row it cites must still be
    # the window=4096 config it was measured at
    cfg = metrics["flash_attn_fwd_s32768_bf16_window4096"]["config"]
    assert cfg["window"] == 4096
    assert seeded.SEEDED_FLASH_BF16_WINDOW_BLOCKS[1] < (
        seeded.SEEDED_FLASH_BF16_BLOCKS[1]
    )


def test_robustness_doc_quotes_elastic_config():
    """docs/robustness.md's "Elastic runtime" section must state the
    detector thresholds, confirmation grace, watchdog budget, and
    checkpoint cadence the code ships — the same discipline as the
    tuning decision table: the doc is the human-readable mirror of
    ``membership.py``/``checkpoint.py`` and must not drift. (Pure
    Python imports, no devices.)"""
    from smi_tpu.parallel import checkpoint, membership

    text = _read("docs/robustness.md")
    assert f"suspect at phi >= {membership.SUSPECT_PHI:g}" in text
    assert f"confirm dead at phi >= {membership.DEAD_PHI:g}" in text
    assert (f"{membership.CONFIRM_GRACE_TICKS}-tick confirmation grace"
            in text)
    assert f"{membership.WATCHDOG_TICKS}-tick watchdog budget" in text
    assert f"default cadence {checkpoint.DEFAULT_CADENCE}" in text
    assert f"${checkpoint.CADENCE_ENV}" in text
    assert f"${checkpoint.DIR_ENV}" in text


def test_serving_doc_quotes_the_shipped_constants():
    """docs/robustness.md's "Serving under overload" section must
    state the pool size, brownout ceilings, wait caps, deadline
    budgets, wire window, per-route cap formula, and the interactive
    p99 bound the serving code ships — the same drift discipline as
    the elastic section. (Pure Python imports, no devices.)"""
    from smi_tpu.serving import admission, qos, scheduler

    text = _read("docs/robustness.md")
    assert "Serving under overload" in text
    assert (f"pool of {admission.DEFAULT_POOL} stream credits"
            in text)
    for cls, pct in (("best_effort", 50), ("batch", 75),
                     ("interactive", 100)):
        assert qos.CLASS_POOL_CEILING[cls] == pct / 100
        assert f"{cls} {pct}%" in text
    assert (
        f"interactive {qos.CLASS_ADMISSION_WAIT_TICKS['interactive']}"
        f", batch {qos.CLASS_ADMISSION_WAIT_TICKS['batch']}, "
        f"best_effort {qos.CLASS_ADMISSION_WAIT_TICKS['best_effort']}"
        f" ticks" in text
    )
    assert (
        f"interactive {qos.CLASS_DEADLINE_TICKS['interactive']}, "
        f"batch {qos.CLASS_DEADLINE_TICKS['batch']}, best_effort\n"
        f"{qos.CLASS_DEADLINE_TICKS['best_effort']} ticks" in text
    )
    assert (f"WIRE_CREDITS={scheduler.WIRE_CREDITS} per destination "
            f"lane" in text)
    assert f"<= {qos.INTERACTIVE_P99_TICKS}\nticks" in text
    assert (f"{qos.CLASS_ADMISSION_WAIT_TICKS['interactive']}-tick\n"
            f"wait cap" in text)
    assert "2*pool/n streams" in text
    assert "`backpressure:rank<r>`" in text
    assert "`brownout:best_effort`" in text
    # the named fault class and its registry stay quoted
    assert "`faults.SlowConsumer`" in text
    assert "SERVING_FAULT_CLASSES" in text


def test_elasticity_doc_quotes_the_shipped_constants():
    """docs/robustness.md's "Demand elasticity" section must state
    the burn threshold, both sustain windows, the hysteresis
    fraction, the cooldown, the floor, and the env knob names the
    elasticity code ships, plus the migration state machine, the
    migrate-scope properties, and the CLI surfaces — the same drift
    discipline as the serving section. (Pure Python imports, no
    devices.)"""
    from smi_tpu import analysis
    from smi_tpu.serving import elasticity as E

    text = _read("docs/robustness.md")
    assert "Demand elasticity" in text
    for const in ("SCALE_BURN_THRESHOLD", "SCALE_OUT_SUSTAIN_TICKS",
                  "SCALE_IN_BURN_FRACTION", "SCALE_IN_SUSTAIN_TICKS",
                  "SCALE_COOLDOWN_TICKS", "MIN_SERVING_RANKS"):
        value = getattr(E, const)
        assert f"| `{const}` | {value} |" in text, (
            f"{const}={value} missing from the hysteresis table"
        )
    for env in (E.AUTOSCALE_ENV, E.SCALE_COOLDOWN_ENV,
                E.SCALE_BURN_ENV):
        assert f"${env}" in text, f"env knob ${env} undocumented"
    # the migration state machine, every state by name
    for state in ("draining", "handoff", "cutover", "committed",
                  "aborted"):
        assert state in text
    assert "`membership-change`" in text
    # the model tier's migrate-scope properties + both mutants
    for name in ("migration-lost-accepted", "placement-epoch-safety",
                 "cutover_without_handoff",
                 "scale_in_with_residents"):
        assert f"`{name}`" in text, f"{name} undocumented"
    migrate_scope = next(
        s for s in analysis.DEFAULT_SCOPES if s.migrate
    )
    assert (f"tenants={migrate_scope.tenants} "
            f"ranks={migrate_scope.ranks} "
            f"chunks={migrate_scope.chunks} "
            f"streams={migrate_scope.streams} "
            f"pool={migrate_scope.pool} "
            f"consume={migrate_scope.consume} "
            f"migrate={migrate_scope.migrate}" in text), (
        "the migrate scope drifted from DEFAULT_SCOPES"
    )
    # the CLI surfaces
    assert "chaos --load --flash-crowd" in text
    assert "serve --selftest --autoscale" in text


def test_partition_doc_quotes_the_shipped_constants():
    """docs/robustness.md's "Partition tolerance" section must state
    the fault-class trio, the quorum env knob / safe range /
    built-in fraction, the fencing verdict vocabulary (the
    ``ctl.quorum`` event's payload), the three campaign cells with
    their CLI surfaces, and the model tier's partition properties
    and mutants with their convictions — the same drift discipline
    as the elasticity section. (Pure Python imports, no devices.)"""
    from smi_tpu import analysis
    from smi_tpu.parallel import faults as F
    from smi_tpu.parallel import membership as M

    text = _read("docs/robustness.md")
    assert "Partition tolerance" in text
    # the fault trio, by class name, and the registry they ride
    for cls in ("PartitionFault", "AsymmetricLinkFault",
                "FlappingLink"):
        assert cls in text, f"fault class {cls} undocumented"
    assert "PARTITION_FAULT_CLASSES" in text
    assert len(F.PARTITION_FAULT_CLASSES) == 3
    # the quorum knob: env name, built-in fraction, safe range
    assert f"${M.QUORUM_FRACTION_ENV}" in text
    assert f"built-in {M.DEFAULT_QUORUM_FRACTION:g}" in text
    assert "[0.5, 1.0)" in text
    # the full fencing verdict vocabulary, as ctl.quorum emits it
    for verdict in ("minted", "granted", "denied", "stale", "lost",
                    "rejected", "rejoin"):
        assert verdict in text, f"verdict {verdict!r} undocumented"
    assert "`ctl.quorum`" in text
    assert "`QuorumLostError`" in text
    assert "`StaleEpochError`" in text
    # the model tier's partition properties + both mutants, with the
    # conviction mapping the registry ships
    for name in ("no-split-brain", "fenced-actuation",
                 "actuate_without_quorum", "accept_in_minority"):
        assert f"`{name}`" in text, f"{name} undocumented"
    assert (analysis.MODEL_MUTANT_PROPERTY["actuate_without_quorum"]
            == "fenced-actuation")
    assert (analysis.MODEL_MUTANT_PROPERTY["accept_in_minority"]
            == "no-split-brain")
    partition_scopes = [s for s in analysis.DEFAULT_SCOPES
                        if s.partition]
    assert sorted(s.ranks for s in partition_scopes) == [2, 3]
    assert "partition=1" in text
    # the three cells and the CLI surfaces
    for cell in ("partition-heal", "partition-migration-abort",
                 "flapping-link"):
        assert cell in text, f"cell {cell} undocumented"
    assert "FLAP_VECTOR_ATTEMPTS" in text
    assert "chaos --partition" in text
    assert "serve --selftest --partition" in text


def test_inference_doc_quotes_the_shipped_constants():
    """docs/robustness.md's "Streaming inference" section must state
    the disaggregation split, the request lifecycle states, the
    engine knobs (gen length, prefill pacing, the saturation blame
    threshold), the handoff-vs-replay reason vocabulary, the six
    campaign cells with their CLI surfaces, and the model tier's
    infer-scope properties and mutants with their convictions — the
    same drift discipline as the partition section. (Pure Python
    imports, no devices.)"""
    from smi_tpu import analysis
    from smi_tpu.serving import campaign as C
    from smi_tpu.serving import inference as I

    text = _read("docs/robustness.md")
    assert "Streaming inference" in text
    # the split rule, literally
    assert "`decode_ranks_for(n)`" in text
    assert "`range(n // 2, n)`" in text
    # the full request lifecycle, every state by name
    for state in I.REQUEST_STATES:
        assert f"`{state}`" in text, f"state {state} undocumented"
    # the engine knobs, quoted at their shipped values
    for const in ("PREFILL_TICKS_PER_CHUNK", "DEFAULT_GEN_LEN",
                  "MIN_INFER_DURATION", "SATURATION_SHED_MIN"):
        value = getattr(I, const)
        assert f"| `{const}` | {value} |" in text, (
            f"{const}={value} missing from the knob table"
        )
    assert (f"interactive={I.PROMPT_CHUNKS['interactive']}, "
            f"batch={I.PROMPT_CHUNKS['batch']}" in text)
    # the two recovery paths' reason vocabulary
    assert "`failover:rank<r>`" in text
    assert "`blame:backpressure:rank<r>`" in text
    assert "replayed_prefills" in text
    # the model tier's infer-scope properties + both mutants, with
    # the conviction mapping the registry ships
    for name in ("kv-shard-safety", "generation-lost-accepted",
                 "decode_failover_without_kv_handoff",
                 "stale_kv_after_cutover"):
        assert f"`{name}`" in text, f"{name} undocumented"
    assert (analysis.MODEL_MUTANT_PROPERTY[
        "decode_failover_without_kv_handoff"] == "kv-shard-safety")
    assert (analysis.MODEL_MUTANT_PROPERTY["stale_kv_after_cutover"]
            == "generation-lost-accepted")
    infer_scopes = [s for s in analysis.DEFAULT_SCOPES if s.infer]
    assert [s.ranks for s in infer_scopes] == [2]
    assert "infer=1" in text
    # the six cells and the CLI surfaces
    assert len(C.INFER_CELLS) == 6
    for cell, _ in C.INFER_CELLS:
        assert cell in text, f"cell {cell} undocumented"
    assert "chaos --infer" in text
    assert "serve --selftest --infer" in text
    assert "traced_kv_dataflow" in text
    assert "inference_fields" in text


def test_two_tier_docs_quote_the_shipped_rates_and_gates():
    """The r6 two-tier sections (docs/tuning.md decision table,
    docs/perf_notes.md "Two-tier collectives (r6)") must state the
    tier rates, env override names, and confidence margin the code
    ships — the same drift discipline as every other table. (Pure
    Python imports: cost_model and engine constants, no devices.)"""
    from smi_tpu.tuning import cost_model as cm
    from smi_tpu.tuning.engine import HIER_MODEL_MARGIN

    tuning = _read("docs/tuning.md")
    notes = _read("docs/perf_notes.md")
    assert "Two-tier collectives (r6)" in notes
    for text in (tuning, notes):
        assert f"{cm.V5E_ICI_BETA_BYTES_PER_S / 1e9:g} GB/s" in text
        assert f"{cm.DCN_BETA_BYTES_PER_S / 1e9:g} GB/s" in text
        assert f"{cm.DCN_ALPHA_S * 1e6:g} us" in text
        assert f"${cm.DCN_BETA_ENV}" in text
    # the three candidates and the gate ladder live in the table
    for name in ("ring", "rs_ag", "hierarchical"):
        assert name in tuning
    assert "SMI_TPU_HIER_MIN_SLICES" in tuning
    assert f"{HIER_MODEL_MARGIN:g}x" in tuning


def test_two_tier_docs_quote_the_simulated_wallclock(monkeypatch):
    """The quoted 2x2-pod wall-clock numbers are re-derived from the
    deterministic credits simulator, so the docs can never drift from
    what the tier-1 assertion actually measures. (Pure Python — the
    simulator and cost model import no JAX.) The docs quote the
    PUBLISHED rates, so a fleet $SMI_TPU_DCN_BETA must not leak into
    the recomputation."""
    from smi_tpu.parallel import credits as C
    from smi_tpu.tuning import cost_model as cm

    monkeypatch.delenv(cm.DCN_BETA_ENV, raising=False)
    rep = C.pod_wallclock_comparison(2, 2, 4 << 20)
    flat_us = f"{round(rep['flat_s'] * 1e6, 1):g}"
    hier_us = f"{round(rep['hierarchical_s'] * 1e6, 1):g}"
    speedup = f"{rep['flat_s'] / rep['hierarchical_s']:.1f}x"
    for name in ("docs/tuning.md", "docs/perf_notes.md"):
        text = _read(name)
        assert flat_us in text, (
            f"{name} does not quote the simulated flat wall-clock "
            f"{flat_us} us — regenerate the two-tier numbers"
        )
        assert hier_us in text, (
            f"{name} does not quote the simulated two-tier wall-clock "
            f"{hier_us} us — regenerate the two-tier numbers"
        )
    assert speedup in _read("docs/perf_notes.md")


def test_analysis_doc_quotes_the_shipped_checks():
    """docs/analysis.md is the human-readable mirror of
    ``smi_tpu/analysis`` and the traffic lint tier: every check the
    verifier runs, every registered protocol, every mutant class, and
    every HLO lint rule the code ships must be named in the doc — the
    same drift discipline as docs/tuning.md. (Pure Python imports, no
    devices.)"""
    from smi_tpu import analysis
    from smi_tpu.parallel import credits, faults, traffic

    text = _read("docs/analysis.md")
    for check in analysis.CHECKS:
        assert f"`{check}`" in text, f"check {check} undocumented"
    for mutant in analysis.MUTANTS:
        assert f"`{mutant}`" in text, f"mutant {mutant} undocumented"
    # the consolidated registry is the enumeration every tier (and
    # this doc) derives from; the fault layer's historical names must
    # stay re-exports of the same tuples
    registered = credits.registered_protocols()
    assert registered == (faults.PROTOCOLS + faults.CHUNKED_PROTOCOLS
                          + faults.POD_PROTOCOLS
                          + faults.ALLTOALL_PROTOCOLS
                          + faults.QUANTIZED_PROTOCOLS)
    for protocol in registered:
        assert f"`{protocol}`" in text, f"{protocol} undocumented"
    # the default shape grid covers exactly the registered protocols
    assert set(analysis.DEFAULT_SHAPES) == set(registered)
    for rule in traffic.TRAFFIC_LINT_CHECKS:
        assert f"`{rule}`" in text, f"lint rule {rule} undocumented"
    # the honesty clauses: what the static tier does NOT prove
    assert "fault-free only" in text
    assert f"`analysis.MAX_LINT_N` ({analysis.MAX_LINT_N})" in text
    assert "smi-tpu lint" in text
    assert "--check --lint" in text
    assert "traffic dump.hlo --lint" in text


def test_analysis_doc_quotes_the_model_tier():
    """docs/analysis.md's "Model-checked control plane" section must
    name every checked property, every control-plane mutant with its
    convicting property, and every default scope the code ships — the
    same drift discipline as the protocol-tier check/mutant tables.
    (Pure Python imports, no devices.)"""
    from smi_tpu import analysis

    text = _read("docs/analysis.md")
    assert "Model-checked control plane" in text
    for prop in analysis.PROPERTIES:
        assert f"`{prop}`" in text, f"property {prop} undocumented"
    for mutant in analysis.MODEL_MUTANTS:
        assert f"`{mutant}`" in text, f"mutant {mutant} undocumented"
        # the conviction column quotes the exactly-one property
        row = next(line for line in text.splitlines()
                   if line.startswith(f"| `{mutant}`"))
        assert f"`{analysis.MODEL_MUTANT_PROPERTY[mutant]}`" in row, (
            f"{mutant}'s documented conviction drifted from "
            f"MODEL_MUTANT_PROPERTY"
        )
    # the scope grid table quotes the shipped DEFAULT_SCOPES
    for scope in analysis.DEFAULT_SCOPES:
        row = (f"tenants={scope.tenants}, ranks={scope.ranks}, "
               f"chunks={scope.chunks}, streams={scope.streams}, "
               f"pool={scope.pool}")
        assert row in text, (
            f"default scope {scope.describe()} missing from the "
            f"scope grid table"
        )
        if scope.kill:
            assert f"{row}, kill={scope.kill}" in text
        if scope.silence:
            assert f"{row}, silence={scope.silence}" in text
    # the honesty clause: what small-scope exhaustiveness does NOT
    # prove, and the no-silent-caps coverage fields
    assert "does not prove" in text
    assert "small-scope hypothesis" in text
    for field in ("`explored`", "`estimated_total`", "`truncated`"):
        assert field in text, f"coverage field {field} undocumented"
    assert "lint --model" in text
    assert "replay_model_trace" in text


def test_perf_rule_constants_pin_their_cost_model_mirrors():
    """The perf tier's thresholds are MIRRORS of cost-model/traffic
    quantities, not free parameters: the VMEM double-buffer bound is
    half the scoped-VMEM frame, the analytic drift bound is the
    documented 25%, and the flash footprint helper decomposes the cost
    model's double-buffered bookkeeping exactly. (Pure Python imports,
    no devices.)"""
    from smi_tpu import analysis
    from smi_tpu.analysis import perf
    from smi_tpu.parallel import traffic
    from smi_tpu.tuning import cost_model as cm

    assert analysis.VMEM_DOUBLE_BUFFER_BOUND == cm.VMEM_LIMIT_BYTES // 2
    assert analysis.ANALYTIC_DRIFT_FRACTION == 0.25
    assert 0.0 < analysis.IDLE_FRACTION_THRESHOLD < 1.0
    assert 0.0 < analysis.BELOW_ROOFLINE_FRACTION < 1.0
    # single-buffer + one more tile generation == the cost model's
    # double-buffered footprint, for every default target
    for bq, bk in ((512, 512), (512, 1024), (1024, 512), (1024, 1024),
                   (4096, 4096)):
        for itemsize in (2, 4):
            tiles = (bq * 128 + 2 * bk * 128) * itemsize
            assert (perf.flash_single_buffer_bytes(bq, bk, 128, itemsize)
                    + tiles
                    == cm.flash_fwd_vmem_bytes(bq, bk, 128, itemsize))
    # the tier rates the decomposition prices at ARE the published
    # cost-model rates (same constants traffic.py mirrors)
    assert cm.V5E_ICI_BETA_BYTES_PER_S == traffic.V5E_ICI_LINK_BYTES_PER_S
    from smi_tpu.parallel import credits as C

    costs = C.default_tier_costs(1.0)
    assert costs.ici.alpha_s == cm.DEFAULT_ALPHA_S
    assert costs.ici.beta_bytes_per_s == cm.V5E_ICI_BETA_BYTES_PER_S


def test_analyzer_reproduces_elapsed_seconds_on_the_full_grid():
    """The acceptance bar restated next to the pins: for EVERY
    registered protocol at every default shape, the static makespan
    decomposition equals ``RingSimulator.elapsed_seconds()`` exactly
    (``==``, not approx), and the committed two-tier acceptance
    vectors reproduce to the tenth of a microsecond. (Pure Python —
    the simulator and analyzer import no JAX.)"""
    from smi_tpu import analysis
    from smi_tpu.analysis.perf import PERF_PAYLOAD_BYTES, _costs_for
    from smi_tpu.analysis.verifier import build_generators
    from smi_tpu.parallel import credits as C

    for protocol, shapes in sorted(analysis.DEFAULT_SHAPES.items()):
        for shape in shapes:
            rep = analysis.decompose_protocol(protocol, **shape)
            costs, _m, _k = _costs_for(protocol, dict(shape),
                                       float(PERF_PAYLOAD_BYTES))
            sim = C.RingSimulator(
                build_generators(protocol, shape["n"],
                                 chunks=shape.get("chunks", 3),
                                 slices=shape.get("slices", 2)),
                C.Strategy(0), costs=costs,
            )
            sim.run()
            assert rep.makespan_s == sim.elapsed_seconds(), (
                protocol, shape,
            )
    pod = analysis.decompose_protocol("allreduce_pod", n=4, slices=2)
    assert round(pod.makespan_s * 1e6, 1) == 1197.3
    assert analysis.ANALYTIC_EXPECTED_US[
        "pod_allreduce_flat_2x2_4mib_us"] == 4894.3
    assert analysis.ANALYTIC_EXPECTED_US[
        "pod_allreduce_two_tier_2x2_4mib_us"] == 1197.3


def test_analysis_doc_quotes_the_perf_tier():
    """docs/analysis.md's "Static performance tier" section must name
    every decomposition component, every perf rule, every perf mutant
    with its convicting rule, the thresholds, and the honesty clauses
    (fault-free only; ATLAS: measurement outranks the analytics) —
    the same drift discipline as the safety-tier tables."""
    from smi_tpu import analysis

    text = _read("docs/analysis.md")
    assert "Static performance tier" in text
    for check in analysis.PERF_CHECKS:
        assert f"`{check}`" in text, f"perf rule {check} undocumented"
    for component in ("alpha", "beta", "serialization", "idle"):
        assert f"`{component}`" in text, (
            f"component {component} undocumented"
        )
    for mutant in analysis.PERF_MUTANTS:
        assert f"`{mutant}`" in text, f"perf mutant {mutant} undocumented"
        row = next(line for line in text.splitlines()
                   if line.startswith(f"| `{mutant}`"))
        from smi_tpu.analysis.perf_mutants import PERF_MUTANT_RULE

        assert f"`{PERF_MUTANT_RULE[mutant]}`" in row, (
            f"{mutant}'s documented conviction drifted from "
            f"PERF_MUTANT_RULE"
        )
    # thresholds quoted at their shipped values
    assert f"({analysis.IDLE_FRACTION_THRESHOLD:g})" in text
    assert f"({analysis.BELOW_ROOFLINE_FRACTION:g})" in text
    assert f"({analysis.ANALYTIC_DRIFT_FRACTION:.0%})" in text
    assert (f"{analysis.VMEM_DOUBLE_BUFFER_BOUND // 1024} KiB"
            in text)
    # the acceptance vectors are quoted
    assert "4894.3" in text and "1197.3" in text
    # honesty clauses: fault-free scope + ATLAS precedence
    assert "Fault-free\nschedules only" in text.replace("\r", "") or (
        "Fault-free schedules only" in " ".join(text.split())
    )
    assert ("measurement outranks any analytic prediction"
            in " ".join(text.split()))
    assert "lint --perf" in text
    assert "--combined" in text
    assert "depends_on_collective" in text
    assert "excluded" in text  # the no-silent-caps tile satellite
    # README carries the new gate commands
    readme = _read("README.md")
    assert "lint --perf --all" in readme
    assert "lint --combined" in readme


def test_bench_scoreboard_baselines_pin_the_committed_artifacts():
    """The bench.py scoreboard's baselines are the committed
    artifacts, not free constants: the stencil baseline is
    BENCH_r05.json's parsed headline, the flash row quotes a real
    PERF.json metric, the allreduce curve is the analyzer's committed
    expectation set, and the committed-only scoreboard passes every
    verdict (a clean tree regresses nothing)."""
    import bench

    r05 = json.load(open(os.path.join(ROOT, "BENCH_r05.json")))
    assert bench.BENCH_R05_STENCIL_CELLS == r05["parsed"]["value"]
    metrics = _load()
    assert bench.SCOREBOARD_FLASH_METRIC in metrics
    # the flash baseline is a PINNED constant equal to the committed
    # measurement — a self-comparison could never regress; a PERF.json
    # re-measure that lands lower must flip the verdict (and fail
    # here until the baseline is consciously re-pinned)
    assert bench.SCOREBOARD_FLASH_TFLOPS_BASELINE == round(
        metrics[bench.SCOREBOARD_FLASH_METRIC]["value"], 2
    )
    board = bench.scoreboard_fields()
    assert set(board) == {"stencil_gcells_per_chip",
                          "flash_train_tflops",
                          "allreduce_payload_curve_us",
                          "alltoall_payload_curve_us",
                          "compression"}
    for name, entry in board.items():
        assert entry["verdict"] == "pass", (name, entry)
        assert entry["measured"] is False
    from smi_tpu.analysis.perf import ANALYTIC_EXPECTED_US

    curve = board["allreduce_payload_curve_us"]
    assert curve["baseline"] == [
        ANALYTIC_EXPECTED_US[f"allreduce_n8_{kb}kib_us"]
        for kb in curve["payload_kib"]
    ]
    a2a = board["alltoall_payload_curve_us"]
    assert a2a["baseline"] == [
        ANALYTIC_EXPECTED_US[f"alltoall_n8_{kb}kib_us"]
        for kb in a2a["payload_kib"]
    ]
    comp = board["compression"]
    assert comp["precision"] == "int8"
    assert comp["baseline"] == [
        ANALYTIC_EXPECTED_US[f"allreduce_int8_n8_{kb}kib_us"]
        for kb in comp["payload_kib"]
    ]
    # live mode: a measured stencil run flips the verdict honestly
    live = bench.scoreboard_fields(r05["parsed"]["value"])
    assert live["stencil_gcells_per_chip"]["measured"] is True
    assert live["stencil_gcells_per_chip"]["verdict"] == "pass"
    worse = bench.scoreboard_fields(
        r05["parsed"]["value"] * (1 - 2 * bench.SCOREBOARD_TOLERANCE)
    )
    assert worse["stencil_gcells_per_chip"]["verdict"] == "regress"
    # the legacy line contract is untouched, and a verdict-less
    # scoreboard is refused (the schema guard)
    payload = {"metric": "m", "value": 1, "unit": "u",
               "vs_baseline": 1, "scoreboard": board}
    line = bench.render_line(payload)
    assert "\n" not in line and json.loads(line)["scoreboard"]
    broken = {k: dict(v) for k, v in board.items()}
    del broken["flash_train_tflops"]["verdict"]
    payload["scoreboard"] = broken
    with pytest.raises(ValueError, match="verdict"):
        bench.render_line(payload)


def test_alltoall_docs_quote_the_shipped_candidates_and_vectors(
        monkeypatch):
    """The r12 all-to-all sections (docs/tuning.md candidate table,
    docs/analysis.md pricing conventions + skewed scope) must state
    the candidates, the env override, the model margin, and the
    simulated acceptance vectors the code ships — re-derived from the
    deterministic simulator so the quoted numbers can never drift from
    what tier-1 asserts. (Pure Python, no devices.)"""
    from smi_tpu import analysis
    from smi_tpu.parallel import collectives as coll_consts
    from smi_tpu.parallel import credits as C
    from smi_tpu.tuning import cost_model as cm
    from smi_tpu.tuning.engine import ALLTOALL_MODEL_MARGIN

    tuning = _read("docs/tuning.md")
    for name in ("pairwise", "bruck", "hierarchical"):
        assert f"`{name}`" in tuning, f"candidate {name} undocumented"
    assert coll_consts.ALLTOALL_ALGO_ENV in tuning
    assert f"{ALLTOALL_MODEL_MARGIN:g}x" in tuning
    assert "power-of-two" in tuning
    # the simulated 2x2 1 MiB-block acceptance vectors, re-derived at
    # the published rates (no fleet $SMI_TPU_DCN_BETA leakage)
    monkeypatch.delenv(cm.DCN_BETA_ENV, raising=False)
    dcn = C.LinkCost(cm.DCN_ALPHA_S, cm.DCN_BETA_BYTES_PER_S)
    rep = C.alltoall_wallclock_comparison(2, 2, float(1 << 20), dcn=dcn)
    pair_us = f"{round(rep['pairwise_s'] * 1e6, 1):g}"
    hier_us = f"{round(rep['hierarchical_s'] * 1e6, 1):g}"
    for name in ("docs/tuning.md", "docs/analysis.md"):
        text = _read(name)
        assert pair_us in text, (
            f"{name} does not quote the simulated flat pairwise "
            f"wall-clock {pair_us} us — regenerate the all-to-all "
            f"numbers"
        )
        assert hier_us in text, (
            f"{name} does not quote the simulated two-tier wall-clock "
            f"{hier_us} us — regenerate the all-to-all numbers"
        )
    # the committed expectations match the recomputed vectors exactly
    assert analysis.ANALYTIC_EXPECTED_US[
        "alltoall_pairwise_2x2_1mib_us"] == float(pair_us)
    assert analysis.ANALYTIC_EXPECTED_US[
        "alltoall_two_tier_2x2_1mib_us"] == float(hier_us)
    # the skewed-routing scope is in the model grid AND documented
    skewed = [s for s in analysis.DEFAULT_SCOPES if s.hot_rank >= 0]
    assert skewed, "the skewed-routing scope left the default grid"
    doc = _read("docs/analysis.md")
    for scope in skewed:
        assert f"hot_rank={scope.hot_rank}" in doc
    assert "`hot_rank`" in doc


def test_alltoall_registry_digest_is_pinned():
    """The consolidated registry is digest-pinned: a registry edit is
    a conscious, test-visible act — in particular the seed-pinned
    chaos sweep's draw set (PROTOCOLS) can never grow silently."""
    import hashlib

    from smi_tpu.parallel import credits

    regs = credits.all_protocol_registries()
    assert list(regs) == ["PROTOCOLS", "CHUNKED_PROTOCOLS",
                          "POD_PROTOCOLS", "ALLTOALL_PROTOCOLS",
                          "QUANTIZED_PROTOCOLS"]
    assert regs["PROTOCOLS"] == (
        "all_gather", "all_reduce", "reduce_scatter",
        "neighbour_stream",
    )
    digest = hashlib.sha256(repr(sorted(
        (name, tuple(protos)) for name, protos in regs.items()
    )).encode()).hexdigest()
    assert digest == (
        "e74b8e143b28171692803cb2884723398f0e3903772e0c76d28b73fd"
        "4aae5dd0"
    ), (
        f"protocol registries changed (digest {digest}) — if this is "
        f"deliberate, update the pin AND confirm the seed-pinned "
        f"chaos sweep (which draws from PROTOCOLS) is unaffected"
    )


def test_tuning_doc_quotes_the_seeded_knobs():
    """docs/tuning.md's decision table must state the seeded values the
    code ships (block tiles, depth, threshold) — the table is the
    human-readable mirror of ``smi_tpu/tuning/seeded.py``."""
    from smi_tpu.tuning import seeded

    text = _read("docs/tuning.md")
    bq, bk = seeded.SEEDED_FLASH_BF16_BLOCKS
    assert f"{bq} / {bk}" in text
    wq, wk = seeded.SEEDED_FLASH_BF16_WINDOW_BLOCKS
    assert f"{wq} / {wk}" in text
    assert f"| {seeded.SEEDED_STENCIL_DEPTH} |" in text
    assert str(seeded.SEEDED_RS_AG_MIN_BYTES) in text


def test_observability_doc_quotes_the_schema():
    """docs/observability.md must render the REAL event schema, the
    recorder bounds, the metric catalog, and the trace schema version
    — the doc is the human-readable mirror of ``smi_tpu/obs`` and
    must not drift from the code registry."""
    from smi_tpu.obs import events as E
    from smi_tpu.obs import trace as T

    text = _read("docs/observability.md")
    # every registered event kind appears in the schema table
    for kind in E.EVENT_KINDS:
        assert f"`{kind}`" in text, (
            f"event kind {kind!r} missing from the schema table"
        )
    # a documented kind that no longer exists is equally a drift
    import re

    # scan the schema TABLE only (the r15 span taxonomy legitimately
    # names dotted components like `credit.stall` further down)
    schema_section = text.split("## Event schema", 1)[1].split(
        "\n## ", 1)[0]
    documented = set(re.findall(r"`((?:credit|dma|barrier|serve|ctl|"
                                r"tune|slo)\.[a-z_]+)`",
                                schema_section))
    assert documented == set(E.EVENT_KINDS)
    # recorder bounds
    assert f"**{E.DEFAULT_RECORDER_CAPACITY} events**" in text
    assert f"**{E.DEFAULT_TAIL_EVENTS} events**" in text
    # pinned trace schema version
    assert f"schema version {T.TRACE_SCHEMA_VERSION}" in text
    # the shipped metric catalog: every instrument the serving stack
    # feeds must be documented (names as used in the registry keys)
    for metric in (
        "admitted_total", "parked_total", "shed_total",
        "sent_chunks_total", "consumed_chunks_total",
        "delivered_total", "replayed_chunks_total",
        "integrity_errors_total", "membership_transitions_total",
        "epoch_bumps_total", "credit_stall_ticks",
        "wire_lane_occupancy", "queue_depth", "pool_occupancy",
        "admission_wait_ticks", "stream_latency_ticks",
        "tune_samples_total", "tune_proposals_total",
        "tune_swaps_total", "tune_rollbacks_total",
        "slo_burn_warnings_total", "slo_breaches_total",
        "slo_recoveries_total",
    ):
        assert f"`{metric}`" in text, (
            f"metric {metric!r} missing from the catalog"
        )


def test_observability_doc_quotes_the_span_slo_tier():
    """The "Spans, blame, and SLOs (r15)" section must quote the REAL
    span taxonomy, the shipped SLO table, the burn windows/floor, and
    the env knob — the doc is the human-readable mirror of
    ``smi_tpu/obs/spans.py`` + ``slo.py`` and must not drift."""
    from smi_tpu.obs import slo as S
    from smi_tpu.obs import spans as SP
    from smi_tpu.obs.events import OBS_RING_ENV

    text = _read("docs/observability.md")
    assert "Spans, blame, and SLOs (r15)" in text
    section = text.split("Spans, blame, and SLOs (r15)", 1)[1]
    # every span component appears in the taxonomy table
    for component in SP.COMPONENTS:
        assert f"`{component}`" in section, (
            f"span component {component!r} missing from the taxonomy"
        )
    # the shipped SLO table, value for value
    for qos, spec in S.DEFAULT_SLOS.items():
        assert f"`{qos}` | {spec.latency_target_ticks} | " \
               f"{spec.error_budget}" in section, (
            f"SLO row for {qos} drifted from DEFAULT_SLOS"
        )
    # burn windows, evidence floor, decile, env knob
    assert (f"({S.SLO_WINDOWS[0]} / {S.SLO_WINDOWS[1]} ticks)"
            in section)
    assert f"**{S.MIN_WINDOW_EVENTS} events**" in section
    assert f"{SP.BLAME_DECILE:.0%}" in section
    assert f"${OBS_RING_ENV}" in section
    # the honesty clauses
    assert "health observation, not a campaign gate" in section
    assert "does not claim" in text.split(
        "Spans, blame, and SLOs (r15)", 1)[1]


def test_tuning_doc_quotes_the_online_retuner():
    """docs/tuning.md's "Online retuning (r14)" section must quote the
    shipped thresholds, env knobs, swap states, model-checker
    properties, and the convicted mutant — the doc is the
    human-readable mirror of ``smi_tpu/tuning/online.py`` +
    ``swap.py`` and must not drift from the code. (Pure Python
    imports, no devices.)"""
    from smi_tpu.tuning import online, swap as S

    text = _read("docs/tuning.md")
    assert "Online retuning (r14)" in text
    section = text.split("Online retuning (r14)", 1)[1]
    # thresholds + env knobs
    assert str(online.DEFAULT_RETUNE_MIN_SAMPLES) in section
    assert f"{online.DEFAULT_RETUNE_MARGIN:g}x" in section
    assert str(online.QUIESCE_TIMEOUT_TICKS) in section
    for env in (online.ONLINE_RETUNE_ENV, online.MIN_SAMPLES_ENV,
                online.MARGIN_ENV):
        assert env in section, f"env knob {env} undocumented"
    # every swap state appears in the state diagram
    for state in S.SWAP_STATES:
        assert f"`{state}`" in section, f"state {state} undocumented"
    # the model-checker story: both properties, the headline mutant,
    # and the honesty clause
    assert "`plan-epoch-safety`" in section
    assert "`swap-lost-accepted`" in section
    assert "`swap_without_quiesce`" in section
    assert "does not prove" in section
    # the resolution ladder names the live tier
    assert "live" in section and "tune --online" in section


def test_roofline_closure_docs_quote_the_shipped_pipeline():
    """The r18 section (docs/perf_notes.md "Roofline closure (r18)",
    docs/tuning.md decision-table row) must state the seeded knobs,
    the modeled sweep costs, and the replay-proven overlap the code
    ships — re-derived from the cost model and the stripe-stream
    decomposition so the quoted numbers can never drift."""
    from smi_tpu.analysis import perf as aperf
    from smi_tpu.tuning import cost_model as cm
    from smi_tpu.tuning.seeded import SEEDED_STENCIL_PIPELINE_KNOBS

    notes = _read("docs/perf_notes.md")
    assert "## Roofline closure (r18)" in notes
    section = notes.split("## Roofline closure (r18)")[1].split(
        "\n## ")[0]
    # the decision table quotes the shipped candidate pricing
    cands = cm.stencil_pipeline_candidates()
    best = cands[0]
    sync = next(c for c in cands if c.knobs["algorithm"] == "sync")
    assert best.name in section and sync.name in section
    assert _round(best.modeled_us, 1) in section
    assert _round(sync.modeled_us, 1) in section
    excl = {c.name for c in cands.excluded}
    assert "pipe:d32:t128:f32" in excl
    assert "pipe:d32:t128:f32" in section
    # the overlap proof quotes the replay, not wishes
    pipe = aperf.decompose_stencil_stream(buffering=3)
    syncrep = aperf.decompose_stencil_stream(buffering=1)
    assert _round(aperf.stencil_overlap_fraction(pipe), 3) in section
    assert _round(pipe.makespan_s * 1e6, 0) in section
    assert _round(syncrep.makespan_s * 1e6, 0) in section
    # the seeded knobs are quoted in both documents
    k = SEEDED_STENCIL_PIPELINE_KNOBS
    tuning = _read("docs/tuning.md")
    assert "stencil_pipeline" in tuning
    row = [ln for ln in tuning.splitlines()
           if "stencil_pipeline" in ln and ln.startswith("|")]
    assert row, "tuning.md decision table lost the stencil_pipeline row"
    assert (f"{k['algorithm']} / {k['depth']} / {k['stripe']} / "
            f"{k['compute_dtype']} / {k['buffering']}") in row[0]
    assert best.knobs == k  # the doc'd winner IS the seeded plan


def test_stencil_analytic_expectations_are_committed():
    """The r18 stencil entries in ANALYTIC_EXPECTED_US price through
    the ONE cost model (symmetric keysets with analytic_predictions)
    and agree with the candidate table's endpoints."""
    from smi_tpu.analysis import perf as aperf
    from smi_tpu.tuning import cost_model as cm

    pred = aperf.analytic_predictions()
    assert set(aperf.ANALYTIC_EXPECTED_US) == set(pred)
    cands = cm.stencil_pipeline_candidates()
    sync = next(c for c in cands if c.knobs["algorithm"] == "sync")
    assert aperf.ANALYTIC_EXPECTED_US[
        "stencil_pipeline_8192_sweep_us"
    ] == pytest.approx(cands[0].modeled_us, rel=0.02)
    assert aperf.ANALYTIC_EXPECTED_US[
        "stencil_sync_8192_sweep_us"
    ] == pytest.approx(sync.modeled_us, rel=0.02)


def test_bench_stencil_roofline_baseline_pins_the_committed_fraction():
    """The scoreboard's roofline baseline is a PINNED constant equal
    to the r05 headline's achieved VPU fraction (same reason as the
    flash pin: a self-comparison could never regress), and a roofline
    regression is not a printable verdict — render_line refuses it."""
    import bench
    from smi_tpu.benchmarks.surface import stencil_roofline

    recomputed = stencil_roofline(
        bench.BENCH_R05_STENCIL_CELLS, 16
    )["vs_vpu_roofline"]
    assert bench.SCOREBOARD_STENCIL_VPU_ROOFLINE_BASELINE == float(
        _round(recomputed, 4)
    )
    board = bench.scoreboard_fields()
    row = board["stencil_gcells_per_chip"]
    assert row["roofline"]["verdict"] == "pass"
    assert row["roofline"]["baseline"] == (
        bench.SCOREBOARD_STENCIL_VPU_ROOFLINE_BASELINE
    )
    payload = {"metric": "m", "value": 1, "unit": "u",
               "vs_baseline": 1, "scoreboard": board}
    assert bench.render_line(payload)
    # a regressed roofline fails the render loudly, not quietly
    worse = bench.scoreboard_fields(
        bench.BENCH_R05_STENCIL_CELLS * (1 - 2 * bench.SCOREBOARD_TOLERANCE)
    )
    payload["scoreboard"] = worse
    with pytest.raises(ValueError, match="roofline regression"):
        bench.render_line(payload)
    # a stencil row with no roofline object at all is refused too
    naked = {k2: dict(v) for k2, v in board.items()}
    del naked["stencil_gcells_per_chip"]["roofline"]
    payload["scoreboard"] = naked
    with pytest.raises(ValueError, match="roofline"):
        bench.render_line(payload)


def test_pipeline_vmem_mirrors_pin_the_kernel_constants():
    """cost_model's stencil pipeline arithmetic IS the kernel's."""
    from smi_tpu.kernels import stencil_pipeline as kpipe
    from smi_tpu.tuning import cost_model as cm

    assert cm.STENCIL_PIPELINE_SLOTS == kpipe.PIPELINE_SLOTS
    assert cm.VMEM_LIMIT_BYTES == kpipe.PIPELINE_VMEM_BYTES
    assert cm.STENCIL_LANE_PAD == kpipe.LANE_PAD
    for stripe, depth in ((128, 8), (64, 32), (256, 8)):
        assert cm.stencil_pipeline_vmem_bytes(
            stripe, 8192, depth
        ) == kpipe.pipeline_vmem_bytes(stripe, 8192, depth)


def test_compressed_docs_quote_the_shipped_constants():
    """The r19 compressed-collectives sections (docs/tuning.md ladder,
    docs/perf_notes.md accuracy contract) must state the wire ratios,
    env knob, quantize floor, and inert model margin the code ships —
    and the constants must agree across the transport and plan tiers
    (one vocabulary, drift-guarded here)."""
    from smi_tpu.parallel import credits as C
    from smi_tpu.tuning import cost_model as cm

    # transport and plan tiers share ONE precision vocabulary
    assert cm.PRECISION_WIRE_RATIO == C.PRECISION_WIRE_RATIO
    assert cm.SPARSE_TOPK_DENSITY == C.SPARSE_TOPK_DENSITY
    assert cm.SPARSE_INDEX_OVERHEAD == C.SPARSE_INDEX_OVERHEAD

    tuning = _read("docs/tuning.md")
    notes = _read("docs/perf_notes.md")
    assert "Compressed collectives (r19)" in notes
    for text in (tuning, notes):
        assert "SMI_TPU_ALLREDUCE_PRECISION" in text
        assert f"{cm.PRECISION_MODEL_MARGIN:g}x" in text
    assert f"{cm.QUANTIZE_MIN_BYTES // 1024} KiB" in tuning
    for name in cm.ALLREDUCE_PRECISIONS:
        assert f"`{name}`" in tuning


def test_compressed_docs_quote_the_simulated_wallclock(monkeypatch):
    """The quoted r19 acceptance vectors are re-derived from the
    deterministic credits simulator at the PUBLISHED rates (a fleet
    $SMI_TPU_DCN_BETA must not leak in), so docs/perf_notes.md can
    never drift from what the quantized tier-1 assertions measure."""
    from smi_tpu.parallel import credits as C
    from smi_tpu.tuning import cost_model as cm

    monkeypatch.delenv(cm.DCN_BETA_ENV, raising=False)
    rep = C.quantized_wallclock_comparison(2, 2, 4 << 20, "int8")
    notes = _read("docs/perf_notes.md")
    for key in ("f32_s", "quantized_s", "f32_dcn_s",
                "quantized_dcn_s"):
        us = f"{round(rep[key] * 1e6, 1):g}"
        assert us in notes, (
            f"docs/perf_notes.md does not quote the simulated "
            f"{key} wall-clock {us} us — regenerate the r19 numbers"
        )
    # the committed pins match the recomputed vectors exactly
    from smi_tpu.analysis.perf import ANALYTIC_EXPECTED_US as E

    assert E["quantized_pod_allreduce_int8_2x2_4mib_us"] == round(
        rep["quantized_s"] * 1e6, 1)
    assert E["quantized_pod_dcn_phase_f32_2x2_4mib_us"] == round(
        rep["f32_dcn_s"] * 1e6, 1)
    assert E["quantized_pod_dcn_phase_int8_2x2_4mib_us"] == round(
        rep["quantized_dcn_s"] * 1e6, 1)
    # the makespan and DCN-phase ratios clear the acceptance bar, and
    # the doc quotes them at 4 decimal places
    makespan_ratio = rep["quantized_s"] / rep["f32_s"]
    dcn_ratio = rep["quantized_dcn_s"] / rep["f32_dcn_s"]
    assert makespan_ratio <= 0.55
    assert f"{makespan_ratio:.4f}" in notes
    assert f"{dcn_ratio:.4f}" in notes
