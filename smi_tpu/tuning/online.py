"""Online autotuner: live-sample shadow comparison, epoch-guarded swap.

ROADMAP item 3, closed: the plan engine resolves
env → cache → live → model → heuristic at *trace* time from an
*offline* sweep, but PR 8's serving front-end generates exactly the
per-tenant, per-payload traffic distributions an offline sweep cannot
anticipate. This module is ATLAS (PAPERS.md) moved from install-time
to run-time, specialized per tenant:

- **ingest** — :class:`OnlineTuner` is ``record()``-compatible with
  :class:`smi_tpu.obs.metrics.SampleSink`, so
  ``tracing.timed(sink=tuner, op=..., payload_bytes=..., tenant=...)``
  streams live wall-clocks straight into it with zero call-site
  changes, and :meth:`OnlineTuner.ingest` replays a recorded
  ``SampleSink`` snapshot offline (``smi-tpu tune --online``).
- **shadow-compare** — per (op, power-of-two payload bucket, tenant)
  cell, the ACTIVE plan's measured mean cost is compared against the
  best rival candidate from :mod:`smi_tpu.tuning.cost_model`'s
  :class:`~smi_tpu.tuning.cost_model.CandidateSet`. A proposal fires
  only past BOTH thresholds — at least :data:`DEFAULT_RETUNE_MIN_SAMPLES`
  samples in the cell AND a measured-over-modeled win of at least
  :data:`DEFAULT_RETUNE_MARGIN` — so noise can never flip a plan.
- **hot-swap** — the winning rival goes through the explicit
  :class:`~smi_tpu.tuning.swap.PlanSwap` machine (propose → quiesce →
  swap → commit/rollback): the plan-cache entry is replaced mid-job
  under a bumped plan epoch + entry ``revision``, stale-plan traffic
  is rejected loudly, and an aborted swap rolls back with zero
  lost-accepted. The machine itself is exhaustively model-checked
  (``smi-tpu lint --model``, the ``retune=1`` scope).

The tuner only RETUNES plans — a cell with no active cache entry has
nothing to hot-swap and is left to the sweep/heuristic layers (first
plans are the offline sweep's job; replacing a *measured* entry that
live traffic proves wrong is this module's).

Everything is observable through the PR-13 schema: ``tune.sample`` /
``tune.propose`` / ``tune.swap`` / ``tune.rollback`` events plus the
``tune_*_total`` counters, incremented at the tuner's own accounting
sites so a metrics snapshot can never disagree with the bookkeeping.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Callable, Dict, List, Optional, Tuple

from smi_tpu.tuning import cost_model as cm
from smi_tpu.tuning.cache import CacheEntry, PlanCache
from smi_tpu.tuning.engine import _collective_topology
from smi_tpu.tuning.plan import PlanKey, payload_bucket
from smi_tpu.tuning.swap import PlanSwap

#: Minimum samples a shadow cell must hold before it may propose a
#: swap — one slow outlier can never flip a plan. Overridable by
#: ``$SMI_TPU_RETUNE_MIN_SAMPLES`` (and per-tuner). docs/tuning.md
#: quotes this (drift-guarded).
DEFAULT_RETUNE_MIN_SAMPLES = 16

#: Minimum measured-over-modeled win factor the rival must show
#: (``measured_mean >= margin * rival_modeled``) before a proposal
#: fires — the hysteresis band that keeps a near-tie from flapping.
#: Overridable by ``$SMI_TPU_RETUNE_MARGIN``.
DEFAULT_RETUNE_MARGIN = 1.5

#: Ticks a quiesce may wait for its drain set before the swap rolls
#: back (reason ``quiesce-timeout``) — a wedged stream must cost the
#: retune, never wedge the tuner.
QUIESCE_TIMEOUT_TICKS = 64

#: Master switch for trace-path integrations (off by default — the
#: tuner only runs where a caller asked for it). Boolean vocabulary
#: below; anything else is a LOUD ValueError naming knob and value
#: (the ``default_deadline`` discipline: a typo must never silently
#: pick a different behaviour).
ONLINE_RETUNE_ENV = "SMI_TPU_ONLINE_RETUNE"
MIN_SAMPLES_ENV = "SMI_TPU_RETUNE_MIN_SAMPLES"
MARGIN_ENV = "SMI_TPU_RETUNE_MARGIN"

_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("", "0", "false", "no", "off")

#: Ops the tuner can arbitrate: the ones whose rival candidates the
#: cost model prices (:func:`op_candidates`). Samples for any other op
#: aggregate in their cells but never propose.
TUNABLE_OPS = ("all_reduce", "all_to_all", "stencil_pipeline")


def online_retune_enabled() -> bool:
    """``$SMI_TPU_ONLINE_RETUNE``: unset/empty/0/false/no/off = OFF;
    1/true/yes/on = ON; anything else is a loud ValueError."""
    raw = os.environ.get(ONLINE_RETUNE_ENV, "").strip().lower()
    if raw in _FALSY:
        return False
    if raw in _TRUTHY:
        return True
    raise ValueError(
        f"${ONLINE_RETUNE_ENV} must be one of "
        f"{_TRUTHY + tuple(v for v in _FALSY if v)} (or unset), got "
        f"{os.environ.get(ONLINE_RETUNE_ENV)!r}"
    )


def retune_min_samples() -> int:
    """``$SMI_TPU_RETUNE_MIN_SAMPLES`` (a positive integer — it
    outranks the built-in :data:`DEFAULT_RETUNE_MIN_SAMPLES`), loud on
    malformed or non-positive values."""
    raw = os.environ.get(MIN_SAMPLES_ENV, "").strip()
    if not raw:
        return DEFAULT_RETUNE_MIN_SAMPLES
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"${MIN_SAMPLES_ENV} must be a positive integer sample "
            f"count, got {raw!r}"
        ) from None
    if value < 1:
        raise ValueError(
            f"${MIN_SAMPLES_ENV} must be >= 1 (a zero-sample "
            f"threshold would let a single outlier flip a plan), "
            f"got {raw!r}"
        )
    return value


def retune_margin() -> float:
    """``$SMI_TPU_RETUNE_MARGIN`` (a finite factor > 1.0 — it outranks
    the built-in :data:`DEFAULT_RETUNE_MARGIN`), loud on malformed
    values: a margin at or below 1.0 removes the hysteresis band and
    noise could flip plans."""
    raw = os.environ.get(MARGIN_ENV, "").strip()
    if not raw:
        return DEFAULT_RETUNE_MARGIN
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"${MARGIN_ENV} must be a win-margin factor, got {raw!r}"
        ) from None
    if not value > 1.0 or math.isinf(value) or math.isnan(value):
        raise ValueError(
            f"${MARGIN_ENV} must be a finite factor > 1.0 (the "
            f"hysteresis band that keeps noise from flipping plans), "
            f"got {raw!r}"
        )
    return value


def op_candidates(op: str, payload_bytes: float, topo: cm.TopologySpec,
                  link: Optional[cm.LinkModel] = None,
                  dtype: str = "float32"):
    """The rival candidate table for one tunable op — the SAME pricing
    ``tune --explain`` prints and the analytic-regression lint rule
    recomputes (one pricing, every consumer). For ``all_reduce`` the
    table is algorithms FIRST (so :func:`priced_sample_us`'s
    first-algorithm-match scan is unchanged), then the lossy wire
    precisions from :func:`cm.allreduce_precision_candidates` — the
    r19 vocabulary growth that lets live traffic retune a dense plan
    into an int8 one through the same swap machine."""
    link = link or cm.LinkModel()
    if op == "all_reduce":
        algos = cm.allreduce_candidates(int(payload_bytes), topo,
                                        link=link)
        pcands = cm.allreduce_precision_candidates(
            int(payload_bytes), topo, dtype=dtype, link=link
        )
        # drop the dense f32 row: it IS the best algorithm candidate,
        # and a duplicate identity would let the tuner propose a
        # no-op swap
        lossy = [c for c in pcands if c.name != "f32"]
        return cm.CandidateSet(list(algos) + lossy, pcands.excluded)
    if op == "all_to_all":
        return cm.alltoall_candidates(int(payload_bytes), topo,
                                      link=link)
    if op == "stencil_pipeline":
        # the payload is the f32 block (extent^2 x 4 B); candidate
        # NAMES are the tuner's algorithm vocabulary (each depth x
        # stripe x dtype point is its own rival), while the remaining
        # knobs stay kernel-shaped so an installed entry is complete
        extent = max(1, int(math.isqrt(max(0, int(payload_bytes)) // 4)))
        cands = cm.stencil_pipeline_candidates(h=extent, w=extent)
        renamed = [
            dataclasses.replace(
                c, knobs={**c.knobs, "algorithm": c.name}
            )
            for c in cands
        ]
        return type(cands)(renamed, cands.excluded)
    return None


def priced_sample_us(op: str, algorithm: str, payload_bytes: float,
                     topo: cm.TopologySpec,
                     link: Optional[cm.LinkModel] = None) -> float:
    """The modeled cost of running ``algorithm`` for ``op`` at this
    payload — the pricing the seeded campaign cells use to synthesize
    deterministic "live" timings (the credits simulator's Hockney
    tiers). Loud on an op/algorithm pair the model cannot price."""
    cands = op_candidates(op, payload_bytes, topo, link)
    if cands is not None:
        for c in cands:
            if (c.knobs.get("algorithm") == algorithm
                    and c.modeled_us is not None):
                return c.modeled_us
    raise ValueError(
        f"no pricing for op {op!r} algorithm {algorithm!r} "
        f"(tunable ops: {TUNABLE_OPS})"
    )


def sample_bucket_bytes(payload_bytes: Optional[float]) -> Optional[int]:
    """The PLAN engine's power-of-two bucket (lower bound, bytes) —
    deliberately the :func:`smi_tpu.tuning.plan.payload_bucket`
    vocabulary, not the metrics histogram's upper-bound grid, so a
    cell maps onto exactly the plan-cache key the engine consults for
    every payload in the bucket (edge payloads included)."""
    if payload_bytes is None:
        return None
    b = max(1, int(payload_bytes))
    return 1 << (b.bit_length() - 1)


@dataclasses.dataclass
class _ShadowCell:
    """Bounded aggregate of one (op, bucket, tenant)'s live timings of
    the ACTIVE plan."""

    count: int = 0
    total_us: float = 0.0
    min_us: Optional[float] = None
    max_us: Optional[float] = None

    def add(self, us: float, n: int = 1) -> None:
        self.count += n
        self.total_us += us * n
        if self.min_us is None or us < self.min_us:
            self.min_us = us
        if self.max_us is None or us > self.max_us:
            self.max_us = us

    @property
    def mean_us(self) -> float:
        return self.total_us / self.count if self.count else 0.0


class OnlineTuner:
    """Live-sample plan retuning over one plan cache.

    ``record()`` is :class:`~smi_tpu.obs.metrics.SampleSink`-shaped
    (the ``tracing.timed(sink=)`` target); :meth:`maybe_propose` turns
    qualified cells into :class:`~smi_tpu.tuning.swap.PlanSwap`
    proposals; the swap transitions (:meth:`start_quiesce`,
    :meth:`execute_swap`, :meth:`commit`, :meth:`rollback`) are driven
    by the host — the serving front-end one transition per tick, the
    model checker one per BFS action, :meth:`run_offline` to
    completion.
    """

    def __init__(
        self,
        cache: Optional[PlanCache] = None,
        topo: Optional[cm.TopologySpec] = None,
        dtype: str = "float32",
        device_kind: str = "unknown",
        min_samples: Optional[int] = None,
        margin: Optional[float] = None,
        link: Optional[cm.LinkModel] = None,
        recorder=None,
        metrics=None,
        quiesce_timeout: int = QUIESCE_TIMEOUT_TICKS,
    ):
        self.cache = cache if cache is not None else PlanCache()
        self.topo = topo or cm.TopologySpec(n=8)
        self.dtype = dtype
        self.device_kind = device_kind
        # env overrides outrank the built-ins; an explicit argument
        # outranks both (the operator wiring the tuner by hand)
        self.min_samples = (retune_min_samples() if min_samples is None
                            else int(min_samples))
        if self.min_samples < 1:
            raise ValueError(
                f"min_samples must be >= 1, got {self.min_samples}"
            )
        self.margin = retune_margin() if margin is None else float(margin)
        if not self.margin > 1.0:
            raise ValueError(
                f"margin must be > 1.0 (the noise-hysteresis band), "
                f"got {self.margin}"
            )
        self.link = link or cm.LinkModel()
        self.recorder = recorder
        self.metrics = metrics
        self.quiesce_timeout = int(quiesce_timeout)
        #: host-attached logical clock for event stamps (the serving
        #: front-end wires its StepClock); default = tick 0
        self.clock: Optional[Callable[[], int]] = None
        self.cells: Dict[Tuple[str, Optional[int], Optional[str]],
                         _ShadowCell] = {}
        self._swaps: Dict[str, PlanSwap] = {}
        # bookkeeping — the tune_* metrics counters are incremented at
        # the same sites, so snapshot == bookkeeping (tested)
        self.samples_ingested = 0
        self.proposals = 0
        self.swaps = 0
        self.rollbacks = 0

    # -- observability plumbing ----------------------------------------

    def _now(self) -> int:
        return int(self.clock()) if self.clock is not None else 0

    def _emit(self, kind: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.emit(kind, self._now(), **fields)

    def _count(self, name: str, by: int = 1, **labels) -> None:
        if self.metrics is not None:
            self.metrics.counter(name, **labels).inc(by)

    # -- ingestion ------------------------------------------------------

    def record(self, op: str, seconds: float,
               payload_bytes: Optional[float] = None,
               tenant: Optional[str] = None) -> None:
        """One live timing of the ACTIVE plan (the
        :class:`~smi_tpu.obs.metrics.SampleSink` signature, so
        ``timed(sink=tuner)`` needs no adapter)."""
        if seconds < 0:
            raise ValueError(f"negative sample {seconds} for {op!r}")
        bucket = sample_bucket_bytes(payload_bytes)
        key = (str(op), bucket, tenant)
        cell = self.cells.get(key)
        if cell is None:
            cell = self.cells[key] = _ShadowCell()
        cell.add(float(seconds) * 1e6)
        self.samples_ingested += 1
        self._emit("tune.sample", op=str(op), bucket=bucket,
                   tenant=tenant)
        self._count("tune_samples_total", op=str(op))

    def ingest(self, sink) -> int:
        """Bulk-ingest a recorded :class:`SampleSink` (the object, its
        ``snapshot()`` dict, or a bare ``entries()`` list) — the
        ``smi-tpu tune --online`` offline-replay path. Returns the
        number of samples folded in; malformed entries are a loud
        ValueError naming the entry.

        Bucket vocabulary caveat: a SampleSink bucket is an
        UPPER-bound power of two covering payloads in ``(B/2, B]``,
        which straddles two plan buckets (``[B/2, B)`` for interior
        payloads, ``[B, 2B)`` for exactly ``B``). The exact payloads
        are gone by the time the sink aggregated, so this mapping
        takes the bound itself as the representative — exact for the
        pow2-aligned payloads this framework's sweeps and collective
        buffers actually use (64 KiB/1 MiB/4 MiB grids), one bucket
        high for interior-heavy traffic. Workloads with interior
        payloads should feed the tuner LIVE via :meth:`record`,
        which buckets the exact payload in the plan vocabulary
        (pinned by tests/test_retune.py)."""
        if hasattr(sink, "entries"):
            entries = sink.entries()
        elif isinstance(sink, dict):
            entries = sink.get("entries")
        else:
            entries = sink
        if not isinstance(entries, (list, tuple)):
            raise ValueError(
                f"a sample sink is a SampleSink, its snapshot dict, "
                f"or an entries list; got {type(sink).__name__}"
            )
        total = 0
        for i, entry in enumerate(entries):
            if (not isinstance(entry, dict)
                    or not isinstance(entry.get("knobs"), dict)
                    or not isinstance(entry.get("cost_us"), (int, float))):
                raise ValueError(
                    f"sample-sink entry {i} is not the SampleSink "
                    f"vocabulary {{'knobs': {{'op': ..., "
                    f"'samples': ...}}, 'cost_us': ...}}: {entry!r}"
                )
            knobs = entry["knobs"]
            op = knobs.get("op")
            samples = knobs.get("samples")
            if not isinstance(op, str) or not isinstance(samples, int) \
                    or samples < 1:
                raise ValueError(
                    f"sample-sink entry {i} needs a string 'op' and a "
                    f"positive integer 'samples' in its knobs, got "
                    f"op={op!r} samples={samples!r}"
                )
            bucket = knobs.get("payload_bucket_bytes")
            tenant = knobs.get("tenant")
            # representative payload = the sink bucket's bound itself
            # (see the docstring's vocabulary caveat)
            key = (op, sample_bucket_bytes(bucket), tenant)
            cell = self.cells.get(key)
            if cell is None:
                cell = self.cells[key] = _ShadowCell()
            cell.add(float(entry["cost_us"]), n=samples)
            if knobs.get("min_us") is not None:
                cell.min_us = min(cell.min_us, float(knobs["min_us"]))
            if knobs.get("max_us") is not None:
                cell.max_us = max(cell.max_us, float(knobs["max_us"]))
            total += samples
            self._emit("tune.sample", op=op,
                       bucket=sample_bucket_bytes(bucket),
                       tenant=tenant, samples=samples)
            self._count("tune_samples_total", by=samples, op=op)
        self.samples_ingested += total
        return total

    # -- the shadow comparison -----------------------------------------

    def plan_key(self, op: str,
                 bucket_bytes: Optional[int]) -> Optional[PlanKey]:
        """The plan-cache key a cell's samples speak about, or ``None``
        for unbucketed (hence untunable) cells."""
        if bucket_bytes is None:
            return None
        return PlanKey(op, payload_bucket(bucket_bytes), self.dtype,
                       self.device_kind,
                       _collective_topology(self.topo))

    def swap_for(self, key: PlanKey) -> PlanSwap:
        sig = key.signature()
        swap = self._swaps.get(sig)
        if swap is None:
            swap = self._swaps[sig] = PlanSwap(self.cache, key)
        return swap

    def _lossy_rivals_armed(self) -> bool:
        """Is there MEASURED evidence that a lossy wire width works on
        this device kind — the quantized sweep's distilled
        ``precision_threshold`` crossover? Mirrors the plan engine's
        ladder: without it the live tier, like the model rung, only
        reroutes (algorithm swaps) and never flips numerics."""
        outer = ((self.topo.outer or 0)
                 if self.topo.hierarchical_eligible else 0)
        for kind in (self.device_kind, "unknown"):
            hit = self.cache.lookup(
                PlanKey("all_reduce", "precision_threshold", "", kind,
                        f"dcn{outer}" if outer else "flat")
            )
            if (hit is not None
                    and "precision_min_bytes" in hit.knobs
                    and "precision" in hit.knobs):
                return True
        return False

    def active_entry(self, key: Optional[PlanKey]) -> Optional[CacheEntry]:
        return None if key is None else self.cache.lookup(key)

    def plan_epoch(self, key: PlanKey) -> int:
        return self.swap_for(key).plan_epoch

    def total_plan_epoch(self) -> int:
        """Monotone sum of every key's plan epoch — the one scalar a
        host stamps onto in-flight work to know whether ANY plan
        changed since it was admitted (the serving front-end's
        re-plan bookkeeping)."""
        return sum(s.plan_epoch for s in self._swaps.values())

    def maybe_propose(self, now: int = 0,
                      drain_census: Optional[Callable] = None
                      ) -> List[PlanSwap]:
        """Scan the cells; stage a :class:`PlanSwap` proposal for every
        one past BOTH thresholds whose best rival candidate beats the
        active plan's measured mean by the margin. ``drain_census``
        maps a proposal-evidence dict to the frozenset of in-flight
        stream ids keyed to the old plan (the host's knowledge);
        ``None`` = nothing to drain. Deterministic scan order."""
        proposed: List[PlanSwap] = []
        for (op, bucket, tenant) in sorted(
            self.cells,
            key=lambda k: (k[0], -1 if k[1] is None else k[1],
                           k[2] or ""),
        ):
            cell = self.cells[(op, bucket, tenant)]
            if op not in TUNABLE_OPS or bucket is None:
                continue
            if cell.count < self.min_samples:
                continue
            key = self.plan_key(op, bucket)
            swap = self.swap_for(key)
            if swap.in_flight():
                continue
            entry = self.active_entry(key)
            if entry is None or "algorithm" not in entry.knobs:
                # nothing to retune: first plans are the sweep's job
                continue
            # the plan's identity is (algorithm, wire precision): an
            # int8 row with the active algorithm is a genuine rival
            # of the dense plan, and vice versa
            active = str(entry.knobs["algorithm"])
            active_id = (active,
                         str(entry.knobs.get("precision", "f32")))
            cands = op_candidates(op, bucket, self.topo, self.link,
                                  dtype=self.dtype)
            # the r19 asymmetry holds on the live tier too: a lossy
            # width is model-priced here, and the model alone must
            # never flip numerics — lossy rows join the rival pool
            # only once a measured precision artifact exists (the
            # quantized sweep's crossover, or the active plan already
            # runs a lossy width and we're retuning between widths)
            lossy_armed = (active_id[1] != "f32"
                           or self._lossy_rivals_armed())
            rivals = [
                c for c in cands
                if (str(c.knobs.get("algorithm")),
                    str(c.knobs.get("precision", "f32"))) != active_id
                and c.modeled_us is not None
                and (lossy_armed
                     or str(c.knobs.get("precision", "f32")) == "f32")
            ]
            if not rivals:
                continue
            best = min(rivals, key=lambda c: c.modeled_us)
            measured = cell.mean_us
            if measured < best.modeled_us * self.margin:
                continue   # inside the hysteresis band: hold the plan
            advantage = measured / best.modeled_us
            rival_algo = str(best.knobs["algorithm"])
            evidence = {
                "op": op, "bucket": bucket, "tenant": tenant,
                "from": active, "to": rival_algo,
                "samples": cell.count,
                "measured_us": round(measured, 3),
                "rival_modeled_us": round(best.modeled_us, 3),
                "advantage": round(advantage, 2),
            }
            rival_precision = str(best.knobs.get("precision", "f32"))
            if rival_precision != "f32" or active_id[1] != "f32":
                # a precision change is named in the evidence — a
                # numerics-affecting swap must never look like a pure
                # routing change in the audit log
                evidence["from_precision"] = active_id[1]
                evidence["to_precision"] = rival_precision
            new_entry = CacheEntry(
                knobs=dict(best.knobs),
                cost_us=None,
                provenance=(
                    f"live:retune:samples={cell.count}:"
                    f"margin={advantage:.2f}x"
                    + (f":tenant={tenant}" if tenant else "")
                ),
            )
            drain = (drain_census(evidence) if drain_census is not None
                     else frozenset())
            swap.propose(new_entry, evidence=evidence, drain=drain)
            self.proposals += 1
            self._emit("tune.propose", op=op, bucket=bucket,
                       from_algo=active, to_algo=rival_algo,
                       samples=cell.count,
                       margin=round(advantage, 2), tenant=tenant)
            self._count("tune_proposals_total", op=op)
            proposed.append(swap)
        return proposed

    # -- driving the swap machine --------------------------------------

    def pending_swaps(self) -> List[PlanSwap]:
        return [s for s in self._swaps.values() if s.in_flight()]

    def start_quiesce(self, swap: PlanSwap,
                      now: Optional[int] = None) -> None:
        swap.quiesce(now if now is not None else self._now())

    def execute_swap(self, swap: PlanSwap) -> CacheEntry:
        """Install the rival entry (revision-bumped, plan epoch
        bumped) and reset every cell speaking about this key — the
        fresh window measures the NEW plan, so a just-committed swap
        can never immediately re-propose itself away."""
        installed = swap.swap()
        self.swaps += 1
        ev = swap.proposal.evidence
        self._emit("tune.swap", op=str(ev.get("op")),
                   bucket=ev.get("bucket"),
                   to_algo=str(ev.get("to")),
                   plan_epoch=swap.plan_epoch,
                   revision=installed.revision)
        self._count("tune_swaps_total", op=str(ev.get("op")))
        sig = swap.key.signature()
        for cell_key in list(self.cells):
            k = self.plan_key(cell_key[0], cell_key[1])
            if k is not None and k.signature() == sig:
                self.cells[cell_key] = _ShadowCell()
        return installed

    def commit(self, swap: PlanSwap) -> None:
        swap.commit()

    def rollback(self, swap: PlanSwap, reason: str = "",
                 now: Optional[int] = None) -> None:
        ev = swap.proposal.evidence if swap.proposal else {}
        swap.rollback(reason)
        self.rollbacks += 1
        self._emit("tune.rollback", op=str(ev.get("op")),
                   bucket=ev.get("bucket"), reason=reason)
        self._count("tune_rollbacks_total",
                    reason=reason or "explicit")

    def run_offline(self) -> List[Tuple[str, Dict[str, object]]]:
        """Drive every qualified proposal straight through the full
        arc (nothing is in flight offline, so quiesce is immediate) —
        the ``smi-tpu tune --online`` engine. Returns the decision
        log: ``("propose", evidence)`` and ``("swap", outcome)``
        records in order."""
        decisions: List[Tuple[str, Dict[str, object]]] = []
        for swap in self.maybe_propose():
            decisions.append(("propose", dict(swap.proposal.evidence)))
        for swap in list(self.pending_swaps()):
            self.start_quiesce(swap, 0)
            installed = self.execute_swap(swap)
            self.commit(swap)
            decisions.append(("swap", {
                "key": swap.key.signature(),
                "algorithm": installed.knobs.get("algorithm"),
                "revision": installed.revision,
                "plan_epoch": swap.plan_epoch,
                "provenance": installed.provenance,
            }))
        return decisions

    # -- reporting ------------------------------------------------------

    def summary(self) -> Dict[str, object]:
        """The campaign-report / ``serve --selftest --retune`` block:
        the bookkeeping the tune_* counters mirror, plus every live
        entry currently installed."""
        live_entries = {
            sig: e.to_json()
            for sig, e in sorted(self.cache.entries.items())
            if e.provenance.startswith("live:")
        }
        return {
            "samples_ingested": self.samples_ingested,
            "cells": len(self.cells),
            "proposals": self.proposals,
            "swaps": self.swaps,
            "rollbacks": self.rollbacks,
            "min_samples": self.min_samples,
            "margin": self.margin,
            "plan_epochs": {
                sig: s.plan_epoch
                for sig, s in sorted(self._swaps.items())
                if s.plan_epoch
            },
            "live_entries": live_entries,
        }
