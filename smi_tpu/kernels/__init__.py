"""Pallas TPU kernels: the framework's hand-written hot paths.

The reference's performance comes from always-running HLS kernels that
stream packets concurrently with compute (``codegen/templates/*.cl``); the
TPU analog is Pallas kernels that fuse multi-pass jnp pipelines into
single VMEM-resident passes and overlap DMA/ICI traffic with compute:

- :mod:`smi_tpu.kernels.stencil` — fused Jacobi sweep (halo patch +
  4-point average + Dirichlet mask in one pass over the block),
- :mod:`smi_tpu.kernels.stencil_temporal` — temporally-blocked Jacobi
  (k sweeps per HBM pass),
- :mod:`smi_tpu.kernels.flash` — flash-attention block fold for the
  ring-attention schedule (VMEM-resident online softmax, f32/bf16),
- :mod:`smi_tpu.kernels.ring` — ring collectives via
  ``make_async_remote_copy`` (explicit ICI RDMA, double-buffered, with
  neighbour-barrier + slot-credit flow control).

Every kernel has a jnp fallback for unaligned shapes/odd dtypes, and is
tested in interpreter mode on the CPU fake mesh.
"""
