"""Hybrid (slice × in-slice) communicator and hierarchical allreduce.

Reference: SMI's network is two-tier — FPGAs grouped per node
(``SMI_DEVICES_PER_NODE``, ``CMakeLists.txt:10``) with intra-node links
costed 1 and inter-node QSFP routes costed 100
(``codegen/program.py:7-8``) — and its router keeps reductions inside
the cheap tier as long as possible. These tests pin the TPU rendition:
an (outer=DCN, inner=ICI) mesh and the reduce-scatter /
cross-slice-reduce / all-gather composition, on the CPU fake mesh
split into virtual slices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import smi_tpu as smi
from smi_tpu.parallel import collectives


def _hybrid(eight_devices, n_slices=2):
    return smi.make_hybrid_communicator(
        n_slices=n_slices, devices=eight_devices
    )


def test_hybrid_mesh_shape(eight_devices):
    comm = _hybrid(eight_devices)
    assert comm.mesh.devices.shape == (2, 4)
    assert comm.axis_names == ("dcn", "ici")
    assert comm.size == 8
    # row-major rank order == the flat device order (slices are
    # contiguous groups, like nodes in the reference's rank sort)
    assert list(comm.mesh.devices.flat) == list(eight_devices)


def test_hybrid_subcomm_sizes(eight_devices):
    comm = _hybrid(eight_devices)
    assert comm.subcomm("ici").size == 4
    assert comm.subcomm("dcn").size == 2


class _StubDevice:
    """Minimal stand-in for a multi-slice platform device."""

    def __init__(self, i, slice_index):
        self.id = i
        self.slice_index = slice_index

    def __repr__(self):
        return f"d{self.id}@s{self.slice_index}"


def test_slice_groups_platform_reported():
    """On a real multi-slice platform the grouping follows each
    device's slice_index, whatever the list order."""
    from smi_tpu.parallel.mesh import _slice_groups

    devs = [_StubDevice(i, slice_index=i % 2) for i in range(6)]
    groups = _slice_groups(devs, None, None)
    assert [len(g) for g in groups] == [3, 3]
    assert all(d.slice_index == 0 for d in groups[0])
    assert all(d.slice_index == 1 for d in groups[1])
    # explicit counts must agree with the platform
    assert _slice_groups(devs, 2, 3) == groups
    with pytest.raises(ValueError, match="platform reports"):
        _slice_groups(devs, 3, None)
    with pytest.raises(ValueError, match="per_slice"):
        _slice_groups(devs, None, 2)


def test_slice_groups_uneven_platform_rejected():
    from smi_tpu.parallel.mesh import _slice_groups

    devs = [_StubDevice(i, slice_index=0 if i < 4 else 1)
            for i in range(6)]
    with pytest.raises(ValueError, match="uneven"):
        _slice_groups(devs, None, None)


def test_hybrid_requires_slice_count(eight_devices):
    with pytest.raises(ValueError, match="n_slices"):
        smi.make_hybrid_communicator(devices=eight_devices)


def test_hybrid_uneven_split_rejected(eight_devices):
    with pytest.raises(ValueError, match="split"):
        smi.make_hybrid_communicator(n_slices=3, devices=eight_devices)


def test_hybrid_explicit_per_slice(eight_devices):
    comm = smi.make_hybrid_communicator(
        n_slices=4, per_slice=2, devices=eight_devices
    )
    assert comm.mesh.devices.shape == (4, 2)


@pytest.mark.parametrize("op,combine", [
    ("add", lambda v: v.sum(0)),
    ("max", lambda v: v.max(0)),
    ("min", lambda v: v.min(0)),
])
def test_hierarchical_allreduce(eight_devices, op, combine):
    """The two-tier composition produces the flat allreduce result on
    every rank."""
    comm = _hybrid(eight_devices)
    rng = np.random.RandomState(11)
    vals = rng.randn(8, 12).astype(np.float32)

    def body(x):
        return collectives.allreduce_hierarchical(x[0], comm, op=op)[None]

    fn = jax.jit(jax.shard_map(
        body, mesh=comm.mesh,
        in_specs=P(("dcn", "ici")), out_specs=P(("dcn", "ici")),
    ))
    out = np.asarray(fn(jnp.asarray(vals)))
    expected = combine(vals)
    for r in range(8):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5, atol=1e-5)


def test_hierarchical_same_axis_rejected(eight_devices):
    comm = _hybrid(eight_devices)
    with pytest.raises(ValueError, match="distinct"):
        collectives.allreduce_hierarchical(
            jnp.zeros((8,)), comm, inner="dcn"
        )
    with pytest.raises(ValueError, match="not in mesh"):
        collectives.allreduce_hierarchical(
            jnp.zeros((8,)), comm, inner="nope", outer="dcn"
        )


def test_hierarchical_allreduce_indivisible_rejected(eight_devices):
    comm = _hybrid(eight_devices)

    def body(x):
        return collectives.allreduce_hierarchical(x[0], comm)[None]

    fn = jax.shard_map(
        body, mesh=comm.mesh,
        in_specs=P(("dcn", "ici")), out_specs=P(("dcn", "ici")),
    )
    with pytest.raises(ValueError, match="divisible"):
        fn(jnp.zeros((8, 7), jnp.float32))


def test_hierarchical_matches_flat_allreduce(eight_devices):
    """Cross-check against the 1-D communicator's allreduce on the same
    data: tiering must not change the result."""
    comm_h = _hybrid(eight_devices)
    comm_f = smi.make_communicator(8, devices=eight_devices)
    rng = np.random.RandomState(13)
    vals = rng.randn(8, 8).astype(np.float32)

    def body_h(x):
        return collectives.allreduce_hierarchical(x[0], comm_h)[None]

    out_h = np.asarray(jax.jit(jax.shard_map(
        body_h, mesh=comm_h.mesh,
        in_specs=P(("dcn", "ici")), out_specs=P(("dcn", "ici")),
    ))(jnp.asarray(vals)))

    @smi.smi_kernel(comm_f, in_specs=P("smi"), out_specs=P("smi"))
    def app(ctx, x):
        return ctx.allreduce(x[0])[None]

    out_f = np.asarray(app(jnp.asarray(vals)))
    np.testing.assert_allclose(out_h, out_f, rtol=1e-5, atol=1e-5)
