"""Multi-host control plane: process bootstrap from a hostfile.

Reference parity: MPI is the reference's control plane — process launch
via the generated hostfile (``codegen/common.py:15-19``), rank/size from
``MPI_Comm_rank/size``, host barriers and bulk staging
(``bandwidth_benchmark.cpp:24,142-154``). The data plane (the NoC) never
touches MPI. Here the split is the same: ``jax.distributed`` is the
control plane that assembles one global device pool from many hosts, and
the data plane is XLA collectives over ICI/DCN.

Typical multi-host launch (one process per host, any launcher — the
reference uses ``mpirun``, here anything that sets a process id works)::

    opts = distributed_options("smi-routes/hostfile", process_id=my_id)
    init_distributed(opts)          # jax.distributed.initialize
    comm = make_communicator()      # global mesh over all hosts' chips

The hostfile is the one ``python -m smi_tpu route`` writes: one line per
rank, host node first, ``#`` comments after.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Union

DEFAULT_COORDINATOR_PORT = 8476


def parse_hostfile(text: str) -> List[str]:
    """Hostfile lines → ordered node list (one entry per rank).

    Mirrors the writer (``smi_tpu.__main__.write_nodefile``): node name
    first, optional ``# device, rank`` comment.
    """
    nodes = []
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if line:
            nodes.append(line)
    return nodes


@dataclasses.dataclass(frozen=True)
class DistributedOptions:
    """Arguments for ``jax.distributed.initialize``, derived from the
    hostfile: one JAX process per distinct node, coordinator on the
    first node."""

    coordinator_address: str
    num_processes: int
    process_id: int

    def __post_init__(self):
        if not (0 <= self.process_id < self.num_processes):
            raise ValueError(
                f"process_id {self.process_id} out of range for "
                f"{self.num_processes} processes"
            )


def distributed_options(
    hostfile: Union[str, os.PathLike],
    process_id: Optional[int] = None,
    coordinator_port: int = DEFAULT_COORDINATOR_PORT,
) -> DistributedOptions:
    """Derive the multi-host bootstrap arguments from a hostfile.

    ``hostfile`` is a path or the raw text. Distinct nodes become JAX
    processes in first-appearance order (several ranks/chips on one node
    stay one process, as the reference packs ``SMI_DEVICES_PER_NODE``
    FPGAs per host). ``process_id`` defaults to, in order:
    ``$SMI_PROCESS_ID``, then 0.
    """
    text = hostfile
    if os.path.exists(str(hostfile)):
        with open(hostfile) as f:
            text = f.read()
    nodes = parse_hostfile(str(text))
    if not nodes:
        raise ValueError("hostfile lists no nodes")
    distinct = list(dict.fromkeys(nodes))
    if process_id is None:
        process_id = int(os.environ.get("SMI_PROCESS_ID", "0"))
    return DistributedOptions(
        coordinator_address=f"{distinct[0]}:{coordinator_port}",
        num_processes=len(distinct),
        process_id=process_id,
    )


def init_distributed(opts: DistributedOptions) -> None:
    """``jax.distributed.initialize`` with the derived options.

    Single-process pools (one node) skip initialization entirely — the
    local runtime already owns every chip, and initialize() would block
    waiting for peers.
    """
    if opts.num_processes <= 1:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=opts.coordinator_address,
        num_processes=opts.num_processes,
        process_id=opts.process_id,
    )
