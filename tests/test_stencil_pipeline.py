"""r18 roofline closure: the explicit-DMA stencil pipeline.

Four claims, each tested where it is cheapest to falsify:

- **Numerics**: the double-buffered pipeline is BIT-identical to the
  jnp reference for f32 across odd shapes x depths x stripes x
  buffering (interpret mode — the same code path bench.py compiles for
  TPU), and the bf16-compute variant stays inside its pinned error
  bound while accumulating in f32.
- **Feasibility**: the kernel's VMEM arithmetic and the cost model's
  are the same function (drift-guarded mirrors), and every candidate
  the model refuses is *named* — VMEM over the frame, stripe shorter
  than the trapezoid cone, non-dividing stripe — never silently
  dropped (the no-silent-caps discipline, extended by the r18 small
  fix to the legacy ``_pick_*`` pickers).
- **Overlap**: the stripe-stream replay through the timestamped
  simulator *proves* the pipeline claim — the synchronous stream is
  DMA-wait bound (idle fraction over threshold, wire depth 1, two
  idle-fraction findings) while the 3-slot rotation hides the stream
  (idle under threshold, depth 3, no findings, >0.9 overlap).
- **Plumbing**: candidates flow end-to-end — cost model -> sweep ->
  plan cache -> engine/explain -> online-tuner vocabulary -> bench
  ``pipeline`` field — and the seeded entry is reachable.

Deterministic CPU cells run in tier-1; the full-grid sweep is
additionally marked slow.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

import smi_tpu as smi
from smi_tpu.analysis import perf as aperf
from smi_tpu.kernels import stencil as kstencil
from smi_tpu.kernels import stencil_pipeline as kpipe
from smi_tpu.kernels import stencil_temporal as ktemporal
from smi_tpu.models import stencil as mstencil
from smi_tpu.tuning import cost_model as cm

pytestmark = pytest.mark.stencil


def _comm(eight_devices, shape=(1, 1)):
    return smi.make_communicator(
        shape=shape, axis_names=("sx", "sy"), devices=eight_devices
    )


def _grid(h, w):
    g = mstencil.initial_grid(h, w)
    g[:, -1] = 2.0
    g[h // 2, :] = 0.5
    return g


# ---------------------------------------------------------------------------
# Numerics: f32 bit-identity, bf16 error bound
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("h,w,depth,stripe", [
    (24, 128, 8, None),    # auto stripe, smallest legal block
    (40, 256, 8, 8),       # explicit minimum stripe
    (72, 384, 16, 24),     # stripe not a power of two, depth 16
    (24, 128, 16, None),   # depth taller than two stripes
])
@pytest.mark.parametrize("buffering", [1, kpipe.PIPELINE_SLOTS])
def test_pipeline_f32_bit_identical_to_reference(
        eight_devices, h, w, depth, stripe, buffering):
    """Property grid: f32 output is BIT-identical (array_equal, not
    allclose) to the jnp reference sweep for both the synchronous
    control (buffering=1) and the 3-slot rotation — the pipeline
    reorders the *stream*, never the arithmetic."""
    comm = _comm(eight_devices)
    g = _grid(h, w)
    fn = kpipe.make_pipeline_stencil_fn(
        comm, depth, h, w, depth=depth, stripe=stripe,
        buffering=buffering, interpret=True,
    )
    out = np.asarray(fn(jnp.asarray(g)))
    ref = mstencil.reference_stencil(g, depth)
    assert np.array_equal(out, ref)


def test_pipeline_f32_bit_identical_distributed(eight_devices):
    """The fused halo refresh keeps bit-identity on a 2x2 mesh."""
    comm = _comm(eight_devices, shape=(2, 2))
    g = _grid(64, 256)
    fn = kpipe.make_pipeline_stencil_fn(
        comm, 8, 64, 256, depth=8, interpret=True,
    )
    out = np.asarray(fn(jnp.asarray(g)))
    assert np.array_equal(out, mstencil.reference_stencil(g, 8))


def test_pipeline_f32_multiple_passes(eight_devices):
    """iterations > depth chains passes through the same rotation."""
    comm = _comm(eight_devices)
    g = _grid(64, 256)
    fn = kpipe.make_pipeline_stencil_fn(
        comm, 16, 64, 256, depth=8, interpret=True,
    )
    out = np.asarray(fn(jnp.asarray(g)))
    assert np.array_equal(out, mstencil.reference_stencil(g, 16))


#: Pinned bf16 contract: one depth-8 pass of the bf16-compute variant
#: (f32 state, f32 accumulate, bf16 neighbour math) stays within this
#: absolute error of the f32 reference. Loosening it is an API change.
BF16_PASS_ATOL = 0.05


def test_pipeline_bf16_error_bound(eight_devices):
    comm = _comm(eight_devices)
    g = _grid(32, 128)
    fn = kpipe.make_pipeline_stencil_fn(
        comm, 8, 32, 128, depth=8, stripe=16,
        compute_dtype="bfloat16", interpret=True,
    )
    out = np.asarray(fn(jnp.asarray(g)))
    ref = mstencil.reference_stencil(g, 8)
    assert out.dtype == np.float32  # state stays f32
    assert np.allclose(out, ref, atol=BF16_PASS_ATOL)
    # and the variant is genuinely different math, not a cast no-op
    assert not np.array_equal(out, ref)


# ---------------------------------------------------------------------------
# Feasibility: VMEM mirrors + named exclusions
# ---------------------------------------------------------------------------


def test_vmem_mirrors_agree():
    """The kernel's footprint arithmetic IS the cost model's — the
    drift guard that keeps 'modeled feasible' and 'actually loads'
    the same predicate."""
    assert kpipe.PIPELINE_VMEM_BYTES == cm.VMEM_LIMIT_BYTES
    assert kpipe.PIPELINE_SLOTS == cm.STENCIL_PIPELINE_SLOTS
    for depth in cm.STENCIL_PIPELINE_DEPTHS:
        for stripe in cm.STENCIL_PIPELINE_STRIPES:
            for buffering in (1, 3):
                assert kpipe.pipeline_vmem_bytes(
                    stripe, 8192, depth, buffering
                ) == cm.stencil_pipeline_vmem_bytes(
                    stripe, 8192, depth, buffering
                )


def test_candidate_feasibility_matches_the_kernel_gate():
    """Every candidate the model ranks must actually be loadable, and
    every VMEM exclusion must actually not fit."""
    cands = cm.stencil_pipeline_candidates()
    for c in cands:
        if c.knobs["algorithm"] != "pipeline":
            continue
        assert cm.stencil_pipeline_vmem_bytes(
            c.knobs["stripe"], 8192, c.knobs["depth"]
        ) <= cm.VMEM_LIMIT_BYTES, c.name
        assert kpipe.pipeline_supported(
            8192, 8192, jnp.float32, c.knobs["depth"],
            stripe=c.knobs["stripe"],
            compute_dtype=c.knobs["compute_dtype"],
        ), c.name
    vmem_excluded = [c for c in cands.excluded if "vmem" in c.note]
    assert vmem_excluded
    for c in vmem_excluded:
        assert cm.stencil_pipeline_vmem_bytes(
            c.knobs["stripe"], 8192, c.knobs["depth"]
        ) > cm.VMEM_LIMIT_BYTES, c.name


def test_candidates_pipelined_strictly_dominates_sync():
    """The tentpole claim at the canonical 8192x8192: the best
    pipelined candidate strictly beats the synchronous control, and
    the refusals are named (d32/t128 blows the frame; any t=256 does)."""
    cands = cm.stencil_pipeline_candidates()
    assert cands[0].name == "pipe:d8:t128:f32"
    assert cands[0].knobs["buffering"] == kpipe.PIPELINE_SLOTS
    sync = next(c for c in cands if c.knobs["algorithm"] == "sync")
    assert sync.name == "sync:d16:t128:f32"
    assert cands[0].modeled_us < sync.modeled_us
    # deeper/wider than the legacy ceiling is actually explored
    assert any(c.knobs["depth"] > 16 for c in cands)
    assert any(c.knobs["compute_dtype"] == "bfloat16" for c in cands)
    excl = {c.name: c.note for c in cands.excluded}
    assert "pipe:d32:t128:f32" in excl
    assert "scoped-VMEM frame" in excl["pipe:d32:t128:f32"]
    assert all("EXCLUDED" in note for note in excl.values())


def test_non_f32_state_dtype_excludes_the_family():
    cands = cm.stencil_pipeline_candidates(dtype="float64")
    assert len(cands) == 0
    assert cands.excluded
    assert all("float64" in c.note for c in cands.excluded)


def test_pipeline_stripe_picker_names_exclusions():
    """r18 small fix, pipeline edition: the picker's companion names
    the pick and the refusal instead of a bare None."""
    stripe, note = kpipe.pick_pipeline_stripe_explained(8192, 8192, 8)
    assert stripe == 128 and "128" in note
    none, note = kpipe.pick_pipeline_stripe_explained(8192, 8192, 7)
    assert none is None and "multiple of 8" in note
    assert kpipe._pick_pipeline_stripe(8192, 8192, 7) is None


def test_legacy_pickers_explain_their_fallbacks():
    """The r18 small fix: ``_pick_tile``/``_pick_stripe``/
    ``_pick_col_tile`` used to silently return None; their explained
    companions now name the reason, and the legacy entry points
    delegate (same picks as before)."""
    tile, note = kstencil.pick_tile_explained(8192, 8192)
    assert tile == 64 and "divisor" in note
    assert kstencil._pick_tile(8192, 8192) == 64
    none, note = kstencil.pick_tile_explained(7, 128)
    assert none is None and "EXCLUDED" in note and "unfused" in note
    assert kstencil._pick_tile(7, 128) is None

    stripe, note = ktemporal.pick_stripe_explained(8192, 8192, 8)
    assert stripe == 32
    assert ktemporal._pick_stripe(8192, 8192, 8) == 32
    none, note = ktemporal.pick_stripe_explained(7, 128, 8)
    assert none is None and "EXCLUDED" in note

    col, note = ktemporal.pick_col_tile_explained(8448)
    assert col == 1408 and "128-lane divisor" in note
    assert ktemporal._pick_col_tile(8448) == 1408
    none, note = ktemporal.pick_col_tile_explained(100)
    assert none is None and "EXCLUDED" in note


# ---------------------------------------------------------------------------
# Overlap proof: the stripe-stream replay
# ---------------------------------------------------------------------------


def test_sync_stream_is_dma_wait_bound():
    """buffering=1 serializes fetch -> compute -> writeback: both
    ranks idle ~half the makespan on the DMA wait edge, the wire never
    holds more than one message in flight, and the decomposer files
    idle-fraction findings — the defect the pipeline exists to fix."""
    rep = aperf.decompose_stencil_stream(buffering=1)
    worst = max(r["idle_fraction"] for r in rep.per_rank)
    assert worst > aperf.IDLE_FRACTION_THRESHOLD
    assert not rep.ok
    assert {f.check for f in rep.findings} == {"idle-fraction"}
    assert max(w["depth"] for w in rep.wires) <= 1


def test_pipelined_stream_proves_overlap():
    """The 3-slot rotation drops DMA-wait idle under the threshold
    with measured wire depth >= 2 and zero findings — overlap is
    *proven* by replay, not asserted by construction."""
    rep = aperf.decompose_stencil_stream(buffering=3)
    worst = max(r["idle_fraction"] for r in rep.per_rank)
    assert worst < aperf.IDLE_FRACTION_THRESHOLD
    assert rep.ok, [f.check for f in rep.findings]
    assert max(w["depth"] for w in rep.wires) >= 2
    assert aperf.stencil_overlap_fraction(rep) > 0.9


def test_pipelined_makespan_strictly_beats_sync():
    sync = aperf.decompose_stencil_stream(buffering=1)
    pipe = aperf.decompose_stencil_stream(buffering=3)
    assert pipe.makespan_s < sync.makespan_s
    # and by a margin, not an epsilon: the stream was half idle
    assert pipe.makespan_s < 0.5 * sync.makespan_s


def test_analytic_expectations_track_the_model():
    """The committed stencil expectations price through the ONE cost
    model the analytic-regression rule replays — symmetric keysets,
    matching values (the scoreboard's expectation-plumbing guard)."""
    pred = aperf.analytic_predictions()
    for key in ("stencil_pipeline_8192_sweep_us",
                "stencil_sync_8192_sweep_us"):
        assert key in aperf.ANALYTIC_EXPECTED_US
        assert key in pred
        assert aperf.ANALYTIC_EXPECTED_US[key] == pytest.approx(
            pred[key], rel=0.02
        )
    assert (aperf.ANALYTIC_EXPECTED_US["stencil_pipeline_8192_sweep_us"]
            < aperf.ANALYTIC_EXPECTED_US["stencil_sync_8192_sweep_us"])


# ---------------------------------------------------------------------------
# Plumbing: sweep -> cache -> engine -> online vocabulary -> bench
# ---------------------------------------------------------------------------


def test_sweep_stencil_persists_a_pipelined_winner():
    """A narrow CPU sweep (interpret-mode correctness gate + replay-
    adjusted model pricing) lands a pipelined entry at the canonical
    key with all five knobs — the cache vocabulary the engine and the
    online tuner consume."""
    from smi_tpu.tuning.sweep import sweep_stencil

    cache = sweep_stencil(
        depths=(8,), stripes=(64,), runs=1, proxy_shape=(128, 256),
    )
    entries = [e for sig, e in cache.entries.items()
               if sig.startswith("stencil_pipeline|8192|float32|")]
    assert len(entries) == 1
    entry = entries[0]
    assert entry.knobs["algorithm"] == "pipeline"
    assert entry.knobs["buffering"] == kpipe.PIPELINE_SLOTS
    assert entry.knobs["depth"] == 8 and entry.knobs["stripe"] == 64
    assert entry.cost_us is not None and entry.cost_us > 0
    assert entry.provenance.startswith("sweep:stencil:")


@pytest.mark.slow
def test_sweep_stencil_full_grid_winner_is_the_modeled_best():
    from smi_tpu.tuning.sweep import sweep_stencil

    cache = sweep_stencil(runs=1)
    entries = [e for sig, e in cache.entries.items()
               if sig.startswith("stencil_pipeline|8192|float32|")]
    assert len(entries) == 1
    assert entries[0].knobs["algorithm"] == "pipeline"
    assert entries[0].knobs["depth"] == 8
    assert entries[0].knobs["stripe"] == 128
    assert entries[0].knobs["compute_dtype"] == "float32"


def test_seeded_pipeline_entry_reachable_through_the_engine():
    from smi_tpu.tuning.engine import PlanEngine
    from smi_tpu.tuning.seeded import (
        SEEDED_DEVICE_KIND,
        SEEDED_STENCIL_PIPELINE_KNOBS,
        seeded_cache,
    )

    e = PlanEngine(cache=seeded_cache(), device_kind=SEEDED_DEVICE_KIND)
    got = e.stencil_pipeline_knobs()
    assert got is not None
    knobs, layer = got
    assert knobs == SEEDED_STENCIL_PIPELINE_KNOBS
    assert layer == "cache"
    text = e.stencil_pipeline_plan().explain()
    assert "buffering = 3" in text and "[cache]" in text
    # the seeded winner matches the model's best feasible candidate
    assert cm.stencil_pipeline_candidates()[0].knobs == knobs


def test_engine_plan_names_exclusions_and_legacy_tiers():
    """``tune --explain stencil`` content: the table, the named VMEM
    exclusions, and the legacy pickers' verdicts in one rendering."""
    from smi_tpu.tuning.engine import PlanEngine
    from smi_tpu.tuning import PlanCache

    text = PlanEngine(
        cache=PlanCache(), device_kind="cpu"
    ).stencil_pipeline_plan().explain()
    assert "pipe:d8:t128:f32" in text
    assert "sync:d16:t128:f32" in text
    assert "[model]" in text
    assert "excluded pipe:d32:t128:f32" in text
    assert "scoped-VMEM frame" in text
    for tier in ("pipeline tier", "temporal tier",
                 "temporal-tiled tier", "fused tier"):
        assert tier in text


def test_planned_stencil_pipeline_never_raises(monkeypatch):
    from smi_tpu.tuning import engine

    assert engine.planned_stencil_pipeline() is None or isinstance(
        engine.planned_stencil_pipeline(), dict
    )
    monkeypatch.setattr(
        engine, "get_engine",
        lambda: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    assert engine.planned_stencil_pipeline() is None


def test_online_tuner_vocabulary_includes_the_pipeline():
    """The retuner can name every candidate: op_candidates exposes the
    priced grid with the candidate name as the algorithm knob (the
    tuner's vocabulary), excluded configs and all."""
    from smi_tpu.tuning import online

    assert "stencil_pipeline" in online.TUNABLE_OPS
    cands = online.op_candidates(
        "stencil_pipeline", 8192 * 8192 * 4, cm.TopologySpec(n=1)
    )
    assert cands
    assert cands[0].knobs["algorithm"] == cands[0].name
    assert any(c.name.startswith("sync:") for c in cands)
    assert cands.excluded


def test_flash_kv_stream_double_buffers_or_is_excluded():
    """The r18 flash treatment: every ranked forward tile carries the
    ``kv_buffering: 2`` contract, and a tile that only fits
    single-buffered (f32 bq4096/bk2048) is excluded with the
    no-double-buffer reason rather than ranked into a serializing
    config."""
    f32 = cm.flash_block_candidates(4096, 128, "float32", False)
    assert all(c.knobs["kv_buffering"] == 2 for c in f32)
    excl = {c.name: c.note for c in f32.excluded}
    assert "bq4096/bk2048" in excl
    assert "no-double-buffer" in excl["bq4096/bk2048"]
    bf16 = cm.flash_block_candidates(4096, 128, "bfloat16", False)
    assert any(c.name == "bq4096/bk2048" for c in bf16)
    # the mirror the perf lint prices with is the same arithmetic
    assert cm.flash_single_buffer_vmem_bytes(
        2048, 2048, 128, 4
    ) == aperf.flash_single_buffer_bytes(2048, 2048, 128, 4)


# ---------------------------------------------------------------------------
# CLI + bench surfaces
# ---------------------------------------------------------------------------


def test_cli_tune_explain_stencil_runs_on_cpu(capsys):
    from smi_tpu.__main__ import main

    assert main(["tune", "--explain", "stencil"]) == 0
    out = capsys.readouterr().out
    assert "pipe:d8:t128:f32" in out
    assert "sync:d16:t128:f32" in out
    assert "modeled_us" in out and "measured_us" in out
    assert "buffering" in out and "compute_dtype" in out
    assert "[model]" in out or "[cache]" in out
    assert "excluded pipe:d32:t128:f32" in out
    assert "scoped-VMEM frame" in out


def test_cli_tune_unknown_op_usage_error_names_stencil(capsys, tmp_path):
    from smi_tpu.__main__ import main

    rc = main(["tune", "--ops", "bogus",
               "--cache", str(tmp_path / "plans.json")])
    assert rc == 2
    err = capsys.readouterr().err
    assert "unknown op" in err and "stencil" in err


def test_bench_pipeline_field_additive_schema():
    """The bench line gains an additive ``pipeline`` field (knobs +
    replay-proven overlap fraction); the legacy metric/value/unit/
    vs_baseline contract is untouched."""
    import bench

    pf = bench.pipeline_fields()
    assert pf["enabled"] is True
    assert pf["buffering"] >= 2
    assert pf["depth"] and pf["stripe"] and pf["compute_dtype"]
    assert pf["overlap_fraction"] > 0.9
    assert isinstance(pf["source"], str)
    payload = {"metric": "m", "value": 1.0, "unit": "u",
               "vs_baseline": 2.0, "pipeline": pf}
    parsed = json.loads(bench.render_line(payload))
    assert parsed["pipeline"]["overlap_fraction"] == pf["overlap_fraction"]
    with pytest.raises(ValueError, match="legacy key"):
        bench.render_line({"metric": "m", "value": 1.0, "unit": "u",
                           "pipeline": pf})
