"""Runtime watchdogs: deadlines that turn indefinite hangs into errors.

The reference guards every test collective with a detached-thread
duration assert (``test/p2p/test_p2p.cpp:30-42`` — hang ⇒ abort); the
framework's own test tier keeps that behaviour (``tests/conftest.py``).
This module is the *runtime* analog for production entry points: a
:class:`Deadline` is threaded through channel transfers and ring-tier
collective dispatch, and :func:`run_with_deadline` hard-bounds
host-side blocking work (execution + readback, e.g.
:func:`smi_tpu.utils.tracing.timed`).

What a deadline can and cannot interrupt, honestly stated:

- **dispatch-level checks** (``Deadline.check`` between collective
  launches / ring hops / stream bursts) are cooperative — they fire at
  the next host-side step, converting a stuck multi-hop pipeline into
  an early, named timeout instead of a silent stall;
- **hard watchdogs** (:func:`run_with_deadline`) run the blocking call
  in a worker thread and abandon it on expiry. The XLA call cannot be
  cancelled — the worker leaks until the runtime returns — but the
  caller gets a :class:`WatchdogTimeout` instead of hanging forever,
  which is what CI and launch scripts need.

Every timeout carries a *state dump* when a provider is given; the ring
tier wires :func:`smi_tpu.parallel.faults.mirror_state_provider` in, so
a hung collective reports the per-rank protocol state of its credit
state machine — which wait each rank parks at when no remote traffic
completes — rather than a bare "timed out".

No JAX import here: the module is usable from the pure-Python protocol
layer and from test tooling alike.
"""

from __future__ import annotations

import math
import os
import queue
import threading
import time
from typing import Any, Callable, Optional

#: Environment knob: a default watchdog budget (seconds) applied when
#: callers construct :func:`default_deadline`. Unset/empty = no default
#: watchdog (zero overhead on the healthy path).
WATCHDOG_ENV = "SMI_WATCHDOG_SECS"


class WatchdogTimeout(TimeoutError):
    """A deadline expired; carries the protocol-state dump if known.

    ``state_dump`` is the formatted per-rank dump (or None); ``elapsed``
    and ``budget`` are seconds. ``state`` is the STRUCTURED per-rank
    dump (the :meth:`credits.RingSimulator.state_dump` dict) when the
    provider supplied one — the machine-readable payload
    :func:`smi_tpu.parallel.recovery.failed_ranks_of` extracts
    crash-stopped ranks from to drive a ULFM-style shrink.
    """

    def __init__(self, message: str, state_dump: Optional[str] = None,
                 elapsed: Optional[float] = None,
                 budget: Optional[float] = None,
                 state: Optional[dict] = None):
        if state_dump:
            message = f"{message}\n{state_dump}"
        super().__init__(message)
        self.state_dump = state_dump
        self.elapsed = elapsed
        self.budget = budget
        self.state = state


def _attach_recorder_tail(error: BaseException, recorder) -> None:
    """Bounded flight-recorder tail onto a timeout in flight
    (duck-typed — this module stays importable without the obs layer,
    the protocol-mirror discipline): ``error.recorder_tail`` always,
    and a ``flight_recorder`` entry inside the structured ``state``
    dict when the error carries one. Never raises."""
    if recorder is None:
        return
    try:
        tail = recorder.tail()
        error.recorder_tail = tail
        state = getattr(error, "state", None)
        if isinstance(state, dict):
            state.setdefault("flight_recorder", tail)
    except Exception:
        pass


class Deadline:
    """A monotonic time budget shared across the steps of one operation.

    Construct once at the entry point, pass down: each dispatch step
    calls :meth:`check` (or reads :meth:`remaining` for a blocking
    wait's own timeout). ``state_provider`` is a zero-arg callable
    returning the dump to attach on expiry (e.g.
    ``faults.mirror_state_provider("reduce", n)``). ``recorder`` is an
    optional flight recorder (:mod:`smi_tpu.obs.events`): an expiring
    deadline then carries the recorder's bounded event tail next to
    the protocol mirror — the hang's causal history, not just its
    final state.
    """

    def __init__(self, seconds: Optional[float],
                 state_provider: Optional[Callable[[], str]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 recorder=None):
        if seconds is not None and seconds < 0:
            raise ValueError(f"deadline must be >= 0, got {seconds}")
        self.budget = seconds
        self.state_provider = state_provider
        self.recorder = recorder
        self._clock = clock
        self._start = clock()

    def elapsed(self) -> float:
        return self._clock() - self._start

    def remaining(self) -> Optional[float]:
        """Seconds left (None = unbounded; never negative)."""
        if self.budget is None:
            return None
        return max(0.0, self.budget - self.elapsed())

    def expired(self) -> bool:
        return self.budget is not None and self.elapsed() >= self.budget

    def _dump(self):
        """(text, structured) from the provider — a provider may return
        a bare string, or a ``(str, dict)`` pair whose dict rides the
        error's ``state`` attribute for programmatic recovery."""
        if self.state_provider is None:
            return None, None
        try:
            dump = self.state_provider()
        except Exception as e:  # the dump must never mask the timeout
            return (
                f"(state dump unavailable: {type(e).__name__}: {e})",
                None,
            )
        if isinstance(dump, tuple) and len(dump) == 2:
            return dump
        return dump, None

    def check(self, context: str = "") -> None:
        """Raise :class:`WatchdogTimeout` if the budget is spent."""
        if not self.expired():
            return
        where = f" during {context}" if context else ""
        text, state = self._dump()
        error = WatchdogTimeout(
            f"deadline of {self.budget:.3g}s exceeded{where} "
            f"(elapsed {self.elapsed():.3g}s)",
            state_dump=text, state=state,
            elapsed=self.elapsed(), budget=self.budget,
        )
        _attach_recorder_tail(error, self.recorder)
        raise error

    def with_provider(self, state_provider: Callable[[], str]) -> "Deadline":
        """Same running clock, different dump source — lets inner layers
        attach their own protocol mirror without restarting the budget."""
        d = Deadline.__new__(Deadline)
        d.budget = self.budget
        d.state_provider = state_provider
        d.recorder = self.recorder
        d._clock = self._clock
        d._start = self._start
        return d


def default_deadline(
    state_provider: Optional[Callable[[], str]] = None,
) -> Optional[Deadline]:
    """A :class:`Deadline` from ``$SMI_WATCHDOG_SECS``, or None.

    Unset, empty, and non-positive values all mean "no watchdog" —
    ``SMI_WATCHDOG_SECS=0`` is off, not an instantly-expired budget.
    A malformed value is a LOUD error naming the knob and the value
    (the ``$SMI_TPU_RS_AG_MIN_BYTES`` discipline): a typo silently
    disabling the watchdog would undo the operator's intent without
    a trace.
    """
    raw = os.environ.get(WATCHDOG_ENV, "").strip()
    if not raw:
        return None
    try:
        seconds = float(raw)
    except ValueError:
        raise ValueError(
            f"${WATCHDOG_ENV} must be a number of seconds (watchdog "
            f"budget; 0 or negative disables), got {raw!r}"
        ) from None
    if not math.isfinite(seconds):
        # NaN never compares expired; +inf is a watchdog that never
        # fires — both silently disable the watchdog, the exact
        # outcome malformed values must not have (0 is the explicit
        # off switch)
        raise ValueError(
            f"${WATCHDOG_ENV} must be a finite number of seconds "
            f"(watchdog budget; 0 or negative disables), got {raw!r}"
        )
    if seconds <= 0:
        return None
    return Deadline(seconds, state_provider=state_provider)


def run_with_deadline(
    fn: Callable[[], Any],
    seconds: Optional[float],
    state_provider: Optional[Callable[[], str]] = None,
    context: str = "",
    recorder=None,
) -> Any:
    """Run ``fn()`` with a hard time budget.

    The call runs in a daemon worker thread; on expiry the caller gets
    a :class:`WatchdogTimeout` (with the state dump) while the worker is
    abandoned — a hung XLA call cannot be cancelled from Python, but the
    host stops waiting on it. ``seconds=None`` runs inline (no thread,
    no overhead). Exceptions from ``fn`` propagate unchanged.

    NOTE: do not wrap *tracing* in this — JAX trace contexts are
    thread-local. Wrap the blocking *execution/readback* step (that is
    what :func:`smi_tpu.utils.tracing.timed` does).

    The worker is a *daemon* thread on purpose: a non-daemon thread (or
    a ThreadPoolExecutor worker) is joined at interpreter exit, so an
    abandoned hung call would stall process shutdown — the exact hang
    the watchdog exists to bound.
    """
    if seconds is None:
        return fn()
    results: "queue.Queue" = queue.Queue(maxsize=1)

    def worker() -> None:
        try:
            results.put(("ok", fn()))
        except BaseException as e:  # deliver, don't die silently
            results.put(("err", e))

    start = time.monotonic()
    thread = threading.Thread(
        target=worker, name="smi-watchdog-worker", daemon=True
    )
    thread.start()
    try:
        kind, value = results.get(timeout=seconds)
    except queue.Empty:
        dump, state = None, None
        if state_provider is not None:
            try:
                dump = state_provider()
            except Exception as e:
                dump = f"(state dump unavailable: {type(e).__name__}: {e})"
            if isinstance(dump, tuple) and len(dump) == 2:
                dump, state = dump
        where = f" during {context}" if context else ""
        error = WatchdogTimeout(
            f"hard watchdog of {seconds:.3g}s exceeded{where} — the "
            f"device call did not complete (worker thread abandoned)",
            state_dump=dump, state=state,
            elapsed=time.monotonic() - start, budget=seconds,
        )
        _attach_recorder_tail(error, recorder)
        raise error from None
    if kind == "err":
        raise value
    return value
