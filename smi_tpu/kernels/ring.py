"""Ring collectives as explicit ICI RDMA Pallas kernels.

Reference parity: the CK_S/CK_R NoC moves packets neighbour-to-neighbour
over serial links with credit flow control (``codegen/templates/cks.cl``,
``ckr.cl``); chain/ring topologies are the routing substrate of the
microbenchmarks (``test/p2p/p2p.json``, ``bandwidth.json``). On TPU the
same neighbour streaming is ``pltpu.make_async_remote_copy`` over ICI,
double-buffered so the send of chunk *k* overlaps the integration of
chunk *k-1* — XLA's built-in collectives do this internally; these
kernels exist for the cases where the schedule must be explicit (fusing
compute into collective steps, the basis for ring-attention-style
overlap) and as the framework's own collective implementation tier.

All kernels are written per-shard (called inside ``shard_map`` over one
mesh axis) and run compiled on TPU or interpreted on the CPU fake mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from smi_tpu.parallel.mesh import Communicator


def _neighbour_barrier(me, n: int, axis_name: str):
    """Block until both ring neighbours entered the kernel, so no RDMA
    lands in a buffer that is still being initialized."""
    del axis_name
    barrier = pltpu.get_barrier_semaphore()
    nn = jnp.int32(n)  # keep arithmetic in int32 even under jax_enable_x64
    left = lax.rem(me - 1 + nn, nn)
    right = lax.rem(me + 1, nn)
    pltpu.semaphore_signal(
        barrier, inc=1, device_id=left,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    pltpu.semaphore_signal(
        barrier, inc=1, device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    pltpu.semaphore_wait(barrier, 2)


def _grant_slot(credit_sem, slot, me, n: int):
    """Tell the left neighbour (the writer into our comm_buf) that
    ``slot`` is free to be overwritten."""
    left = lax.rem(me - 1 + jnp.int32(n), jnp.int32(n))
    pltpu.semaphore_signal(
        credit_sem.at[slot], inc=1, device_id=left,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )


def _ring_all_gather_kernel(
    x_ref, o_ref, comm_buf, send_sem, recv_sem, credit_sem,
    *, axis_name: str, n: int, flow_control: bool
):
    """Each device forwards the chunk it most recently received to its
    right neighbour; after n-1 steps everyone holds every chunk.

    Flow control: a writer may only RDMA into a remote slot after the
    remote granted it (credit semaphore) — slot 1 is granted at start
    (empty), and each slot is re-granted once its content has been
    forwarded onward (send complete). Without this a fast rank could
    clobber a slow neighbour's unsent chunk; the interpret-mode tests
    run ranks sequentially and cannot catch that race."""
    me = lax.axis_index(axis_name)
    chunk = x_ref.shape[0]
    if flow_control:
        _neighbour_barrier(me, n, axis_name)
    o_ref[pl.ds(me * chunk, chunk), ...] = x_ref[...]
    comm_buf[0] = x_ref[...]
    if flow_control:
        _grant_slot(credit_sem, 1, me, n)  # slot 1 starts empty

    def step(s, _):
        nn = jnp.int32(n)
        src_rank = lax.rem(me - s - 1 + nn, nn)  # whose chunk arrives now
        dst = lax.rem(me + 1, nn)
        slot, nslot = s % 2, (s + 1) % 2
        if flow_control:
            # wait until the remote says its slot `nslot` is reusable
            pltpu.semaphore_wait(credit_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        if flow_control:
            # our slot `slot` has now been sent onward: grant it upstream
            _grant_slot(credit_sem, slot, me, n)
        o_ref[pl.ds(src_rank * chunk, chunk), ...] = comm_buf[nslot]
        return ()

    lax.fori_loop(0, n - 1, step, ())


def ring_all_gather(
    x: jax.Array,
    axis_name: str,
    n: int,
    interpret: bool = False,
) -> jax.Array:
    """All-gather ``x`` (this shard's chunk) along a ring.

    Call inside ``shard_map``; returns the ``(n * chunk, ...)`` gathered
    array on every rank. Equivalent to ``lax.all_gather(..., tiled=True)``
    but with an explicit neighbour-ring schedule.
    """
    chunk = x.shape[0]
    out_shape = jax.ShapeDtypeStruct((n * chunk,) + x.shape[1:], x.dtype)
    # Interpret mode executes ranks sequentially and does not implement
    # remote semaphore signals; the credit protocol is only live (and only
    # needed) in compiled multi-chip execution.
    kernel = functools.partial(
        _ring_all_gather_kernel, axis_name=axis_name, n=n,
        flow_control=not interpret,
    )
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2,) + x.shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            collective_id=0, has_side_effects=True
        ),
        interpret=interpret,
    )(x)


def _ring_all_reduce_kernel(
    x_ref, o_ref, comm_buf, send_sem, recv_sem, credit_sem,
    *, axis_name: str, n: int, flow_control: bool
):
    """Circulating-partial ring reduce: every rank simultaneously streams
    its running partial to its right neighbour and folds its own
    contribution into what arrives; after n-1 hops every rank holds the
    full sum (each via a rotated association order)."""
    me = lax.axis_index(axis_name)
    if flow_control:
        _neighbour_barrier(me, n, axis_name)
    comm_buf[0] = x_ref[...]
    if flow_control:
        _grant_slot(credit_sem, 1, me, n)

    # After step s each rank's live slot holds the sum of the s+2
    # contributions x_{me-s-1} + ... + x_{me}; after n-1 steps that is the
    # full sum on every rank simultaneously (each accumulated a rotated
    # association order).
    def step(s, _):
        slot, nslot = s % 2, (s + 1) % 2
        dst = lax.rem(me + 1, jnp.int32(n))
        if flow_control:
            pltpu.semaphore_wait(credit_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        if flow_control:
            _grant_slot(credit_sem, slot, me, n)
        comm_buf[nslot] = comm_buf[nslot] + x_ref[...]
        return ()

    lax.fori_loop(0, n - 1, step, ())
    final_slot = (n - 1) % 2
    o_ref[...] = comm_buf[final_slot]


def ring_all_reduce(
    x: jax.Array,
    axis_name: str,
    n: int,
    interpret: bool = False,
) -> jax.Array:
    """Sum-all-reduce along a ring with explicit neighbour RDMA.

    Each rank's partial sum makes a full circuit: after ``n-1`` hops every
    rank has accumulated all ``n`` contributions (each rank accumulates a
    rotated order, so sums match up to float reassociation).
    """
    kernel = functools.partial(
        _ring_all_reduce_kernel, axis_name=axis_name, n=n,
        flow_control=not interpret,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2,) + x.shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            collective_id=1, has_side_effects=True
        ),
        interpret=interpret,
    )(x)


def make_ring_all_gather(comm: Communicator, interpret: bool = False):
    """Jitted wrapper: sharded input chunks → replicated gathered array."""
    axis = comm.axis_names[0]
    n = comm.size

    def shard(x):
        return ring_all_gather(x, axis, n, interpret=interpret)

    return jax.jit(
        jax.shard_map(
            shard, mesh=comm.mesh, in_specs=P(axis), out_specs=P(None),
            check_vma=False,
        )
    )


def make_ring_all_reduce(comm: Communicator, interpret: bool = False):
    axis = comm.axis_names[0]
    n = comm.size

    def shard(x):
        if x.shape[0] != 1:
            raise ValueError(
                f"make_ring_all_reduce expects one row per shard (global "
                f"leading dim == comm size {n}); got local shape {x.shape}"
            )
        return ring_all_reduce(x[0], axis, n, interpret=interpret)[None]

    return jax.jit(
        jax.shard_map(
            shard, mesh=comm.mesh, in_specs=P(axis), out_specs=P(axis),
            check_vma=False,
        )
    )
