// smi-manifest: extract the communication-op manifest from user sources.
//
// Usage: smi-manifest [--no-rendezvous] [--no-validate] FILE...
//
// Prints one JSON object per discovered op on stdout (the reference
// rewriter's protocol, source-rewriter/src/ops/ops.cpp:24-40 consumed by
// codegen/rewrite.py:36-57) and diagnostics on stderr. Exit status: 0 on
// success, 1 on scan errors or port-uniqueness violations.

#include <fstream>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

#include "scanner.h"

int main(int argc, char** argv) {
  bool rendezvous = true;
  bool validate = true;
  std::vector<std::string> files;
  for (int i = 1; i < argc; i++) {
    std::string arg = argv[i];
    if (arg == "--no-rendezvous") {
      rendezvous = false;
    } else if (arg == "--no-validate") {
      validate = false;
    } else if (arg == "--help" || arg == "-h") {
      std::cout << "usage: smi-manifest [--no-rendezvous] [--no-validate] "
                   "FILE...\n";
      return 0;
    } else {
      files.push_back(arg);
    }
  }
  if (files.empty()) {
    std::cerr << "smi-manifest: no input files\n";
    return 1;
  }

  std::vector<smi::Operation> all_ops;
  bool failed = false;
  for (const auto& path : files) {
    std::ifstream in(path);
    if (!in) {
      std::cerr << "smi-manifest: cannot open " << path << "\n";
      failed = true;
      continue;
    }
    std::ostringstream buf;
    buf << in.rdbuf();
    smi::ScanResult result = smi::scan_source(buf.str(), path);
    for (const auto& err : result.errors) {
      std::cerr << "smi-manifest: " << err << "\n";
      failed = true;
    }
    all_ops.insert(all_ops.end(), result.ops.begin(), result.ops.end());
  }

  if (validate) {
    for (const auto& err : smi::validate_ops(all_ops, rendezvous)) {
      std::cerr << "smi-manifest: " << err << "\n";
      failed = true;
    }
  }

  std::cout << smi::to_json_lines(all_ops);
  return failed ? 1 : 0;
}
