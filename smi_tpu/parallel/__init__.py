"""TPU data plane: mesh/communicator, P2P channels, collectives, routing.

This package is the substrate half of the framework: the reference's
generated NoC (CK_S/CK_R routing kernels + per-op support kernels,
``codegen/templates/``) is replaced by a ``jax.sharding.Mesh`` with XLA
collectives and masked ``ppermute`` inside ``shard_map``; the routing-table
machinery survives as a capability tier that maps logical ports onto mesh
neighbourhoods.
"""
