"""Pallas kernel tests: interpreter mode on the CPU fake mesh.

The fused stencil kernel is additionally compiled for real TPU by
bench.py; here interpret mode checks numerics on the same code path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import smi_tpu as smi
from smi_tpu.kernels import ring as kring
from smi_tpu.kernels import stencil as kstencil
from smi_tpu.models import stencil


def test_fused_stencil_matches_reference_interpret(eight_devices):
    comm = smi.make_communicator(
        shape=(2, 2), axis_names=("sx", "sy"), devices=eight_devices
    )
    g = stencil.initial_grid(32, 256)
    g[:, -1] = 2.0
    fn = kstencil.make_fused_stencil_fn(comm, 4, 32, 256, interpret=True)
    out = np.asarray(fn(jnp.asarray(g)))
    ref = stencil.reference_stencil(g, 4)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_fused_stencil_single_rank_interpret(eight_devices):
    comm = smi.make_communicator(
        shape=(1, 1), axis_names=("sx", "sy"), devices=eight_devices
    )
    g = stencil.initial_grid(16, 128)
    fn = kstencil.make_fused_stencil_fn(comm, 3, 16, 128, interpret=True)
    out = np.asarray(fn(jnp.asarray(g)))
    ref = stencil.reference_stencil(g, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_pallas_supported_gating():
    assert kstencil.pallas_supported(512, 1024, jnp.float32)
    assert not kstencil.pallas_supported(512, 1000, jnp.float32)  # lanes
    assert not kstencil.pallas_supported(7, 128, jnp.float32)     # rows
    assert not kstencil.pallas_supported(512, 1024, jnp.float64)  # dtype


@pytest.mark.parametrize("n", [4, 8])
def test_ring_all_gather_interpret(eight_devices, n):
    comm = smi.make_communicator(n, devices=eight_devices)
    fn = kring.make_ring_all_gather(comm, interpret=True)
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    out = np.asarray(fn(x))
    np.testing.assert_array_equal(out, np.asarray(x))


def test_ring_all_reduce_interpret(eight_devices):
    n = 4
    comm = smi.make_communicator(n, devices=eight_devices)
    fn = kring.make_ring_all_reduce(comm, interpret=True)
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n, 8, 128)
    out = np.asarray(fn(x))
    expected = np.asarray(x).sum(axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5)




def test_ring_kernels_unaligned_lane_widths(eight_devices):
    """Payloads whose lane width is not a 128-multiple stream correctly:
    the wrappers pad to the Mosaic lane tile and slice back (r4 fix —
    Mosaic rejects unaligned slot slices; the AOT tier caught it on the
    corner halo's W+2-wide slabs while interpret mode accepted them)."""
    from jax.sharding import PartitionSpec as P

    n = 4
    comm = smi.make_communicator(n, devices=eight_devices)
    ma = kring.mesh_axes_of(comm)

    def run(shard, in_s, out_s, x):
        f = jax.jit(
            jax.shard_map(shard, mesh=comm.mesh, in_specs=in_s,
                          out_specs=out_s, check_vma=False)
        )
        return np.asarray(f(x))

    # all_gather, width 37
    x = jnp.arange(n * 37, dtype=jnp.float32).reshape(n, 37)
    out = run(
        lambda v: kring.ring_all_gather(
            v.reshape(-1), "smi", n, interpret=True, mesh_axes=ma
        ).reshape(1, -1),
        P("smi", None), P("smi", None), x,
    )
    np.testing.assert_array_equal(out, np.tile(np.asarray(x).reshape(-1), (n, 1)))

    # MAX all_reduce with all-negative values, width 33: the zero pad
    # must never leak into the reduction result
    x2 = -jnp.abs(jnp.arange(n * 33, dtype=jnp.float32).reshape(n, 33)) - 1.0
    out2 = run(
        lambda v: kring.ring_all_reduce(
            v[0], "smi", n, op="max", interpret=True, mesh_axes=ma
        )[None],
        P("smi", None), P("smi", None), x2,
    )
    np.testing.assert_allclose(out2, np.tile(np.asarray(x2).max(0), (n, 1)))

    # reduce_scatter, width 19 (replicated input: every rank contributes
    # the same buffer, so rank r's shard is n * block_r)
    x3 = jnp.arange(2 * n * 19, dtype=jnp.float32).reshape(2 * n, 19)
    out3 = run(
        lambda v: kring.ring_reduce_scatter(
            v, "smi", n, interpret=True, mesh_axes=ma
        ),
        P(None, None), P("smi", None), x3,
    )
    np.testing.assert_allclose(out3, n * np.asarray(x3))

    # neighbour stream, 3 chunks of width 45
    x4 = jnp.arange(n * 3 * 45, dtype=jnp.float32).reshape(n, 3, 45)
    out4 = run(
        lambda v: kring.neighbour_stream(
            v, "smi", n, interpret=True, mesh_axes=ma
        ),
        P("smi", None, None), P("smi", None, None), x4,
    )
    np.testing.assert_allclose(out4, np.roll(np.asarray(x4), 1, axis=0))
# ------------------------------------------------- temporal blocking --


from smi_tpu.kernels import stencil_temporal as ktemporal


@pytest.mark.parametrize(
    "px,py,h,w,iters,depth",
    [
        (1, 1, 32, 256, 8, 8),     # one pass exactly
        (2, 2, 64, 512, 16, 8),    # two passes, 2x2 mesh
        (2, 4, 64, 1024, 20, 8),   # remainder of 4 single sweeps
        (1, 2, 16, 256, 8, 8),     # single stripe per block
        (2, 2, 64, 512, 32, 16),   # bench.py's depth (fastest on v5e)
    ],
)
def test_temporal_stencil_matches_reference(
    eight_devices, px, py, h, w, iters, depth
):
    comm = smi.make_communicator(
        shape=(px, py), axis_names=("sx", "sy"),
        devices=eight_devices[: px * py],
    )
    g = stencil.initial_grid(h, w)
    g[:, -1] = 2.0
    g[h // 2, :] = 0.5
    fn = ktemporal.make_temporal_stencil_fn(
        comm, iters, h, w, depth=depth, interpret=True
    )
    out = np.asarray(fn(jnp.asarray(g)))
    ref = stencil.reference_stencil(g, iters)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_temporal_supported_gating():
    assert ktemporal.temporal_supported(512, 1024, jnp.float32)
    assert not ktemporal.temporal_supported(512, 1000, jnp.float32)  # lanes
    assert not ktemporal.temporal_supported(512, 1024, jnp.float64)  # dtype
    assert not ktemporal.temporal_supported(512, 1024, jnp.float32, depth=7)
    assert ktemporal._pick_stripe(8192, 8192, 8) == 32


def test_halo_exchange_corners(eight_devices):
    """Corner patches carry diagonal-neighbour data (two-phase)."""
    from smi_tpu.parallel.halo import halo_exchange_2d_corners

    comm = smi.make_communicator(
        shape=(2, 2), axis_names=("hx", "hy"), devices=eight_devices[:4]
    )
    d = 2
    g = jnp.arange(16 * 16, dtype=jnp.float32).reshape(16, 16)

    def shard_fn(block):
        h = halo_exchange_2d_corners(block, comm, depth=d)
        # flatten into one array for inspection: rows = top | bottom
        return jnp.concatenate([h.top, h.bottom], axis=0)[None]

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm.mesh, in_specs=P("hx", "hy"),
        out_specs=P(("hx", "hy")), check_vma=False,
    ))
    out = np.asarray(fn(g))  # (4, 2*d, 8+2*d)
    ref = np.asarray(g)
    # rank (1,1) holds block rows 8..16, cols 8..16. Its top halo rows are
    # global rows 6..8, cols 6..18 clipped -> cols 6..16 with d pad:
    top11 = out[3][:d]
    np.testing.assert_array_equal(top11[:, d:-d], ref[6:8, 8:16])
    # corner: top-left d x d patch = diagonal rank (0,0)'s bottom-right
    np.testing.assert_array_equal(top11[:, :d], ref[6:8, 6:8])


def test_temporal_multi_stripe_pipeline(eight_devices, monkeypatch):
    """Force a small VMEM budget so blocks split into several stripes,
    exercising the tail-carry software pipeline (n > 1)."""
    monkeypatch.setattr(ktemporal, "VMEM_BYTES_TARGET", 500_000)
    comm = smi.make_communicator(
        shape=(2, 1), axis_names=("sx", "sy"), devices=eight_devices[:2]
    )
    h, w = 64, 128
    assert ktemporal._pick_stripe(h // 2, w, 8) not in (None, h // 2)
    g = stencil.initial_grid(h, w)
    g[:, -1] = 2.0
    g[h // 2, :] = 0.5
    fn = ktemporal.make_temporal_stencil_fn(
        comm, 16, h, w, depth=8, interpret=True
    )
    out = np.asarray(fn(jnp.asarray(g)))
    ref = stencil.reference_stencil(g, 16)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize(
    "px,py,h,w,t,wc,depth",
    [
        (1, 1, 32, 512, 16, 256, 8),   # 3 col tiles x 2 row stripes
        (1, 2, 16, 256, 16, 128, 8),   # single row stripe per block
        (1, 1, 64, 512, 64, 768, 8),   # single col tile (n_cols=1)
        (2, 2, 64, 512, 16, 256, 8),   # real top/bottom halos + corners
        # depth=16: the trapezoid shrink actually fires (off becomes 8
        # at sweep 8) — depth=8 keeps it a no-op
        (1, 1, 32, 512, 16, 256, 16),
        (2, 2, 64, 512, 16, 256, 16),
    ],
)
def test_temporal_tiled_kernel_matches_reference(
    eight_devices, monkeypatch, px, py, h, w, t, wc, depth
):
    """The column-tiled kernel shape (tall stripes, 3-block column
    reads) is bit-exact vs the serial reference."""
    monkeypatch.setattr(
        ktemporal, "_plan", lambda *_a: ("tiled", (t, wc))
    )
    comm = smi.make_communicator(
        shape=(px, py), axis_names=("sx", "sy"),
        devices=eight_devices[: px * py],
    )
    g = stencil.initial_grid(h, w)
    g[:, -1] = 2.0
    g[h // 2, :] = 0.5
    fn = ktemporal.make_temporal_stencil_fn(
        comm, 16, h, w, depth=depth, interpret=True
    )
    out = np.asarray(fn(jnp.asarray(g)))
    ref = stencil.reference_stencil(g, 16)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_plan_prefers_tiled_for_wide_blocks():
    assert ktemporal._plan(8192, 8192, 8)[0] == "tiled"
    assert ktemporal._plan(32, 256, 8)[0] == "full"
