"""AOT topology compilation: prove multi-chip lowering without the chips.

Reference parity: the reference's emulator tier feeds a real hardware
build stage — ``aoc`` compiles the emulator-tested kernels to bitstream
targets even on hosts with no FPGA attached
(``/root/reference/CMakeLists.txt:159-196``), so toolchain rejections
surface before anyone owns hardware. The TPU analog is JAX AOT
compilation against a :class:`~jax.experimental.topologies.TopologyDescription`:
``jax.jit(fn).lower(shapes).compile()`` over a mesh of *abstract* TPU
devices runs the real XLA SPMD partitioner and the real Mosaic kernel
compiler exactly as a pod of that shape would — on a host that owns one
chip or none. Shape, layout, scratch/semaphore, ``collective_id`` and
partitioning errors all surface here; only data-dependent runtime
behavior (which the interpret tier covers) does not.

This caught a real bug on first contact: the ring kernels passed a
``collective_id`` in no-flow-control mode, which interpret mode accepts
and Mosaic rejects ("collective_id has to be unspecified ... when not
using a custom barrier") — see ``kernels/ring.py::_compiler_params``.

Entry points: :func:`topology_communicator` /
:func:`hybrid_topology_communicator` build communicators over abstract
devices; :func:`compile_sharded` lowers one program;
:func:`check_surface` compiles the framework's full multi-chip surface
— the four ring kernels in both flow-control modes, the flash (dp, sp)
transformer train step, the hierarchical two-tier allreduce, the
multi-kernel-instance ring composites (4-direction halo exchange,
concurrent streams, hop-by-hop P2P, rooted collectives), and the three
reference applications at pod-real shapes — and returns per-program
executable reports. ``python -m smi_tpu aot-verify``
drives it and writes the evidence artifact; ``tests/test_aot_tpu.py``
is the opt-in test tier.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from smi_tpu.parallel.mesh import Communicator, DEFAULT_AXIS

#: Default AOT target: a v5e 2x4 slice — 8 chips, the same extent as the
#: emulator tier's 8 virtual devices, so every emulator-tier program
#: shape compiles unchanged.
DEFAULT_TOPOLOGY = "v5e:2x4"


def parse_topology(topology: str):
    """``"v5e:2x4*2"`` -> ``("v5e:2x4", {"num_slices": 2})``.

    The ``*s`` suffix names a GENUINE multi-slice topology: the PJRT
    TPU plugin materializes ``s`` slices of the base shape, each
    abstract device carrying a real ``slice_index`` — so the SPMD
    partitioner sees an actual DCN boundary, not a virtual split of
    one slice's flat device list.
    """
    if "*" in topology:
        name, s = topology.split("*", 1)
        return name, {"num_slices": int(s)}
    return topology, {}


#: Hard bound (seconds) on the abstract-topology lookup. On a host
#: with a TPU compile client the call returns in well under a second;
#: on a TPU-less host it normally raises quickly — but some PJRT
#: states *hang* instead (observed mid-suite on the CPU tier, where it
#: stalled the whole run until the test watchdog aborted the process).
#: Override with $SMI_AOT_TOPOLOGY_TIMEOUT_S.
TOPOLOGY_LOOKUP_TIMEOUT_S = 45.0


def topology_devices(topology: str = DEFAULT_TOPOLOGY):
    """Abstract devices of a named TPU topology (no hardware needed).

    Raises whatever the platform raises when no TPU compile client is
    reachable — callers (the test tier) turn that into a skip. The
    lookup runs under a hard watchdog
    (:func:`smi_tpu.utils.watchdog.run_with_deadline`), which bounds
    hangs that block with the GIL released. It CANNOT bound the
    GIL-holding spin some libtpu states enter on a TPU-less host — for
    that, set ``SMI_TPU_DISABLE_AOT_TOPOLOGY=1`` (the pytest emulator
    tier does, ``tests/conftest.py``) so the lookup fails fast instead
    of starting.
    """
    import os

    if os.environ.get("SMI_TPU_DISABLE_AOT_TOPOLOGY", "").strip() not in (
        "", "0", "false", "no"
    ):
        # the CPU test tier sets this (tests/conftest.py): with libtpu
        # installed but no TPU attached, the topology client can spin
        # for minutes holding the GIL mid-suite — the AOT tier is its
        # own opt-in pytest invocation (SMI_TPU_RUN_AOT_TESTS=1), so
        # the emulator tier fails the lookup fast and skips instead
        raise RuntimeError(
            "AOT topology lookup disabled on this test tier "
            "(SMI_TPU_DISABLE_AOT_TOPOLOGY is set); run the AOT tier "
            "with SMI_TPU_RUN_AOT_TESTS=1 to enable it"
        )

    from jax.experimental import topologies

    from smi_tpu.utils.watchdog import run_with_deadline

    name, kwargs = parse_topology(topology)
    budget = float(
        os.environ.get(
            "SMI_AOT_TOPOLOGY_TIMEOUT_S", TOPOLOGY_LOOKUP_TIMEOUT_S
        )
    )
    return run_with_deadline(
        lambda: topologies.get_topology_desc(
            name, platform="tpu", **kwargs
        ).devices,
        budget if budget > 0 else None,
        context=f"abstract topology lookup for {topology}",
    )


def slice_partition(topology: str):
    """``{logical_device_index: slice_index}`` of a (possibly
    multi-slice) topology — the partition
    :func:`traffic.tier_crossing_bytes` folds crossing bytes over.

    Keys are positions in the hybrid mesh's device-assignment order
    (slice-major, the :func:`hybrid_topology_communicator` layout),
    NOT PJRT device ids: HLO replica groups with
    ``use_global_device_ids`` number devices by their flattened
    assignment index (multi-slice abstract devices carry ids like
    100000 that never appear in the HLO).

    Derived from the SAME ``_slice_groups`` flattening the hybrid
    communicator builds its mesh from, so the partition can never
    drift from the actual device assignment."""
    from smi_tpu.parallel.mesh import _slice_groups

    devices = list(topology_devices(topology))
    n_slices = len({getattr(d, "slice_index", 0) or 0 for d in devices})
    if n_slices == 1:
        return {i: 0 for i in range(len(devices))}
    groups = _slice_groups(devices, n_slices, None)
    return dict(enumerate(
        idx for idx, group in enumerate(groups) for _ in group
    ))


def grid2d(n: int):
    """Near-square 2-D factorization of a power-of-two extent:
    8 -> (2, 4), 16 -> (4, 4), 32 -> (4, 8)."""
    px = 1
    while px * px * 4 <= n:
        px *= 2
    if n % px:
        raise ValueError(f"cannot factor {n} devices into a 2-D grid")
    return px, n // px


def topology_communicator(
    topology: str = DEFAULT_TOPOLOGY,
    shape: Optional[Sequence[int]] = None,
    axis_names: Optional[Sequence[str]] = None,
) -> Communicator:
    """Communicator over a topology's abstract devices.

    Mirrors :func:`smi_tpu.parallel.mesh.make_communicator`, but the
    mesh can only be compiled against, not executed on.
    """
    devices = topology_devices(topology)
    if shape is None:
        shape = (len(devices),)
    if axis_names is None:
        axis_names = (
            (DEFAULT_AXIS,) if len(shape) == 1
            else tuple(f"smi{i}" for i in range(len(shape)))
        )
    n = math.prod(shape)
    if n > len(devices):
        raise ValueError(
            f"mesh shape {tuple(shape)} needs {n} devices, topology "
            f"{topology!r} has {len(devices)}"
        )
    dev_array = np.array(devices[:n]).reshape(tuple(shape))
    return Communicator(
        mesh=Mesh(dev_array, tuple(axis_names)),
        axis_names=tuple(axis_names),
    )


def hybrid_topology_communicator(
    topology: str = DEFAULT_TOPOLOGY,
    n_slices: int = 2,
    axis_names: Sequence[str] = ("dcn", "ici"),
) -> Communicator:
    """Two-tier (slice x in-slice) communicator over abstract devices.

    A GENUINE multi-slice topology (``"v5e:2x4*2"``) groups devices by
    their real ``slice_index`` — the mesh's outer axis is the actual
    DCN boundary the partitioner lowers against. A single-slice
    topology falls back to the CPU emulator tier's convention: the
    flat device list splits evenly into ``n_slices`` virtual slices
    (``mesh._slice_groups`` semantics).
    """
    from smi_tpu.parallel.mesh import _slice_groups

    devices = list(topology_devices(topology))
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} slices"
        )
    dev_array = np.array(_slice_groups(devices, n_slices, None))
    return Communicator(
        mesh=Mesh(dev_array, tuple(axis_names)),
        axis_names=tuple(axis_names),
    )


def shaped(comm: Communicator, shape, dtype, spec: P):
    """ShapeDtypeStruct carrying the mesh sharding for AOT lowering."""
    return jax.ShapeDtypeStruct(
        shape, dtype, sharding=NamedSharding(comm.mesh, spec)
    )


def compile_sharded(jitted, *arg_shapes, options=None):
    """Lower + compile a jitted program against abstract-device shardings.

    Returns the :class:`jax.stages.Compiled` executable. Compilation is
    the whole point — a Mosaic or partitioner rejection raises here.
    ``options`` defaults to the framework's canonical TPU compile
    options (``utils/compile.py``) so the tier compiles what production
    runs; pass a program's own options explicitly if they differ.
    """
    from smi_tpu.utils.compile import TPU_COMPILER_OPTIONS

    if options is None:
        options = dict(TPU_COMPILER_OPTIONS)
    return jitted.lower(*arg_shapes).compile(options)


def executable_report(compiled) -> dict:
    """Cost/memory facts of a compiled executable, JSON-ready.

    The ``aoc -report`` analog's per-program payload
    (``/root/reference/CMakeLists.txt:113-118``): where the FPGA flow
    reports area and Fmax before a full build, the TPU flow reports the
    compiled code size, argument/output/temp HBM footprint, and XLA's
    flop/byte cost model — enough to sanity-check a program's resource
    story before committing pod time.
    """
    report: dict = {}
    try:
        mem = compiled.memory_analysis()
        report["memory"] = {
            "generated_code_bytes": int(mem.generated_code_size_in_bytes),
            "argument_bytes": int(mem.argument_size_in_bytes),
            "output_bytes": int(mem.output_size_in_bytes),
            "alias_bytes": int(mem.alias_size_in_bytes),
            "temp_bytes": int(mem.temp_size_in_bytes),
            # live per-chip HBM at peak: arguments + outputs + XLA
            # temporaries, minus donated/aliased buffers counted twice
            # — the number the fits-in-HBM claims are judged against
            "per_chip_hbm_bytes": int(
                mem.argument_size_in_bytes + mem.output_size_in_bytes
                + mem.temp_size_in_bytes - mem.alias_size_in_bytes
            ),
        }
    except Exception as e:  # pragma: no cover - backend-dependent
        report["memory"] = {"unavailable": str(e)}
    try:
        costs = compiled.cost_analysis()
        if isinstance(costs, (list, tuple)):
            costs = costs[0] if costs else {}
        report["cost"] = {
            k: float(v)
            for k, v in sorted(costs.items())
            if isinstance(v, (int, float)) and (
                k in ("flops", "transcendentals")
                or k.startswith("bytes accessed")
            )
        }
    except Exception as e:  # pragma: no cover - backend-dependent
        report["cost"] = {"unavailable": str(e)}
    try:
        from smi_tpu.parallel.traffic import (
            collective_traffic,
            has_collectives,
        )

        text = compiled.as_text()
        records = collective_traffic(compiled, text)
        report["collectives"] = records
        in_loop = any(r.get("in_loop") for r in records)
        megascale = any(r.get("megascale") for r in records)
        if records and not in_loop and not megascale:
            # bandwidth-only v5e wall-clock bound of the program's
            # collectives — the compiled-evidence column the ring
            # tier's schedule predictions are compared against
            from smi_tpu.parallel.traffic import predicted_program_us

            report["ici_predicted_us"] = round(
                predicted_program_us(records), 4
            )
        elif in_loop:
            # a while-loop collective's record is per HLO occurrence —
            # a prediction would be low by the trip count, so the
            # column is withheld rather than shipped wrong
            report["ici_predicted_error"] = (
                "collectives inside a while loop: per-occurrence "
                "bytes under-count by the trip count"
            )
        elif megascale:
            # megascale sends cross the DCN boundary — pricing them at
            # the ICI link rate would misrank flat vs hierarchical
            report["ici_predicted_error"] = (
                "program crosses a slice boundary: megascale DCN "
                "sends cannot be priced at the ICI link rate"
            )
        if not records and has_collectives(text):
            # mark a parser miss so the empty list never ships as data
            report["collectives_error"] = (
                "HLO contains collective instructions but none "
                "parsed — traffic parser miss"
            )
    except Exception as e:  # pragma: no cover - backend-dependent
        # an empty (falsy) list + explicit error key: downstream guards
        # (tests/test_traffic.py) fail loudly instead of reading a
        # truthy sentinel as data
        report["collectives"] = []
        report["collectives_error"] = str(e)
    return report


def cost_facts(compiled) -> dict:
    """Kernel-side inputs of the tuning cost model, from one compiled
    executable: flops, HBM bytes-accessed, and the per-chip HBM peak.

    The bridge between this tier and :mod:`smi_tpu.tuning` — the plan
    engine's roofline ranking
    (``tuning.cost_model.kernel_roofline_us``) consumes exactly these
    facts, so a knob candidate can be priced from an AOT compile alone,
    on a host that owns no TPU. Missing facts are ``None`` (backend-
    dependent availability, same caveat as :func:`executable_report`).
    """
    rep = executable_report(compiled)
    cost = rep.get("cost", {})
    bytes_accessed = None
    for k, v in cost.items():
        # the aggregate "bytes accessed" entry, not the per-operand
        # "bytes accessed N{...}" breakdowns
        if k == "bytes accessed" or (
            k.startswith("bytes accessed") and bytes_accessed is None
        ):
            bytes_accessed = v
            if k == "bytes accessed":
                break
    return {
        "flops": cost.get("flops"),
        "bytes_accessed": bytes_accessed,
        "per_chip_hbm_bytes": rep.get("memory", {}).get(
            "per_chip_hbm_bytes"
        ),
    }


# ---------------------------------------------------------------------------
# The multi-chip surface
# ---------------------------------------------------------------------------


def _ring_cases(topology: str):
    """(name, build) pairs for the four ring kernels x flow-control."""
    from smi_tpu.kernels import ring

    comm = topology_communicator(topology)
    axis, n = comm.axis_names[0], comm.size
    chunk, width = 16, 256

    def case(name, shard, in_spec, out_spec, shape, dtype=jnp.float32):
        def build():
            f = jax.jit(
                jax.shard_map(
                    shard, mesh=comm.mesh, in_specs=in_spec,
                    out_specs=out_spec, check_vma=False,
                )
            )
            return compile_sharded(f, shaped(comm, shape, dtype, in_spec))
        return name, build

    for fc in (True, False):
        tag = "fc" if fc else "nofc"
        yield case(
            f"ring_all_gather_{tag}",
            lambda x, fc=fc: ring.ring_all_gather(x, axis, n, flow_control=fc),
            P(axis, None), P(None, None), (n * chunk, width),
        )
        yield case(
            f"ring_all_reduce_{tag}",
            lambda x, fc=fc: ring.ring_all_reduce(
                x[0], axis, n, flow_control=fc
            )[None],
            P(axis, None), P(axis, None), (n, width),
        )
        yield case(
            f"ring_reduce_scatter_{tag}",
            lambda x, fc=fc: ring.ring_reduce_scatter(
                x, axis, n, flow_control=fc
            ),
            P(None, None), P(axis, None), (n * chunk, width),
        )
        yield case(
            f"neighbour_stream_{tag}",
            lambda x, fc=fc: ring.neighbour_stream(
                x, axis, n, flow_control=fc
            ),
            P(axis, None, None), P(axis, None, None),
            (n * 4, 8, width),
        )


def _ring_dtype_cases(topology: str):
    """Ring kernels at the non-f32 payload dtypes of the header
    library's surface (``ops/types.py``: int/float/double/char/short —
    TPU-native analogs int32/float32/bf16/int8/int16). Mosaic's
    dtype-specific tiling and DMA paths are exactly what interpret mode
    cannot check (it accepted bf16 ``pltpu.roll``, which Mosaic rejects
    — ``docs/perf_notes.md`` r4); the ring kernels use no rolls, and
    this pins that their slot slices and RDMA stay legal per dtype."""
    from smi_tpu.kernels import ring

    comm = topology_communicator(topology)
    axis, n = comm.axis_names[0], comm.size

    def case(name, shard, in_spec, out_spec, shape, dtype):
        def build():
            f = jax.jit(
                jax.shard_map(
                    shard, mesh=comm.mesh, in_specs=in_spec,
                    out_specs=out_spec, check_vma=False,
                )
            )
            return compile_sharded(f, shaped(comm, shape, dtype, in_spec))
        return name, build

    yield case(
        "ring_all_reduce_bf16",
        lambda x: ring.ring_all_reduce(x[0], axis, n)[None],
        P(axis, None), P(axis, None), (n, 256), jnp.bfloat16,
    )
    yield case(
        "ring_all_gather_int32",
        lambda x: ring.ring_all_gather(x, axis, n),
        P(axis, None), P(None, None), (n * 16, 256), jnp.int32,
    )
    yield case(
        "neighbour_stream_bf16",
        lambda x: ring.neighbour_stream(x, axis, n),
        P(axis, None, None), P(axis, None, None), (n * 4, 8, 256),
        jnp.bfloat16,
    )
    # 8/16-bit integer payloads (char/short): packing factors 4 and 2.
    # int8 covers MOVEMENT kernels only — Mosaic has no 8-bit vector
    # arithmetic ("Only vector<i16> and vector<i32> are supported"),
    # so the REDUCING ring kernels reject int8 with a clear error and
    # point at the XLA tier (caught by this tier as bug #7; interpret
    # mode happily adds i8)
    yield case(
        "neighbour_stream_int8",
        lambda x: ring.neighbour_stream(x, axis, n),
        P(axis, None, None), P(axis, None, None), (n * 4, 8, 256),
        jnp.int8,
    )
    yield case(
        "ring_all_reduce_int16",
        lambda x: ring.ring_all_reduce(x[0], axis, n)[None],
        P(axis, None), P(axis, None), (n, 256), jnp.int16,
    )


def _subset_ring_cases(topology: str):
    """Rings over a subset / a pair of axes of a 2-D mesh: the logical
    device-id reconstruction (``ring._logical_id_fn``) must survive
    Mosaic lowering, not just interpret mode."""
    from smi_tpu.kernels import ring

    px, py = grid2d(len(topology_devices(topology)))
    comm = topology_communicator(
        topology, shape=(px, py), axis_names=("mx", "my")
    )
    n = px * py
    mesh_axes = ring.mesh_axes_of(comm)

    def build_subset():
        def shard(x):
            return ring.ring_all_reduce(
                x[0], "my", py, mesh_axes=mesh_axes
            )[None]

        f = jax.jit(
            jax.shard_map(
                shard, mesh=comm.mesh,
                in_specs=P(("mx", "my"), None),
                out_specs=P(("mx", "my"), None), check_vma=False,
            )
        )
        return compile_sharded(
            f, shaped(comm, (n, 256), jnp.float32, P(("mx", "my"), None))
        )

    yield "ring_all_reduce_subset_axis", build_subset

    def build_two_axis():
        def shard(x):
            return ring.ring_all_gather(
                x, ("mx", "my"), n, mesh_axes=mesh_axes
            )

        f = jax.jit(
            jax.shard_map(
                shard, mesh=comm.mesh,
                in_specs=P(("mx", "my"), None),
                out_specs=P(None, None), check_vma=False,
            )
        )
        return compile_sharded(
            f, shaped(comm, (n * 16, 256), jnp.float32,
                      P(("mx", "my"), None))
        )

    yield "ring_all_gather_two_axis", build_two_axis


def _transformer_cases(topology: str):
    """Flash (dp, sp) train step at pod-real shapes, compile-only.

    Two configs: causal MHA bf16 (the headline S=8k-per-chip shape) and
    the windowed GQA long-context config — both through the compiled
    flash tier (``use_flash=True``, no interpret), which is exactly the
    path the CPU suite can only run interpreted.
    """
    from smi_tpu.models import transformer as tf

    comm = topology_communicator(
        topology, shape=grid2d(len(topology_devices(topology))),
        axis_names=("dp", "sp"),
    )
    dp, sp = comm.axis_sizes

    def case(name, cfg, s_global, batch):
        def build():
            params = jax.tree_util.tree_map(
                lambda a: shaped(comm, a.shape, a.dtype, P()),
                tf.init_params(cfg),
            )
            x = shaped(
                comm, (batch, s_global, cfg.embed), jnp.float32,
                P("dp", "sp"),
            )
            step = tf.make_train_step(comm, cfg, use_flash=True)
            return compile_sharded(step, params, x, x)
        return name, build

    yield case(
        "train_step_mha_bf16",
        tf.BlockConfig(embed=256, heads=4, head_dim=128,
                       compute_dtype="bfloat16"),
        s_global=4096 * sp, batch=dp,
    )
    yield case(
        "train_step_gqa_window_bf16",
        tf.BlockConfig(embed=256, heads=8, head_dim=128, kv_heads=1,
                       window=4096, compute_dtype="bfloat16"),
        s_global=8192 * sp, batch=dp,
    )


def _longcontext_sp_case(topology: str):
    """The 1M-token rung: the (dp, sp) sequence-parallel train step.

    One chip tops out at 512k-token training (f32 dq alone is 4 GiB at
    1M — ``docs/perf_notes.md``); the framework's answer, like the
    reference's decomposition-with-halo-exchange answer to a grid that
    outgrows one FPGA (``/root/reference/examples/include/stencil.h.in:32-38``),
    is sequence parallelism: shard S over the sp axis so every per-chip
    tensor (q/k/v shards, flash residuals, the f32 dq shard) divides by
    sp. This case compiles the TRUE 1M-token config — window 4096,
    GQA 8:1, bf16 compute, embed 1024 — against the topology;
    ``executable_report``'s per-chip memory analysis proves the
    footprint fits HBM and its collectives table records the ring K/V
    exchange (collective-permutes over sp) plus the gradient psums.
    The scaled-shape correctness run lives in
    ``__graft_entry__.dryrun_multichip``.
    """
    from smi_tpu.models import transformer as tf

    comm = topology_communicator(
        topology, shape=grid2d(len(topology_devices(topology))),
        axis_names=("dp", "sp"),
    )
    dp, sp = comm.axis_sizes

    def build():
        cfg = tf.BlockConfig(
            embed=1024, heads=8, head_dim=128, kv_heads=1,
            window=4096, compute_dtype="bfloat16",
        )
        params = jax.tree_util.tree_map(
            lambda a: shaped(comm, a.shape, a.dtype, P()),
            tf.init_params(cfg),
        )
        x = shaped(
            comm, (dp, 1048576, cfg.embed), jnp.float32, P("dp", "sp")
        )
        step = tf.make_train_step(comm, cfg, use_flash=True)
        return compile_sharded(step, params, x, x)

    yield "train_step_1m_sp", build


def _hierarchical_case(topology: str):
    from smi_tpu.parallel import collectives

    comm = hybrid_topology_communicator(topology, n_slices=2)
    inner = comm.mesh.shape["ici"]
    n = comm.size

    def build():
        f = jax.jit(
            jax.shard_map(
                lambda x: collectives.allreduce_hierarchical(
                    x[0], comm
                )[None],
                mesh=comm.mesh,
                in_specs=P(("dcn", "ici")),
                out_specs=P(("dcn", "ici")),
                check_vma=False,
            )
        )
        return compile_sharded(
            f, shaped(comm, (n, inner * 32), jnp.float32, P(("dcn", "ici")))
        )

    yield "allreduce_hierarchical", build

    def build_flat():
        # the comparison program for the crossing-bytes analysis
        # (docs/perf_notes.md): one flat psum over both tiers, same
        # shape — its slice-spanning replica group moves the FULL
        # payload across the slow tier, where the hierarchical form
        # crosses with 1/inner of it
        f = jax.jit(
            jax.shard_map(
                lambda x: lax.psum(x[0], ("dcn", "ici"))[None],
                mesh=comm.mesh,
                in_specs=P(("dcn", "ici")),
                out_specs=P(("dcn", "ici")),
                check_vma=False,
            )
        )
        return compile_sharded(
            f, shaped(comm, (n, inner * 32), jnp.float32, P(("dcn", "ici")))
        )

    yield "allreduce_flat", build_flat


def _xla_tier_cases(topology: str):
    """XLA-tier collectives at the ring cases' exact shapes.

    The comparison column of the ring-vs-XLA artifact table
    (``docs/perf_notes.md``): same payloads, same mesh, the default
    tier's ``lax`` collectives instead of the explicit RDMA kernels —
    code size from ``memory_analysis``, ICI traffic from the compiled
    HLO (``parallel/traffic.py``).
    """
    comm = topology_communicator(topology)
    axis, n = comm.axis_names[0], comm.size
    chunk, width = 16, 256

    def case(name, shard, in_spec, out_spec, shape):
        def build():
            f = jax.jit(
                jax.shard_map(
                    shard, mesh=comm.mesh, in_specs=in_spec,
                    out_specs=out_spec, check_vma=False,
                )
            )
            return compile_sharded(
                f, shaped(comm, shape, jnp.float32, in_spec)
            )
        return name, build

    yield case(
        "xla_all_gather",
        lambda x: lax.all_gather(x, axis, axis=0, tiled=True),
        P(axis, None), P(None, None), (n * chunk, width),
    )
    yield case(
        "xla_all_reduce",
        lambda x: lax.psum(x[0], axis)[None],
        P(axis, None), P(axis, None), (n, width),
    )
    yield case(
        "xla_reduce_scatter",
        lambda x: lax.psum_scatter(x, axis, scatter_dimension=0,
                                   tiled=True),
        P(None, None), P(axis, None), (n * chunk, width),
    )
    yield case(
        "xla_neighbour_shift",
        lambda x: lax.ppermute(
            x, axis, [(i, (i + 1) % n) for i in range(n)]
        ),
        P(axis, None, None), P(axis, None, None), (n * 4, 8, width),
    )


def _composite_ring_cases(topology: str):
    """Multi-kernel-instance ring compositions.

    The primitive ring kernels compile one Pallas instance each; these
    programs instantiate SEVERAL ring kernels in one XLA program —
    distinct ``collective_id`` domains, interleaved or dependent
    schedules — which is where Mosaic semaphore/collective-id
    allocation can reject what interpret mode accepts. Reference
    analog: every composed app/test target goes through the hardware
    toolchain, not just the communication library
    (``/root/reference/CMakeLists.txt:38-196``).
    """
    from smi_tpu.parallel import collectives
    from smi_tpu.parallel.channels import P2PChannel, stream_concurrent
    from smi_tpu.parallel.halo import (
        halo_exchange_2d,
        halo_exchange_2d_corners,
    )

    comm2d = topology_communicator(
        topology, shape=grid2d(len(topology_devices(topology))),
        axis_names=("sx", "sy"),
    )
    comm1d = topology_communicator(topology)
    axis = comm1d.axis_names[0]
    n = comm1d.size

    def build_halo(corners: bool):
        # all four ring-tier shift directions (4 neighbour-stream kernel
        # instances on streams 0-3) in ONE program; the corners variant
        # additionally makes the vertical shifts depend on the
        # horizontal ones (two dependent RDMA rounds)
        exchange = halo_exchange_2d_corners if corners else halo_exchange_2d

        def shard(block):
            h = exchange(block, comm2d, depth=1, backend="ring")
            # return every slab so no direction is dead-code-eliminated
            return h.top, h.bottom, h.left, h.right

        f = jax.jit(
            jax.shard_map(
                shard, mesh=comm2d.mesh, in_specs=P("sx", "sy"),
                out_specs=(P("sx", "sy"),) * 4, check_vma=False,
            )
        )
        return compile_sharded(
            f, shaped(comm2d, (512, 1024), jnp.float32, P("sx", "sy"))
        )

    yield "halo_ring_4dir", lambda: build_halo(corners=False)
    yield "halo_ring_corners", lambda: build_halo(corners=True)

    def build_concurrent():
        # two concurrent multi-hop neighbour streams, distinct port ->
        # stream slots -> barrier-semaphore domains, burst-interleaved
        # in one program (the multi_collectives.cl overlap shape)
        chans = [
            P2PChannel(comm=comm1d, port=0, src=0, dst=2, count=1024,
                       buffer_size=256, consecutive_reads=2),
            P2PChannel(comm=comm1d, port=1, src=1, dst=3, count=1024,
                       buffer_size=256, consecutive_reads=2),
        ]

        def shard(a, b):
            return tuple(
                o[None]
                for o in stream_concurrent(chans, (a, b), backend="ring")
            )

        f = jax.jit(
            jax.shard_map(
                shard, mesh=comm1d.mesh, in_specs=(P(), P()),
                out_specs=(P(axis), P(axis)), check_vma=False,
            )
        )
        x = shaped(comm1d, (1024,), jnp.float32, P())
        return compile_sharded(f, x, x)

    yield "stream_concurrent_ring", build_concurrent

    def build_p2p_transfer():
        # hop-by-hop P2P between NON-adjacent ranks: three dependent
        # neighbour-stream kernel instances sharing one stream slot
        ch = P2PChannel(comm=comm1d, port=2, src=0, dst=3, count=2048,
                        buffer_size=512)

        def shard(x):
            return ch.transfer(x, backend="ring")[None]

        f = jax.jit(
            jax.shard_map(
                shard, mesh=comm1d.mesh, in_specs=P(),
                out_specs=P(axis), check_vma=False,
            )
        )
        return compile_sharded(f, shaped(comm1d, (2048,), jnp.float32, P()))

    yield "p2p_transfer_ring_multihop", build_p2p_transfer

    def build_rooted_reduce():
        def shard(x):
            return collectives.reduce(
                x[0], comm1d, op="max", root=3, port=0, backend="ring"
            )[None]

        f = jax.jit(
            jax.shard_map(
                shard, mesh=comm1d.mesh, in_specs=P(axis, None),
                out_specs=P(axis, None), check_vma=False,
            )
        )
        return compile_sharded(
            f, shaped(comm1d, (n, 256), jnp.float32, P(axis, None))
        )

    yield "reduce_ring_rooted", build_rooted_reduce

    def build_rooted_gather():
        def shard(x):
            return collectives.gather(
                x, comm1d, root=5, port=1, backend="ring"
            )[None]

        f = jax.jit(
            jax.shard_map(
                shard, mesh=comm1d.mesh, in_specs=P(axis, None),
                out_specs=P(axis, None, None), check_vma=False,
            )
        )
        return compile_sharded(
            f, shaped(comm1d, (n * 16, 256), jnp.float32, P(axis, None))
        )

    yield "gather_ring_rooted", build_rooted_gather


def _app_cases(topology: str):
    """The three reference applications at pod-real shapes, compile-only.

    Reference analog: ``smi_target()`` wires every example through the
    aoc hardware toolchain at its hardware config
    (``/root/reference/CMakeLists.txt:38-196``,
    ``examples/CMakeLists.txt:2-7`` — stencil 8192x8192 on 2x4 ranks).
    """
    from smi_tpu.models import gesummv, kmeans, stencil

    px, py = grid2d(len(topology_devices(topology)))
    comm2d = topology_communicator(
        topology, shape=(px, py), axis_names=("sx", "sy")
    )

    def build_stencil():
        # the reference's hardware config: 8192^2 on its process grid
        # (2x4 at the reference's shape; scales with the topology)
        fn = stencil.make_stencil_fn(comm2d, iterations=4)
        return compile_sharded(
            fn, shaped(comm2d, (8192, 8192), jnp.float32, P("sx", "sy"))
        )

    yield f"app_stencil_8192_{px}x{py}", build_stencil

    def build_stencil_temporal():
        # the flagship temporal-blocked Pallas tier at the same shape
        from smi_tpu.kernels import stencil_temporal as kt

        depth = kt.pick_temporal_depth(
            8192 // px, 8192 // py, jnp.float32, 16
        ) or 8
        fn = kt.make_temporal_stencil_fn(
            comm2d, 16, 8192, 8192, depth=depth
        )
        return compile_sharded(
            fn, shaped(comm2d, (8192, 8192), jnp.float32, P("sx", "sy"))
        )

    yield f"app_stencil_temporal_8192_{px}x{py}", build_stencil_temporal

    def build_stencil_ring():
        # halos over the RDMA tier inside the sweep loop: 4 ring kernel
        # instances per sweep x 2 sweeps under fori_loop
        fn = stencil.make_stencil_fn(comm2d, iterations=2, backend="ring")
        return compile_sharded(
            fn, shaped(comm2d, (1024, 2048), jnp.float32, P("sx", "sy"))
        )

    yield f"app_stencil_ring_{px}x{py}", build_stencil_ring

    def build_gesummv():
        # 2-rank operator split + streamed axpy combine, n=4096
        comm2 = topology_communicator(topology, shape=(2,))
        fn = gesummv.make_gesummv_fn(comm2, n=4096, alpha=1.5, beta=2.5)
        return compile_sharded(
            jax.jit(fn),
            shaped(comm2, (2, 4096, 4096), jnp.float32,
                   P(comm2.axis_names[0])),
            shaped(comm2, (4096,), jnp.float32, P()),
        )

    yield "app_gesummv_4096", build_gesummv

    def build_kmeans():
        # rooted Reduce+Bcast inside the fori_loop, 512k points x 10 iters
        comm1 = topology_communicator(topology)
        fn = kmeans.make_kmeans_fn(comm1, iterations=10)
        return compile_sharded(
            fn,
            shaped(comm1, (comm1.size * 65536, 2), jnp.float32,
                   P(comm1.axis_names[0])),
            shaped(comm1, (8, 2), jnp.float32, P()),
        )

    yield "app_kmeans_512k", build_kmeans


def ring_case_predictions(topology: str = DEFAULT_TOPOLOGY) -> dict:
    """Schedule-predicted ICI traffic for the ring-tier programs.

    The ring kernels' remote DMAs live inside Mosaic, invisible to HLO
    — but their schedules are static (``kernels/ring.py``), so per-
    device send bytes follow from the very case parameters the surface
    compiles (``_ring_cases``/``_ring_dtype_cases``/
    ``_composite_ring_cases``: chunk=16, width=256, and the composite
    channel configs). Each entry carries the ICI bytes and the
    bandwidth-only time bound at the v5e link rate
    (``traffic.V5E_ICI_LINK_BYTES_PER_S``) — the column that lets the
    ring tier and the XLA tier (whose ``ici_predicted_us`` comes from
    parsed HLO) be compared on compiled evidence alone.
    """
    from smi_tpu.parallel.traffic import predicted_us, ring_traffic

    n = len(topology_devices(topology))
    chunk, width = 16, 256  # _ring_cases' shapes

    preds = {}

    def put(name, kind, payload_bytes, chunks=1, hops=1):
        b = ring_traffic(
            kind, n, payload_bytes, chunks=chunks, hops=hops
        )["ici_send_bytes"]
        preds[name] = {
            "ici_send_bytes": int(b),
            "predicted_us": round(predicted_us(b), 4),
        }

    for tag in ("fc", "nofc"):
        put(f"ring_all_gather_{tag}", "all_gather", chunk * width * 4)
        put(f"ring_all_reduce_{tag}", "all_reduce", width * 4)
        put(f"ring_reduce_scatter_{tag}", "reduce_scatter",
            chunk * width * 4)
        # per-shard (4, 8, width) f32: 4 chunks of one 8-row slab
        put(f"neighbour_stream_{tag}", "neighbour_stream",
            8 * width * 4, chunks=4)
    put("ring_all_reduce_bf16", "all_reduce", width * 2)
    put("ring_all_gather_int32", "all_gather", chunk * width * 4)
    put("neighbour_stream_bf16", "neighbour_stream", 8 * width * 2,
        chunks=4)
    put("neighbour_stream_int8", "neighbour_stream", 8 * width * 1,
        chunks=4)
    put("ring_all_reduce_int16", "all_reduce", width * 2)
    # hop-by-hop P2P 0 -> 3: 2048 f32 in 512-element chunks, 3 hops
    # (aggregate forwarded bytes at one link's rate)
    put("p2p_transfer_ring_multihop", "neighbour_stream", 512 * 4,
        chunks=4, hops=3)
    # two concurrent streams 0->2 / 1->3: 1024 f32 in 256-element
    # chunks, 2 hops each; distinct ports ride distinct slots, so the
    # bound is ONE stream's bytes (they overlap), not the sum
    put("stream_concurrent_ring", "neighbour_stream", 256 * 4,
        chunks=4, hops=2)
    # rooted ring reduce: dispatches to ring_all_reduce on the (width,)
    # per-rank shard — the running partial makes n-1 hops
    put("reduce_ring_rooted", "all_reduce", width * 4)
    # rooted ring gather: rank r's (16, width) block travels its ring
    # distance to the root; the root's inbound link carries all n-1
    # blocks — that link is the bound
    put("gather_ring_rooted", "neighbour_stream", chunk * width * 4,
        chunks=1, hops=n - 1)
    return preds


def surface_cases(topology: str = DEFAULT_TOPOLOGY):
    """All (name, build) pairs of the multi-chip AOT surface."""
    yield from _ring_cases(topology)
    yield from _ring_dtype_cases(topology)
    yield from _subset_ring_cases(topology)
    yield from _transformer_cases(topology)
    yield from _longcontext_sp_case(topology)
    yield from _hierarchical_case(topology)
    yield from _composite_ring_cases(topology)
    yield from _app_cases(topology)
    yield from _xla_tier_cases(topology)


def hybrid_cases(topology: str):
    """The case subset for a genuine multi-slice topology.

    Only XLA collectives are legal across a DCN boundary (the ring
    kernels' remote DMAs are an ICI mechanism), so a ``*s`` topology
    compiles the two-tier programs: the hierarchical allreduce against
    its flat comparison, with the mesh's outer axis on the REAL slice
    boundary.
    """
    yield from _hierarchical_case(topology)


def is_multislice(topology: str) -> bool:
    return parse_topology(topology)[1].get("num_slices", 1) > 1


def check_surface(
    topology: str = DEFAULT_TOPOLOGY,
    verbose: bool = False,
    cases=None,
):
    """Compile the multi-chip surface for a topology; return reports.

    ``cases`` selects the case generator (default: the full surface
    for single-slice topologies, :func:`hybrid_cases` for genuine
    multi-slice ones). Raises on the first lowering failure — the test
    tier wants a loud FAIL, not a summary with holes.
    """
    if cases is None:
        cases = hybrid_cases if is_multislice(topology) else surface_cases
    reports = {}
    for name, build in cases(topology):
        if verbose:
            print(f"  aot-compile {name} ...", flush=True)
        compiled = build()
        reports[name] = executable_report(compiled)
    if is_multislice(topology):
        # the hybrid subset has no ring-tier program to annotate; the
        # single-rate column is already withheld per-program by
        # executable_report (megascale sends cross the REAL DCN
        # boundary — the crossing/local split via tier_crossing_bytes
        # is the meaningful signal here). Belt-and-braces in case a
        # hybrid program's crossing stage lowered without megascale
        # sends (it would price DCN at the ICI rate):
        for rep in reports.values():
            rep.pop("ici_predicted_us", None)
    else:
        for name, pred in ring_case_predictions(topology).items():
            if name in reports:
                reports[name]["ring_predicted"] = pred
    return reports
