"""Communicator = device mesh + named axes.

Reference parity: ``include/smi/communicator.h`` — ``SMI_Comm`` is a
``{rank, size}`` pair produced by the generated ``SmiInit_<program>()``
(``codegen/templates/host_hlslib.cl:87-89``). On TPU the communicator is a
``jax.sharding.Mesh``: *size* is the mesh extent, *rank* is the flattened
``lax.axis_index`` inside ``shard_map``, and "initialising the NoC" is
simply constructing the mesh — XLA owns physical routing over ICI.

Multi-dimensional meshes are first-class (the stencil app uses a 2-D
(PX, PY) mesh, reference ``examples/include/stencil.h.in:32-38``): a
communicator carries an ordered tuple of axis names and exposes a
flattened rank over all of them, row-major, matching the deterministic
rank assignment of ``codegen/routing.py:61-69``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from smi_tpu.ops.serialization import Topology

DEFAULT_AXIS = "smi"


@dataclasses.dataclass(frozen=True)
class Communicator:
    """An SMI communicator over a JAX mesh.

    ``axis_names`` are the mesh axes this communicator spans, in row-major
    significance order (first axis is the slowest-varying in the flattened
    rank). ``SMI_Comm_rank``/``SMI_Comm_size`` analogs are :meth:`rank`
    (traced, shard_map-only) and :attr:`size` (static).

    ``topology``, when built from a topology file, keeps the parsed link
    list and MPMD program map available to the routing layer and to
    program-aware dispatch (``mpmd_dispatch``).
    """

    mesh: Mesh
    axis_names: Tuple[str, ...] = (DEFAULT_AXIS,)
    topology: Optional[Topology] = dataclasses.field(
        default=None, compare=False
    )
    #: Membership epoch (elastic runtime): bumped by every composition
    #: change — :meth:`shrink` and :meth:`regrow` — so traffic tagged
    #: with a superseded epoch is rejectable (:meth:`validate_epoch`).
    #: ``compare=False``: two communicators over the same devices are
    #: interchangeable for dispatch regardless of how many membership
    #: changes produced them.
    epoch: int = dataclasses.field(default=0, compare=False)

    def __post_init__(self):
        for name in self.axis_names:
            if name not in self.mesh.axis_names:
                raise ValueError(
                    f"axis {name!r} not in mesh axes {self.mesh.axis_names}"
                )

    @property
    def size(self) -> int:
        """Total ranks (``SMI_Comm_size``, ``communicator.h:26-31``)."""
        return int(
            math.prod(self.mesh.shape[name] for name in self.axis_names)
        )

    @property
    def axis_sizes(self) -> Tuple[int, ...]:
        return tuple(self.mesh.shape[name] for name in self.axis_names)

    def rank(self) -> jax.Array:
        """Flattened rank of the calling shard (``SMI_Comm_rank``).

        Only valid inside ``shard_map`` over this communicator's axes.
        """
        r = jax.lax.axis_index(self.axis_names[0])
        for name in self.axis_names[1:]:
            r = r * self.mesh.shape[name] + jax.lax.axis_index(name)
        return r

    def coords(self) -> Tuple[jax.Array, ...]:
        """Per-axis coordinates of the calling shard (traced)."""
        return tuple(jax.lax.axis_index(name) for name in self.axis_names)

    @property
    def spec(self) -> P:
        """PartitionSpec sharding the leading dim over all comm axes."""
        return P(self.axis_names)

    @property
    def replicated(self) -> P:
        return P()

    @property
    def is_tpu(self) -> bool:
        """True when every mesh device is a TPU — the gate for compiled
        Pallas fast paths (the CPU fake mesh runs them in interpret
        mode instead)."""
        return all(
            dev.platform == "tpu" for dev in self.mesh.devices.flat
        )

    def subcomm(self, *axis_names: str) -> "Communicator":
        """Communicator over a subset of axes (rows/columns of the mesh)."""
        return Communicator(
            mesh=self.mesh, axis_names=tuple(axis_names), topology=self.topology
        )

    def alltoall_schedule(self):
        """The pairwise all-to-all step schedule over THIS
        communicator's current size
        (:func:`smi_tpu.parallel.routing.alltoall_pairwise_schedule`):
        per step, the (src, dst) logical-rank pairs the exchange
        drives. Because it is derived from ``self.size``, the schedule
        follows every membership change — a shrunk or regrown
        communicator's schedule is exactly the smaller/larger
        rotation over the surviving logical ranks, with every ordered
        pair still covered exactly once (shrink/regrow compatibility,
        property-tested in tests/test_alltoall.py)."""
        from smi_tpu.parallel.routing import alltoall_pairwise_schedule

        return alltoall_pairwise_schedule(self.size)

    def shrink(self, excluded_ranks) -> "Communicator":
        """Rebuild a healthy-subset communicator without the given ranks.

        The ULFM-style degraded-mode primitive (MPI fault-tolerance
        extensions' ``MPI_Comm_shrink``): after a failure is detected —
        a watchdog timeout naming a stalled rank, an unroutable cut from
        the routing layer — the job continues on the survivors.
        Survivors keep their relative rank order (the flattened order of
        this communicator), and the shrunk mesh is 1-D over the default
        axis: axis structure cannot survive arbitrary holes, and a
        recovery phase re-deriving a 2-D layout should build a fresh
        communicator from the surviving devices explicitly.

        The topology (if any) is dropped: its rank numbering no longer
        matches the shrunk mesh; degraded *routing* keeps the full rank
        space instead (:class:`smi_tpu.parallel.routing.FailureSet`).
        """
        excluded, _ = self._validate_membership_args(
            excluded_ranks, None, "shrink")
        size = self.size
        if len(excluded) >= size:
            raise ValueError(
                f"cannot shrink a {size}-rank communicator by "
                f"{len(excluded)} ranks: no survivors"
            )
        if not excluded:
            return self
        survivors = [
            d for r, d in enumerate(self._flat_rank_devices("shrink"))
            if r not in excluded
        ]
        mesh = Mesh(
            np.array(survivors).reshape(len(survivors)), (DEFAULT_AXIS,)
        )
        return Communicator(
            mesh=mesh, axis_names=(DEFAULT_AXIS,), epoch=self.epoch + 1
        )

    def _flat_rank_devices(self, what: str):
        """Devices in this communicator's flattened rank order:
        transpose the mesh array to (comm axes..., other axes...) and
        read the comm-axes block row-major. Requires the communicator
        to span all mesh axes — membership surgery on a sub-axis view
        would silently desynchronize the other axes' rank numbering."""
        mesh_names = list(self.mesh.axis_names)
        order = [mesh_names.index(a) for a in self.axis_names] + [
            i for i, n in enumerate(mesh_names) if n not in self.axis_names
        ]
        flat = np.transpose(self.mesh.devices, order).reshape(self.size, -1)
        if flat.shape[1] != 1:
            raise ValueError(
                f"{what}() needs a communicator spanning all mesh axes "
                f"(mesh axes {tuple(mesh_names)}, comm axes "
                f"{self.axis_names}); {what} the full communicator and "
                "rebuild sub-axes from the survivors"
            )
        return [flat[r, 0] for r in range(self.size)]

    def regrow(self, excluded_ranks, readmit_ranks,
               epoch: Optional[int] = None) -> "Communicator":
        """The inverse of :meth:`shrink`: re-admit recovered ranks.

        Called on the ORIGINAL (pre-shrink) communicator — the only
        holder of the full rank order — with the currently-excluded
        set and the subset of it to re-admit. Returns a fresh 1-D
        communicator over the surviving + re-admitted devices in
        original rank order, under a **new epoch**. Pass ``epoch``
        (``shrunk.epoch + 1`` of the LIVE chain) when more than one
        shrink produced the excluded set; the default assumes the
        natural single-shrink cycle and bumps the original's epoch
        TWICE — once for that shrink, once for this regrow — so the
        shrunk incarnation's epoch can never collide with the regrown
        one's (a collision would let exactly the stale pre-regrow
        traffic the gate exists to reject pass
        :meth:`validate_epoch`). Pair with
        :class:`~smi_tpu.parallel.membership.MembershipView` for the
        full audit trail. When this communicator carries a real
        ``topology``, the still-dead devices are declared as a
        :class:`~smi_tpu.parallel.routing.FailureSet` and every member
        pair must still route around them — a regrow that would strand
        anyone raises
        :class:`~smi_tpu.parallel.routing.RouteCutError` naming the
        cut instead of handing back a broken communicator. Without a
        topology (the common bare-mesh case) no physical check runs:
        XLA owns routing over ICI and a plain JAX mesh has no wire
        list to validate against — mirroring :meth:`shrink`, which has
        never needed one. (A degraded *ring order* around down wires
        is :func:`~smi_tpu.parallel.recovery.plan_ring`'s job at
        resume time; membership here has no down pairs, only dead
        devices, so original rank order is the plan.) Traffic from the
        pre-regrow incarnation is rejected by :meth:`validate_epoch`.
        """
        excluded, readmit = self._validate_membership_args(
            excluded_ranks, readmit_ranks, "regrow"
        )
        size = self.size
        still_dead = excluded - readmit
        self._check_regrow_routes(still_dead)
        alive = [r for r in range(size) if r not in still_dead]
        devices = self._flat_rank_devices("regrow")
        members = [devices[r] for r in alive]
        mesh = Mesh(
            np.array(members).reshape(len(members)), (DEFAULT_AXIS,)
        )
        return Communicator(
            mesh=mesh, axis_names=(DEFAULT_AXIS,),
            epoch=self.epoch + 2 if epoch is None else epoch,
        )

    def _validate_membership_args(self, excluded_ranks, readmit_ranks,
                                  what: str):
        """Shared argument validation for the shrink/regrow pairs
        (flat and pod): range-checks the excluded set and, when
        ``readmit_ranks`` is given (the regrow pair), the
        readmit ⊆ excluded relation and non-emptiness. Returns
        ``(excluded, readmit)`` as sets (``readmit`` is None for the
        shrink pair). One copy, so the flat and pod paths can never
        drift on what counts as a legal membership change."""
        excluded = set(excluded_ranks)
        readmit = None
        if readmit_ranks is not None:
            readmit = set(readmit_ranks)
            stray = sorted(readmit - excluded)
            if stray:
                raise ValueError(
                    f"cannot regrow ranks {stray}: they are not in the "
                    f"excluded set {sorted(excluded)}"
                )
            if not readmit:
                raise ValueError(
                    f"{what}() needs at least one rank to re-admit"
                )
        size = self.size
        bad = sorted(r for r in excluded if not (0 <= r < size))
        if bad:
            raise ValueError(
                f"excluded ranks {bad} out of range for comm size {size}"
            )
        return excluded, readmit

    def _check_regrow_routes(self, still_dead) -> None:
        """Physical leg of the regrow contract: with a real topology
        the still-dead devices become a FailureSet and every surviving
        member pair must route around them, or RouteCutError names the
        cut instead of handing back a broken communicator. Bare JAX
        meshes (no topology) skip — XLA owns ICI routing there."""
        if self.topology is None:
            return
        from smi_tpu.parallel.routing import (
            FailureSet,
            build_routing_context,
            check_all_pairs_routable,
        )

        topo_devices = self.topology.devices
        cut = FailureSet(devices=frozenset(
            topo_devices[r] for r in sorted(still_dead)
        ))
        ctx = build_routing_context(self.topology, excluded=cut)
        alive = [r for r in range(self.size) if r not in still_dead]
        check_all_pairs_routable(
            ctx, [topo_devices[r] for r in alive]
        )

    def _pod_axes(self, what: str) -> Tuple[int, int]:
        """(slices, per_slice) of a two-axis hybrid communicator;
        loud otherwise — pod membership surgery on a flat mesh has no
        slice structure to preserve."""
        if len(self.axis_names) != 2:
            raise ValueError(
                f"{what}() needs a 2-axis (slices, per_slice) hybrid "
                f"communicator; got axes {self.axis_names} — use "
                f"{what.replace('_pod', '')}() on flat meshes"
            )
        outer, inner = self.axis_names
        return self.mesh.shape[outer], self.mesh.shape[inner]

    def _pod_mesh_without(self, dead_slices, what: str,
                          epoch: int) -> "Communicator":
        """Rebuild the hybrid mesh with whole dead slices dropped from
        the outer axis — the one copy of the row layout shared by
        :meth:`shrink_pod` and :meth:`regrow_pod`, so the two can
        never diverge on slice-row ordering or device flattening."""
        slices, per_slice = self._pod_axes(what)
        devices = self._flat_rank_devices(what)
        rows = [
            [devices[s * per_slice + i] for i in range(per_slice)]
            for s in range(slices) if s not in dead_slices
        ]
        mesh = Mesh(np.array(rows), self.axis_names)
        return Communicator(
            mesh=mesh, axis_names=self.axis_names, epoch=epoch
        )

    def shrink_pod(self, excluded_ranks) -> "Communicator":
        """Pod-aware :meth:`shrink` for a hybrid (slices, per_slice)
        communicator.

        Whole dead slices drop out of the OUTER axis with the hybrid
        shape preserved — the survivors keep their two-tier mesh, so
        hierarchical collectives continue over the remaining slices.
        A partial slice cannot keep the shape (JAX meshes are
        rectangular; unequal slices do not tile), so dead *ranks*
        fall back to the flat 1-D ring over all survivors — exactly
        the ``plan_pod_rings`` flat-fallback rule, at mesh level.
        Epoch bumps once either way (no-op exclusion returns ``self``
        unbumped, mirroring :meth:`shrink`).
        """
        slices, per_slice = self._pod_axes("shrink_pod")
        excluded, _ = self._validate_membership_args(
            excluded_ranks, None, "shrink_pod"
        )
        size = self.size
        if not excluded:
            return self
        if len(excluded) >= size:
            raise ValueError(
                f"cannot shrink a {size}-rank pod by {len(excluded)} "
                f"ranks: no survivors"
            )
        by_slice: dict = {}
        for r in excluded:
            by_slice.setdefault(r // per_slice, set()).add(r)
        if any(len(dead) < per_slice for dead in by_slice.values()):
            return self.shrink(excluded)  # partial slice: flat ring
        return self._pod_mesh_without(by_slice, "shrink_pod",
                                      epoch=self.epoch + 1)

    def regrow_pod(self, excluded_ranks, readmit_ranks,
                   epoch: Optional[int] = None) -> "Communicator":
        """The inverse of :meth:`shrink_pod`, called on the ORIGINAL
        pod communicator (the holder of the full slice structure).
        When the still-dead set after re-admission consists of whole
        slices (usually empty — everyone came back), the result keeps
        the hybrid (slices', per_slice) shape; a still-dead partial
        slice falls back to the flat :meth:`regrow`. Epoch semantics
        mirror :meth:`regrow` (default assumes the single
        shrink→regrow cycle and bumps twice; pass ``epoch`` for
        longer chains)."""
        slices, per_slice = self._pod_axes("regrow_pod")
        excluded, readmit = self._validate_membership_args(
            excluded_ranks, readmit_ranks, "regrow_pod"
        )
        still_dead = excluded - readmit
        by_slice: dict = {}
        for r in still_dead:
            by_slice.setdefault(r // per_slice, set()).add(r)
        new_epoch = self.epoch + 2 if epoch is None else epoch
        if any(len(dead) < per_slice for dead in by_slice.values()):
            return self.regrow(excluded, readmit, epoch=new_epoch)
        self._check_regrow_routes(still_dead)
        return self._pod_mesh_without(by_slice, "regrow_pod",
                                      epoch=new_epoch)

    def validate_epoch(self, rank: int, epoch: int,
                       what: str = "message") -> None:
        """Reject traffic tagged with another epoch — the loud
        stale-epoch gate (:class:`~membership.StaleEpochError`):
        packets from a shrunk-out incarnation can never be folded into
        the regrown job silently. A *newer* epoch than ours is the
        mirror failure — WE missed a membership change (split view) —
        and is named as such so the operator debugs the right side."""
        if epoch != self.epoch:
            from smi_tpu.parallel.membership import StaleEpochError

            raise StaleEpochError(rank, epoch, self.epoch, what=what)

    def heirs(self, excluded_ranks) -> dict:
        """excluded rank -> its surviving heir (nearest successor).

        The recovery layer's inheritance rule: when a rank is shrunk
        away, its duties — serving its progress-logged chunks, folding
        its logged contribution into the restarted reduction — pass to
        the first surviving rank after it on the ring. Delegates to
        :func:`smi_tpu.parallel.recovery.heir_of` (the single
        implementation the simulator's recovery also uses, so the two
        can never drift). Raises ``ValueError`` when nobody survives
        (validated by :meth:`shrink`'s own rules).
        """
        # deferred: recovery is pure Python but imports the fault layer
        from smi_tpu.parallel.recovery import heir_of

        excluded = set(excluded_ranks)
        size = self.size
        bad = sorted(r for r in excluded if not (0 <= r < size))
        if bad:
            raise ValueError(
                f"excluded ranks {bad} out of range for comm size {size}"
            )
        if len(excluded) >= size:
            raise ValueError(
                f"no survivors among {size} ranks to inherit from "
                f"{sorted(excluded)}"
            )
        survivors = [r for r in range(size) if r not in excluded]
        return {r: heir_of(r, survivors, size) for r in excluded}

    def program_of_rank(self, rank: int):
        """The program rank ``rank`` runs under MPMD (None if no topology)."""
        if self.topology is None:
            return None
        device = self.topology.mapping.devices[rank]
        return self.topology.mapping.program_for(device)


def make_communicator(
    n_devices: Optional[int] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Optional[Sequence[str]] = None,
    devices=None,
) -> Communicator:
    """Build a communicator from the available devices.

    ``shape``/``axis_names`` give a multi-dimensional mesh (e.g. ``(2, 4)``
    with ``("x", "y")`` for the stencil's process grid); the default is a
    1-D mesh named ``"smi"`` over ``n_devices`` (all devices if omitted).
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        n = n_devices if n_devices is not None else len(devices)
        shape = (n,)
    if axis_names is None:
        axis_names = (
            (DEFAULT_AXIS,) if len(shape) == 1
            else tuple(f"smi{i}" for i in range(len(shape)))
        )
    n_total = math.prod(shape)
    if n_total > len(devices):
        raise ValueError(
            f"mesh shape {tuple(shape)} needs {n_total} devices, "
            f"have {len(devices)}"
        )
    dev_array = np.array(devices[:n_total]).reshape(shape)
    mesh = Mesh(dev_array, tuple(axis_names))
    return Communicator(mesh=mesh, axis_names=tuple(axis_names))


def _slice_groups(devices, n_slices, per_slice):
    """Group devices into equal slices (pure — unit-testable with stub
    devices). Platform-reported ``slice_index`` wins; otherwise the
    flat list splits evenly into ``n_slices`` virtual slices."""
    by_slice: dict = {}
    for d in devices:
        by_slice.setdefault(getattr(d, "slice_index", None) or 0,
                            []).append(d)
    if len(by_slice) > 1:
        groups = [by_slice[k] for k in sorted(by_slice)]
        sizes = {len(g) for g in groups}
        if len(sizes) != 1:
            raise ValueError(
                f"uneven slices: {sorted(len(g) for g in groups)}"
            )
        if n_slices is not None and n_slices != len(groups):
            raise ValueError(
                f"n_slices={n_slices} but platform reports {len(groups)}"
            )
        if per_slice is not None and per_slice != len(groups[0]):
            raise ValueError(
                f"per_slice={per_slice} but slices have {len(groups[0])}"
            )
        return groups
    if n_slices is None:
        raise ValueError(
            "single-slice platform: pass n_slices to split the "
            "device list into virtual slices"
        )
    flat = list(devices)
    if per_slice is None:
        if len(flat) % n_slices:
            raise ValueError(
                f"{len(flat)} devices do not split into "
                f"{n_slices} slices"
            )
        per_slice = len(flat) // n_slices
    if n_slices * per_slice > len(flat):
        raise ValueError(
            f"need {n_slices * per_slice} devices, have {len(flat)}"
        )
    flat = flat[: n_slices * per_slice]
    return [
        flat[i * per_slice : (i + 1) * per_slice]
        for i in range(n_slices)
    ]


def make_hybrid_communicator(
    n_slices: Optional[int] = None,
    per_slice: Optional[int] = None,
    axis_names: Sequence[str] = ("dcn", "ici"),
    devices=None,
) -> Communicator:
    """Two-tier communicator: outer axis across slices, inner within.

    Reference parity: the SMI network is two-tier — FPGAs grouped per
    node (``SMI_DEVICES_PER_NODE=2``, ``CMakeLists.txt:10``) with
    intra-node links costed 1 and inter-node QSFP routes costed 100
    (``codegen/program.py:7-8``), so the router prefers staying inside
    a node. The TPU analog is a multi-slice system: fast ICI inside a
    slice, DCN across slices. This builds a ``(n_slices, per_slice)``
    mesh whose OUTER axis is the slow tier, so collectives over
    ``axis_names[1]`` ride ICI and only the cross-slice stage touches
    DCN (see ``collectives.allreduce_hierarchical``).

    On a real multi-slice platform the grouping follows each device's
    reported ``slice_index``; on single-slice or CPU (the emulator
    tier) the flat device list is split evenly into ``n_slices``
    groups, which keeps rank order identical across tiers.
    """
    if devices is None:
        devices = jax.devices()
    if len(axis_names) != 2:
        raise ValueError(f"need (outer, inner) axis names, got {axis_names}")
    groups = _slice_groups(devices, n_slices, per_slice)
    dev_array = np.array(groups)
    mesh = Mesh(dev_array, tuple(axis_names))
    return Communicator(mesh=mesh, axis_names=tuple(axis_names))


def mesh_from_topology(topology: Topology, devices=None) -> Communicator:
    """Build a communicator whose rank order follows a topology file.

    Devices in the topology are ranked deterministically by ``(node,
    index)`` (``codegen/routing.py:61-69``) and mapped onto the first N JAX
    devices in that order. The physical link list and MPMD program map are
    kept on the communicator (``.topology``) for the routing layer
    (port→neighbour assignment) and program-aware dispatch.
    """
    base = make_communicator(n_devices=len(topology.devices), devices=devices)
    return dataclasses.replace(base, topology=topology)
