"""Fault injection for the credit protocol: plans, verdicts, mirrors.

Reference gap this fills: the SMI emulator validates the NoC only under
*healthy* schedules — strict channel depths make races reproduce
(``CMakeLists.txt:188-191``) but nothing in the reference ever drops a
credit, stalls a rank, or cuts a link. Production collective stacks
treat those as table stakes (ULFM-style shrinking communicators in MPI,
datacenter fabrics routing around failed links), so the TPU port's
executable protocol spec (:mod:`smi_tpu.parallel.credits`) is extended
here with a deterministic, seedable :class:`FaultPlan` and a verdict
harness over all four ring protocols.

Fault classes (the matrix ``tests/test_faults.py`` sweeps):

- **dropped credit grant** — a slot re-grant is lost; the upstream
  writer waits forever → detected as :class:`~credits.DeadlockError`
  with a per-rank state dump.
- **duplicated credit grant** — a surplus credit lets the writer RDMA
  into a slot the receiver may still be consuming → detected as
  :class:`~credits.ClobberError`, or (when the schedule dodges the
  race) as the surplus count at exit, :class:`~credits.CreditLeakError`.
- **delayed DMA completion** — a copy is slow but not lost →
  **tolerated**: the credit protocol is correct under arbitrary landing
  order, delivery stays intact.
- **stalled rank** — crash-stop after N actions; neighbours block on
  its barrier/credits → detected as a deadlock whose dump names the
  stalled rank.
- **down link** — all traffic between two ranks is lost (signals and
  DMAs, both directions) → detected as a deadlock at the first
  wait that needed the dead wire.
- **bit-flipped payload / truncated DMA** — a chunk is damaged in
  flight with the protocol machinery none the wiser → detected by the
  verified-transport framing (:func:`credits.verified_steps`) as
  :class:`~credits.IntegrityError` (checksum mismatch naming rank,
  chunk, expected vs got); on BARE transport the same injection is
  silent corruption, which is the framing's existence proof.
- **reordered chunks** — two consecutive frames from one source swap
  positions on the wire → detected as
  :class:`~credits.IntegrityError` (sequence mismatch). Reordering is
  a framing-level concept (bare payloads carry no sequence number).

The invariant the harness enforces for every cell: the run either
completes with verified delivery (**tolerated**) or raises a *named*
invariant violation carrying enough state to debug it (**detected**) —
never silent corruption. A wrong-output completion is re-raised as
:class:`SilentCorruption` so no test can accidentally bless it.

:func:`mirror_stall_dump` is the runtime watchdogs' "state-machine
mirror": for a hung device collective it reports where each rank of the
matching protocol stands when no remote traffic completes — the
protocol-level picture a timeout error should carry.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, FrozenSet, Optional, Tuple

from smi_tpu.parallel import credits as C

#: The four ring protocols the plan can execute, keyed as the fault
#: matrix names them. Re-exported from the consolidated registry
#: (:func:`credits.all_protocol_registries` — the ONE source of truth
#: every analysis tier enumerates); this module keeps its historical
#: names so the seed-pinned chaos campaign's draw set stays the same
#: object, digest-tested in tests/test_alltoall.py.
PROTOCOLS = C.PROTOCOLS

#: Pipelined variants runnable through :func:`run_under_faults` but NOT
#: part of the default chaos sweep (the seed-pinned campaign counts the
#: four base protocols): ``all_reduce_chunked`` is the chunked
#: double-buffered schedule of ``kernels/ring.py`` — ``chunks`` pipeline
#: rows interleaving per ring step on their own slot pairs.
CHUNKED_PROTOCOLS = C.CHUNKED_PROTOCOLS

#: Fault classes the matrix is exhaustive over. The last three damage
#: payloads *in flight* — faults the credit protocol cannot see at all;
#: only the verified-transport framing (``credits.verified_steps``)
#: turns them into named IntegrityErrors instead of silent corruption.
FAULT_CLASSES = ("dropped_grant", "duplicated_grant", "delayed_dma",
                 "stalled_rank", "down_link", "bit_flip_payload",
                 "reordered_chunks", "truncated_dma")

#: The wire-integrity subset of :data:`FAULT_CLASSES`.
INTEGRITY_FAULT_CLASSES = ("bit_flip_payload", "reordered_chunks",
                           "truncated_dma")

#: Elastic (job-level) fault classes, deliberately NOT in
#: :data:`FAULT_CLASSES`: the seed-pinned base chaos campaign draws
#: from that tuple, so extending it would silently re-roll every
#: pinned cell (the same discipline that keeps CHUNKED_PROTOCOLS out
#: of the base sweep). These classes drive the membership layer
#: (:mod:`smi_tpu.parallel.membership`) across *iterations of a job*,
#: not actions of one collective — ``smi-tpu chaos --elastic`` sweeps
#: them.
ELASTIC_FAULT_CLASSES = ("flapping_rank", "stalled_heartbeat")

#: The two-tier pod protocol runnable through :func:`run_under_faults`
#: but NOT in the seed-pinned base sweep (same discipline as
#: :data:`CHUNKED_PROTOCOLS`): ``allreduce_pod`` is the hierarchical
#: rs(ICI) -> ring(DCN) -> ag(ICI) composition of
#: :func:`credits.allreduce_pod_rank`.
POD_PROTOCOLS = C.POD_PROTOCOLS

#: The all-to-all family (pairwise exchange / Bruck log-step /
#: two-tier pod), runnable through :func:`run_under_faults` but NOT in
#: the seed-pinned base sweep — same discipline as every
#: post-seed registry. The Bruck variant refuses non-power-of-two
#: rank counts loudly.
ALLTOALL_PROTOCOLS = C.ALLTOALL_PROTOCOLS

#: The compressed-wire allreduce family (r19: quantized pod
#: composition + top-k sparse gather), runnable through
#: :func:`run_under_faults` but NOT in the seed-pinned base sweep —
#: same discipline as every post-seed registry. Quantization changes
#: the VALUES by contract, never the framing: a bit flip on a
#: quantized or sparse frame is still a named IntegrityError, and bare
#: transport is still proven SilentCorruption.
QUANTIZED_PROTOCOLS = C.QUANTIZED_PROTOCOLS

#: Serving-level fault classes, deliberately NOT in
#: :data:`FAULT_CLASSES` (same seed-pinning rule as
#: :data:`ELASTIC_FAULT_CLASSES`). They drive the multi-tenant
#: front-end (:mod:`smi_tpu.serving`) across ticks of a serving loop,
#: not actions of one collective: a ``SlowConsumer`` is the
#: *saturated-not-dead* regime — the destination keeps heartbeating
#: while its consumer stalls, so wire credits exhaust and the stall
#: must surface as named admission-edge shedding, never as a
#: membership transition. ``smi-tpu chaos --load`` sweeps them.
SERVING_FAULT_CLASSES = ("slow_consumer",)

#: DCN-tier fault classes, deliberately NOT in :data:`FAULT_CLASSES`
#: (the seed-pinned base chaos campaign would re-roll; same rule as
#: :data:`ELASTIC_FAULT_CLASSES`). They target the pod's slow wire
#: tier specifically: a down DCN link severs two *slices* (every
#: cross-slice wire between them, both directions), a DCN delay is
#: the slow-but-never-lost hold the inter-slice fabric actually
#: exhibits. ``tests/test_multislice.py`` sweeps them against the pod
#: protocol; verified-transport framing composes unchanged (a
#: payload tampered on a DCN wire is the same named IntegrityError
#: an ICI tamper is).
DCN_FAULT_CLASSES = ("dcn_link_down", "dcn_delay")

#: Partition fault classes, deliberately NOT in :data:`FAULT_CLASSES`
#: (same seed-pinning rule as every post-seed registry). Unlike every
#: class above, these are *windowed and directional*: a link is cut
#: for a tick interval and then HEALS, possibly in one direction only
#: (A hears B while B stops hearing A — the asymmetric regime that
#: makes heartbeat evidence diverge between the two sides), or flaps
#: on a seeded duty cycle. Both sides stay alive throughout, which is
#: exactly what crash-stop faults (:class:`StalledRank`,
#: :class:`DownLink`) can never model — each side can declare the
#: other dead and keep actuating, the split-brain hazard quorum
#: fencing (:mod:`smi_tpu.parallel.membership`) exists to close.
#: Consulted by the simulator through the tick-aware
#: ``link_blocked(src, dst, tick)`` hook; ``smi-tpu chaos
#: --partition`` sweeps them.
PARTITION_FAULT_CLASSES = ("partition", "asymmetric_link",
                           "flapping_link")

#: Named invariant violations that count as *detection*. A bare
#: ProtocolError (wrong delivery) is NOT in this set — that is silent
#: corruption and fails the matrix.
DETECTED_ERRORS = (C.ClobberError, C.DeadlockError, C.CreditLeakError,
                   C.IntegrityError)


class SilentCorruption(AssertionError):
    """A faulted run completed but delivered wrong data — the one
    outcome the fault matrix forbids (on hardware it would be
    invisible)."""


@dataclasses.dataclass(frozen=True)
class DroppedGrant:
    """Lose the ``nth`` credit grant signalled by ``rank``."""

    rank: int
    nth: int = 0


@dataclasses.dataclass(frozen=True)
class DuplicatedGrant:
    """Deliver the ``nth`` credit grant signalled by ``rank`` twice."""

    rank: int
    nth: int = 0


@dataclasses.dataclass(frozen=True)
class DelayedDma:
    """Hold the ``nth`` DMA started by ``src`` for ``hold`` scheduler
    events (slow, never lost: it lands once nothing else can run)."""

    src: int
    nth: int = 0
    hold: int = 64


@dataclasses.dataclass(frozen=True)
class StalledRank:
    """Crash-stop ``rank`` after ``after`` executed actions."""

    rank: int
    after: int = 0


@dataclasses.dataclass(frozen=True)
class DownLink:
    """All traffic between ranks ``a`` and ``b`` is lost, both ways."""

    a: int
    b: int


@dataclasses.dataclass(frozen=True)
class BitFlipPayload:
    """Corrupt the payload of the ``nth`` DMA started by ``src`` in
    flight (checksum stays the sender's) — the framing must catch it
    as an ``IntegrityError(kind="checksum")``."""

    src: int
    nth: int = 0


@dataclasses.dataclass(frozen=True)
class ReorderedChunks:
    """Swap the wire sequence numbers of the ``nth`` and ``nth+1``
    frames sent by ``src`` (CRCs recomputed, payloads intact) — a pure
    reordering signature the framing must catch as an
    ``IntegrityError(kind="sequence")``. With only ``nth`` in flight
    (last chunk) it degrades to a lone sequence skip, still detected."""

    src: int
    nth: int = 0


@dataclasses.dataclass(frozen=True)
class TruncatedDma:
    """Truncate the payload of the ``nth`` DMA started by ``src``
    (partial landing; checksum stays the full payload's) — caught as
    an ``IntegrityError(kind="checksum")``."""

    src: int
    nth: int = 0


@dataclasses.dataclass(frozen=True)
class DcnLinkDown:
    """The DCN path between ``slice_a`` and ``slice_b`` of a
    ``per_slice``-wide pod is severed: every cross-slice signal and
    DMA between ranks of the two slices is lost, both directions —
    the inter-slice analog of :class:`DownLink`, at slice granularity
    because DCN connectivity is per slice pair (one host fabric
    route), not per rank pair. In-slice ICI traffic is untouched.
    Detected as a :class:`~credits.DeadlockError` at the first pod
    phase-B wait that needed the dead route.
    """

    slice_a: int
    slice_b: int
    per_slice: int = 2

    def __post_init__(self):
        if self.slice_a == self.slice_b:
            raise ValueError(
                f"a DCN link connects two DISTINCT slices, got "
                f"{self.slice_a} twice (in-slice wires are ICI — use "
                f"DownLink)"
            )
        if self.per_slice < 1:
            raise ValueError(f"per_slice must be >= 1, got {self.per_slice}")

    def severs(self, a: int, b: int) -> bool:
        slice_of = C.pod_slice_of(self.per_slice)
        return {slice_of(a), slice_of(b)} == {self.slice_a, self.slice_b}


@dataclasses.dataclass(frozen=True)
class DcnDelay:
    """Hold the ``nth`` DMA started by ``src`` for ``hold`` scheduler
    events — but only when that DMA actually crosses a slice boundary
    of the ``per_slice``-wide pod (an in-slice copy is ICI business
    and this fault never touches it). The DCN tier's characteristic
    fault: slow, never lost — **tolerated** by the credit protocol
    like :class:`DelayedDma`, which is exactly what the pod protocol
    must prove about its cross-slice phase."""

    src: int
    nth: int = 0
    hold: int = 64
    per_slice: int = 2

    def __post_init__(self):
        if self.per_slice < 1:
            raise ValueError(f"per_slice must be >= 1, got {self.per_slice}")


@dataclasses.dataclass(frozen=True)
class FlappingRank:
    """``rank`` crash-stops at job iteration ``dies_at``, recovers,
    and asks to rejoin at iteration ``rejoins_at``.

    A *job-level* fault (units are iterations of an iterative job, not
    actions of one collective): the phi-accrual detector must confirm
    the death before any watchdog fires, survivors shrink and restore
    from the last checkpoint manifest, and the recovered rank regrows
    under a new epoch — with the dead incarnation's traffic rejected
    as :class:`~smi_tpu.parallel.membership.StaleEpochError`. Inside a
    single simulator run the rank simply runs or is absent (membership
    decides), so the plan's simulator hooks ignore this class.
    """

    rank: int
    dies_at: int = 2
    rejoins_at: int = 8

    def __post_init__(self):
        if self.rejoins_at <= self.dies_at:
            raise ValueError(
                f"FlappingRank must die before it rejoins "
                f"(dies_at={self.dies_at}, rejoins_at={self.rejoins_at})"
            )


@dataclasses.dataclass(frozen=True)
class SlowConsumer:
    """Rank ``rank``'s consumer stalls for ``stall_ticks`` step-clock
    ticks starting at ``from_tick`` — alive, heartbeating, computing
    nothing.

    The serving-level fault the end-to-end credit chain exists for:
    landed chunks stop being consumed, the destination's wire credits
    exhaust within :data:`~smi_tpu.serving.scheduler.WIRE_CREDITS`
    chunks, its accepted streams stop completing, their stream credits
    stay held, and the admission edge must shed NEW work to that
    destination with a named error (``backpressure:rank<r>``) instead
    of growing any queue. The phi-accrual detector must at most
    suspect-and-clear the rank — a membership transition on a merely
    saturated rank is a campaign failure (the dead-vs-saturated
    distinction, exercised from the saturated side).
    """

    rank: int
    from_tick: int = 40
    stall_ticks: int = 60

    def __post_init__(self):
        if self.stall_ticks < 1:
            raise ValueError(
                f"stall_ticks must be >= 1, got {self.stall_ticks}"
            )


@dataclasses.dataclass(frozen=True)
class StalledHeartbeat:
    """``rank`` stays alive and computing but its heartbeats go silent
    for ``silent_for`` step-clock ticks starting at ``from_tick``.

    The fault the two-threshold detector exists for: the rank must be
    *suspected* (phi crosses the suspect threshold) and then cleared
    when heartbeats resume — never confirmed dead, never shrunk. A
    detector that kills it is a false positive the elastic campaign
    counts as a failure. No simulator-hook effect (the data plane is
    healthy).
    """

    rank: int
    from_tick: int = 50
    silent_for: int = 20


@dataclasses.dataclass(frozen=True)
class PartitionFault:
    """Cut every wire between the ``minority`` rank set and the rest
    of the ring, BOTH directions, for ticks ``[from_tick, until_tick)``
    — then heal.

    The clean network partition: both sides stay alive and keep
    heartbeating *within* their side, but no signal, DMA, or heartbeat
    crosses the cut while the window is open. Each side's phi-accrual
    evidence therefore says the other side died — without quorum
    fencing, each side shrinks the other and keeps actuating, and on
    heal the two histories collide silently. The windowed analog of
    :class:`DownLink` (which never heals) at rank-set granularity.
    """

    minority: FrozenSet[int]
    from_tick: int = 40
    until_tick: int = 120

    def __post_init__(self):
        if not self.minority:
            raise ValueError("PartitionFault needs a non-empty minority "
                             "rank set (an empty cut partitions nothing)")
        if self.until_tick <= self.from_tick:
            raise ValueError(
                f"PartitionFault window is empty: from_tick="
                f"{self.from_tick}, until_tick={self.until_tick} "
                f"(must heal strictly after it cuts)"
            )

    def blocks(self, src: int, dst: int, tick: int) -> bool:
        if not (self.from_tick <= tick < self.until_tick):
            return False
        return (src in self.minority) != (dst in self.minority)


@dataclasses.dataclass(frozen=True)
class AsymmetricLinkFault:
    """Traffic FROM ``src`` TO ``dst`` is lost for ticks
    ``[from_tick, until_tick)``; the ``dst``->``src`` direction keeps
    flowing — then heal.

    The asymmetric partition: ``src`` still hears ``dst`` (so from
    ``src``'s side the world looks healthy) while ``dst`` stops
    hearing ``src`` (so ``dst``'s detector watches ``src``'s phi climb
    toward dead). Heartbeat evidence DIVERGES between the two sides —
    the regime where one side confirms a death the other side never
    suspected, which symmetric cuts cannot produce.
    """

    src: int
    dst: int
    from_tick: int = 40
    until_tick: int = 120

    def __post_init__(self):
        if self.src == self.dst:
            raise ValueError(
                f"an asymmetric link connects two DISTINCT ranks, got "
                f"{self.src} twice"
            )
        if self.until_tick <= self.from_tick:
            raise ValueError(
                f"AsymmetricLinkFault window is empty: from_tick="
                f"{self.from_tick}, until_tick={self.until_tick}"
            )

    def blocks(self, src: int, dst: int, tick: int) -> bool:
        if not (self.from_tick <= tick < self.until_tick):
            return False
        return src == self.src and dst == self.dst


@dataclasses.dataclass(frozen=True)
class FlappingLink:
    """The ``a``<->``b`` wire flaps on a seeded duty cycle: within
    each ``period``-tick window of ``[from_tick, until_tick)`` the
    link is down (both directions) for ``down_ticks`` consecutive
    ticks at a seeded offset, up otherwise.

    The fault two-threshold detection exists for, exercised at the
    *link* rather than the rank: beats are lost in bursts but always
    resume within the confirmation grace, so the detector must ride
    suspect/clear cycles WITHOUT ever confirming a death — a
    membership transition (or a park/rejoin oscillation) on a merely
    flapping wire is the failure mode the hysteresis gate counts.
    Deterministic per ``(a, b, seed)``: the same fault always flaps
    the same ticks.
    """

    a: int
    b: int
    from_tick: int = 40
    until_tick: int = 160
    period: int = 8
    down_ticks: int = 3
    seed: int = 0

    def __post_init__(self):
        if self.a == self.b:
            raise ValueError(
                f"a flapping link connects two DISTINCT ranks, got "
                f"{self.a} twice"
            )
        if self.until_tick <= self.from_tick:
            raise ValueError(
                f"FlappingLink window is empty: from_tick="
                f"{self.from_tick}, until_tick={self.until_tick}"
            )
        if self.period < 1:
            raise ValueError(f"period must be >= 1, got {self.period}")
        if not (1 <= self.down_ticks <= self.period):
            raise ValueError(
                f"down_ticks must be in 1..period={self.period}, got "
                f"{self.down_ticks} (a full-period outage is a "
                f"PartitionFault, not a flap)"
            )

    def blocks(self, src: int, dst: int, tick: int) -> bool:
        if {src, dst} != {self.a, self.b}:
            return False
        if not (self.from_tick <= tick < self.until_tick):
            return False
        window, offset = divmod(tick - self.from_tick, self.period)
        start = random.Random(
            f"flap:{self.a}:{self.b}:{self.seed}:{window}"
        ).randrange(self.period - self.down_ticks + 1)
        return start <= offset < start + self.down_ticks


def _corrupt_value(inner, truncate: bool):
    """Type-preserving in-flight damage: on hardware a flipped or
    truncated buffer still has the buffer's type — the reduction
    combines it, the consumer consumes it, nothing crashes. The
    simulator's symbolic payloads must behave the same way so bare
    (unframed) transport COMPLETES with wrong data rather than
    erroring, which is exactly the silent-corruption outcome the
    framing exists to prevent."""
    if truncate:
        if isinstance(inner, str):
            return inner[: len(inner) // 2]
        if isinstance(inner, frozenset):
            kept = sorted(inner, key=repr)[: len(inner) // 2]
            return frozenset(kept)
        if isinstance(inner, tuple):
            return inner[: len(inner) // 2]
        return ("truncated", repr(inner)[:4])
    if isinstance(inner, str):
        return inner + "\x01"
    if isinstance(inner, frozenset):
        return inner | {("bitflipped",)}
    if isinstance(inner, tuple):
        return inner + (("bitflipped",),)
    if isinstance(inner, int):
        return inner ^ 1
    return ("bitflipped", inner)


def _damage(payload, truncate: bool = False):
    """Corrupt a payload in flight. Framed: mutate the inner payload,
    KEEP the sender's CRC (the damage the checksum exists to catch).
    Bare: the same mutation, undetectable by anything but the harness's
    final output check."""
    if isinstance(payload, C.Frame):
        return dataclasses.replace(
            payload, payload=_corrupt_value(payload.payload, truncate)
        )
    return _corrupt_value(payload, truncate)


def _shift_seq(payload, delta: int):
    """Move a frame's wire sequence number by ``delta``, CRC recomputed
    — a pure reordering signature. Bare payloads carry no sequence
    number, so reordering is inexpressible there (no-op)."""
    if isinstance(payload, C.Frame):
        return C.make_frame(payload.src, payload.seq + delta,
                            payload.payload, wire=payload.wire)
    return payload


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Deterministic fault schedule for one simulator run.

    Implements the hook interface :class:`credits.RingSimulator`
    consults (``grant_multiplier`` / ``dma_hold`` / ``stall_after`` /
    ``link_down`` / the tick-aware ``link_blocked``). An empty plan is
    behaviourally identical to ``faults=None`` — the healthy fuzzer.
    """

    dropped_grants: Tuple[DroppedGrant, ...] = ()
    duplicated_grants: Tuple[DuplicatedGrant, ...] = ()
    delayed_dmas: Tuple[DelayedDma, ...] = ()
    stalled_ranks: Tuple[StalledRank, ...] = ()
    down_links: FrozenSet[Tuple[int, int]] = frozenset()
    bit_flips: Tuple[BitFlipPayload, ...] = ()
    reorders: Tuple[ReorderedChunks, ...] = ()
    truncations: Tuple[TruncatedDma, ...] = ()
    #: Job-level elastic faults (no simulator-hook effect; consumed by
    #: the membership layer's elastic soak).
    flapping_ranks: Tuple[FlappingRank, ...] = ()
    stalled_heartbeats: Tuple[StalledHeartbeat, ...] = ()
    #: Serving-level faults (no simulator-hook effect; consumed by the
    #: multi-tenant front-end's chaos-under-load cells).
    slow_consumers: Tuple[SlowConsumer, ...] = ()
    #: DCN-tier faults (slice-pair link cuts, cross-slice-only DMA
    #: holds) — consulted through the same hooks, slice-resolved.
    dcn_link_downs: Tuple[DcnLinkDown, ...] = ()
    dcn_delays: Tuple[DcnDelay, ...] = ()
    #: Partition-tier faults: windowed, possibly one-directional,
    #: possibly flapping link cuts that HEAL — consulted through the
    #: tick-aware ``link_blocked(src, dst, tick)`` hook (the simulator
    #: prefers it over plain ``link_down`` when present).
    partitions: Tuple[PartitionFault, ...] = ()
    asymmetric_links: Tuple[AsymmetricLinkFault, ...] = ()
    flapping_links: Tuple[FlappingLink, ...] = ()

    # -- hook interface (credits.RingSimulator) ------------------------
    def grant_multiplier(self, rank: int, nth: int) -> int:
        for f in self.dropped_grants:
            if f.rank == rank and f.nth == nth:
                return 0
        for f in self.duplicated_grants:
            if f.rank == rank and f.nth == nth:
                return 2
        return 1

    def dma_hold(self, src: int, nth: int) -> int:
        for f in self.delayed_dmas:
            if f.src == src and f.nth == nth:
                return f.hold
        return 0

    def dma_hold_to(self, src: int, dst: int, nth: int) -> int:
        """Destination-aware hold (the simulator prefers this hook
        when present): the base per-source holds plus the DCN delays,
        which apply only to a copy that actually crosses slices."""
        held = self.dma_hold(src, nth)
        if held:
            return held
        for f in self.dcn_delays:
            if f.src == src and f.nth == nth:
                slice_of = C.pod_slice_of(f.per_slice)
                if slice_of(src) != slice_of(dst):
                    return f.hold
        return 0

    def stall_after(self, rank: int) -> Optional[int]:
        for f in self.stalled_ranks:
            if f.rank == rank:
                return f.after
        return None

    def link_down(self, a: int, b: int) -> bool:
        if (a, b) in self.down_links or (b, a) in self.down_links:
            return True
        return any(f.severs(a, b) for f in self.dcn_link_downs)

    def link_blocked(self, src: int, dst: int, tick: int) -> bool:
        """Tick-aware, DIRECTIONAL link state — the hook the simulator
        prefers over :meth:`link_down` when present. Subsumes the
        static cuts (a permanently-down link is blocked at every tick)
        and adds the windowed partition classes: a symmetric cut
        blocks both directions across the minority boundary inside its
        window, an asymmetric cut blocks exactly its ``src``->``dst``
        direction, a flapping link blocks its seeded down-ticks.
        Healing is the whole point: past ``until_tick`` the wire
        carries traffic again and the two sides must reconcile.
        """
        if self.link_down(src, dst):
            return True
        for f in self.partitions:
            if f.blocks(src, dst, tick):
                return True
        for f in self.asymmetric_links:
            if f.blocks(src, dst, tick):
                return True
        for f in self.flapping_links:
            if f.blocks(src, dst, tick):
                return True
        return False

    def tamper(self, src: int, nth: int, payload):
        """Damage the ``nth`` DMA payload of ``src`` in flight.

        On a framed payload (``credits.Frame``) the damage is surgical:
        bit flips and truncation mutate the payload while keeping the
        sender's CRC (so only the receiver's checksum can notice);
        reordering swaps the sequence numbers of two consecutive frames
        with CRCs recomputed (so only the sequence check can notice).
        On a BARE payload the same damage lands undetectably — the run
        completes with wrong delivery, which the verdict harness
        re-raises as :class:`SilentCorruption`: the pair of behaviours
        is the framing layer's existence proof. Reordering is a
        framing-level concept (there is no sequence number to swap on a
        bare payload), so it is a no-op on unframed transport.
        """
        for f in self.bit_flips:
            if f.src == src and f.nth == nth:
                return _damage(payload)
        for f in self.truncations:
            if f.src == src and f.nth == nth:
                return _damage(payload, truncate=True)
        for f in self.reorders:
            if f.src == src and nth == f.nth:
                return _shift_seq(payload, +1)
            if f.src == src and nth == f.nth + 1:
                return _shift_seq(payload, -1)
        return payload

    # -- construction ---------------------------------------------------
    @property
    def empty(self) -> bool:
        return not (
            self.dropped_grants or self.duplicated_grants
            or self.delayed_dmas or self.stalled_ranks or self.down_links
            or self.bit_flips or self.reorders or self.truncations
            or self.flapping_ranks or self.stalled_heartbeats
            or self.slow_consumers
            or self.dcn_link_downs or self.dcn_delays
            or self.partitions or self.asymmetric_links
            or self.flapping_links
        )

    def faults(self) -> Tuple:
        """Every individual fault in the plan, deterministically ordered
        — the unit the chaos delta-debugger removes one at a time."""
        return (
            self.dropped_grants + self.duplicated_grants
            + self.delayed_dmas + self.stalled_ranks
            + tuple(DownLink(a, b) for a, b in sorted(self.down_links))
            + self.bit_flips + self.reorders + self.truncations
            + self.flapping_ranks + self.stalled_heartbeats
            + self.slow_consumers
            + self.dcn_link_downs + self.dcn_delays
            + self.partitions + self.asymmetric_links
            + self.flapping_links
        )

    def describe(self) -> List[str]:
        """One human-readable line per fault (the chaos report's and
        the minimal reproducer's rendering)."""
        return [
            f"{type(f).__name__}({', '.join(f'{k}={v}' for k, v in dataclasses.asdict(f).items())})"
            for f in self.faults()
        ]

    @classmethod
    def single(cls, fault) -> "FaultPlan":
        """A plan with exactly one fault."""
        if isinstance(fault, DroppedGrant):
            return cls(dropped_grants=(fault,))
        if isinstance(fault, DuplicatedGrant):
            return cls(duplicated_grants=(fault,))
        if isinstance(fault, DelayedDma):
            return cls(delayed_dmas=(fault,))
        if isinstance(fault, StalledRank):
            return cls(stalled_ranks=(fault,))
        if isinstance(fault, DownLink):
            return cls(down_links=frozenset({(fault.a, fault.b)}))
        if isinstance(fault, BitFlipPayload):
            return cls(bit_flips=(fault,))
        if isinstance(fault, ReorderedChunks):
            return cls(reorders=(fault,))
        if isinstance(fault, TruncatedDma):
            return cls(truncations=(fault,))
        if isinstance(fault, FlappingRank):
            return cls(flapping_ranks=(fault,))
        if isinstance(fault, StalledHeartbeat):
            return cls(stalled_heartbeats=(fault,))
        if isinstance(fault, SlowConsumer):
            return cls(slow_consumers=(fault,))
        if isinstance(fault, DcnLinkDown):
            return cls(dcn_link_downs=(fault,))
        if isinstance(fault, DcnDelay):
            return cls(dcn_delays=(fault,))
        if isinstance(fault, PartitionFault):
            return cls(partitions=(fault,))
        if isinstance(fault, AsymmetricLinkFault):
            return cls(asymmetric_links=(fault,))
        if isinstance(fault, FlappingLink):
            return cls(flapping_links=(fault,))
        raise TypeError(f"unknown fault {fault!r}")

    @classmethod
    def of(cls, faults) -> "FaultPlan":
        """A plan combining an iterable of individual faults — the
        multi-fault schedules the chaos campaign sweeps."""
        plan = cls()
        for fault in faults:
            single = cls.single(fault)
            plan = cls(
                dropped_grants=plan.dropped_grants + single.dropped_grants,
                duplicated_grants=(plan.duplicated_grants
                                   + single.duplicated_grants),
                delayed_dmas=plan.delayed_dmas + single.delayed_dmas,
                stalled_ranks=plan.stalled_ranks + single.stalled_ranks,
                down_links=plan.down_links | single.down_links,
                bit_flips=plan.bit_flips + single.bit_flips,
                reorders=plan.reorders + single.reorders,
                truncations=plan.truncations + single.truncations,
                flapping_ranks=(plan.flapping_ranks
                                + single.flapping_ranks),
                stalled_heartbeats=(plan.stalled_heartbeats
                                    + single.stalled_heartbeats),
                slow_consumers=(plan.slow_consumers
                                + single.slow_consumers),
                dcn_link_downs=(plan.dcn_link_downs
                                + single.dcn_link_downs),
                dcn_delays=plan.dcn_delays + single.dcn_delays,
                partitions=plan.partitions + single.partitions,
                asymmetric_links=(plan.asymmetric_links
                                  + single.asymmetric_links),
                flapping_links=(plan.flapping_links
                                + single.flapping_links),
            )
        return plan

    @classmethod
    def random(cls, fault_class: str, n: int, seed: int) -> "FaultPlan":
        """One deterministic random fault of ``fault_class`` for an
        ``n``-ring — the seeded generator the matrix sweeps. The same
        (class, n, seed) triple always builds the same plan."""
        rng = random.Random(f"{fault_class}:{n}:{seed}")
        rank = rng.randrange(n)
        if fault_class == "dropped_grant":
            return cls.single(DroppedGrant(rank, nth=rng.randrange(3)))
        if fault_class == "duplicated_grant":
            return cls.single(DuplicatedGrant(rank, nth=rng.randrange(3)))
        if fault_class == "delayed_dma":
            return cls.single(DelayedDma(
                rank, nth=rng.randrange(3), hold=rng.randrange(8, 120),
            ))
        if fault_class == "stalled_rank":
            return cls.single(StalledRank(rank, after=rng.randrange(12)))
        if fault_class == "down_link":
            return cls.single(DownLink(rank, (rank + 1) % n))
        if fault_class == "bit_flip_payload":
            return cls.single(BitFlipPayload(rank, nth=rng.randrange(3)))
        if fault_class == "reordered_chunks":
            return cls.single(ReorderedChunks(rank, nth=rng.randrange(3)))
        if fault_class == "truncated_dma":
            return cls.single(TruncatedDma(rank, nth=rng.randrange(3)))
        if fault_class == "flapping_rank":
            # dies after the detector bootstrap, rejoins mid-job so the
            # regrow path always exercises (elastic cells run >= 14
            # iterations)
            dies = 2 + rng.randrange(4)
            return cls.single(FlappingRank(
                rank, dies_at=dies, rejoins_at=dies + 4 + rng.randrange(4),
            ))
        if fault_class == "stalled_heartbeat":
            # silence starts after the soak's ~40-tick bootstrap and is
            # calibrated to the two-threshold band: long enough that
            # suspicion is guaranteed (>= suspect latency ~16 ticks for
            # any window phase), short enough that the resuming beat
            # lands inside the confirmation grace even in the worst
            # phase (window + ~2 periods of schedule phase must stay
            # under suspect latency + CONFIRM_GRACE_TICKS) — suspected,
            # cleared, never killed, for EVERY (from_tick, silent_for)
            # this generator can draw (swept in tests/test_membership)
            return cls.single(StalledHeartbeat(
                rank, from_tick=50 + rng.randrange(40),
                silent_for=16 + rng.randrange(9),
            ))
        if fault_class == "slow_consumer":
            # stall starts after the serving bootstrap has traffic in
            # flight, lasts long enough that backpressure must reach
            # the admission edge (the wire window is WIRE_CREDITS=4
            # chunks; tens of ticks of stall guarantee exhaustion)
            return cls.single(SlowConsumer(
                rank, from_tick=30 + rng.randrange(40),
                stall_ticks=40 + rng.randrange(41),
            ))
        if fault_class in DCN_FAULT_CLASSES:
            # pod shape convention for random draws: 2 slices of n//2
            # (the n-rank ring split in half) — n must be even
            if n < 2 or n % 2:
                raise ValueError(
                    f"DCN fault draws need an even n >= 2 (two slices "
                    f"of n//2), got n={n}"
                )
            per_slice = n // 2
            if fault_class == "dcn_link_down":
                return cls.single(DcnLinkDown(0, 1, per_slice=per_slice))
            return cls.single(DcnDelay(
                rank, nth=rng.randrange(3), hold=rng.randrange(8, 120),
                per_slice=per_slice,
            ))
        if fault_class in PARTITION_FAULT_CLASSES:
            if n < 2:
                raise ValueError(
                    f"partition fault draws need n >= 2 (a one-rank "
                    f"ring has no wire to cut), got n={n}"
                )
            start = 40 + rng.randrange(20)
            if fault_class == "partition":
                # a strict minority: never more than (n-1)//2 ranks on
                # the cut side, so the other side always keeps quorum
                size = 1 + rng.randrange(max(1, (n - 1) // 2))
                ranks = rng.sample(range(n), size)
                return cls.single(PartitionFault(
                    frozenset(ranks), from_tick=start,
                    until_tick=start + 60 + rng.randrange(40),
                ))
            if fault_class == "asymmetric_link":
                return cls.single(AsymmetricLinkFault(
                    rank, (rank + 1) % n, from_tick=start,
                    until_tick=start + 60 + rng.randrange(40),
                ))
            return cls.single(FlappingLink(
                rank, (rank + 1) % n, from_tick=start,
                until_tick=start + 80 + rng.randrange(40),
                period=8, down_ticks=2 + rng.randrange(2), seed=seed,
            ))
        raise ValueError(
            f"unknown fault class {fault_class!r}; "
            f"known: {FAULT_CLASSES + ELASTIC_FAULT_CLASSES + SERVING_FAULT_CLASSES + DCN_FAULT_CLASSES + PARTITION_FAULT_CLASSES}"
        )


# ---------------------------------------------------------------------------
# Verdicts: run one protocol under one plan, classify the outcome
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Verdict:
    """Outcome of one fault-matrix cell."""

    kind: str  # "tolerated" | "detected"
    error: Optional[C.ProtocolError] = None

    @property
    def tolerated(self) -> bool:
        return self.kind == "tolerated"

    @property
    def detected(self) -> bool:
        return self.kind == "detected"

    @property
    def error_name(self) -> Optional[str]:
        return None if self.error is None else type(self.error).__name__


def _simulate(protocol: str, n: int, strategy: C.Strategy,
              plan: Optional[FaultPlan], chunks: int,
              verified: bool = True, slices: int = 2,
              recorder=None) -> None:
    if protocol == "all_gather":
        C.simulate_all_gather(n, strategy, faults=plan, verified=verified,
                              recorder=recorder)
    elif protocol == "all_reduce":
        C.simulate_all_reduce(n, strategy, faults=plan, verified=verified,
                              recorder=recorder)
    elif protocol == "reduce_scatter":
        C.simulate_reduce_scatter(n, strategy, faults=plan,
                                  verified=verified, recorder=recorder)
    elif protocol == "neighbour_stream":
        C.simulate_neighbour_stream(n, chunks, strategy, faults=plan,
                                    verified=verified,
                                    recorder=recorder)
    elif protocol == "all_reduce_chunked":
        C.simulate_all_reduce_chunked(n, chunks, strategy, faults=plan,
                                      verified=verified,
                                      recorder=recorder)
    elif protocol == "allreduce_pod":
        if n % slices:
            raise ValueError(
                f"allreduce_pod needs n divisible by slices, got "
                f"n={n} slices={slices}"
            )
        C.simulate_allreduce_pod(slices, n // slices, strategy,
                                 faults=plan, verified=verified,
                                 recorder=recorder)
    elif protocol == "all_to_all":
        C.simulate_all_to_all(n, strategy, faults=plan,
                              verified=verified, recorder=recorder)
    elif protocol == "all_to_all_bruck":
        C.simulate_all_to_all(n, strategy, variant="bruck",
                              faults=plan, verified=verified,
                              recorder=recorder)
    elif protocol == "all_to_all_pod":
        if n % slices:
            raise ValueError(
                f"all_to_all_pod needs n divisible by slices, got "
                f"n={n} slices={slices}"
            )
        C.simulate_all_to_all_pod(slices, n // slices, strategy,
                                  faults=plan, verified=verified,
                                  recorder=recorder)
    elif protocol == "all_reduce_quantized":
        if n % slices:
            raise ValueError(
                f"all_reduce_quantized needs n divisible by slices, "
                f"got n={n} slices={slices}"
            )
        C.simulate_all_reduce_quantized(slices, n // slices, strategy,
                                        faults=plan, verified=verified,
                                        recorder=recorder)
    elif protocol == "all_reduce_sparse":
        C.simulate_all_reduce_sparse(n, strategy, faults=plan,
                                     verified=verified,
                                     recorder=recorder)
    else:
        raise ValueError(
            f"unknown protocol {protocol!r}; known: "
            f"{C.registered_protocols()}"
        )


def run_under_faults(
    protocol: str,
    n: int,
    plan: Optional[FaultPlan],
    strategy: Optional[C.Strategy] = None,
    chunks: int = 5,
    verified: bool = True,
    slices: int = 2,
    recorder=None,
) -> Verdict:
    """Execute one ring protocol under a fault plan and classify.

    Returns a *tolerated* verdict only when the run completed AND the
    harness verified delivery; a *detected* verdict for any named
    invariant violation (clobber / deadlock / credit leak / integrity).
    A completed run with wrong payloads raises
    :class:`SilentCorruption` — that outcome must never be classified,
    it must fail the build.

    ``verified`` runs the protocols over the verified-transport framing
    (the default, and behaviourally identical to bare transport under
    every non-tampering fault); ``verified=False`` strips the framing,
    which is how the matrix proves the payload-tampering classes WOULD
    be silent corruption without it.

    ``recorder`` (duck-typed flight recorder,
    :class:`smi_tpu.obs.events.FlightRecorder`) threads through to the
    simulator: a *detected* verdict's error then carries the bounded
    event tail (``recorder_tail``) naming the causal history behind
    the failure — what a campaign cell attaches to its evidence.
    """
    strategy = strategy if strategy is not None else C.Strategy(0)
    try:
        _simulate(protocol, n, strategy, plan, chunks, verified=verified,
                  slices=slices, recorder=recorder)
    except DETECTED_ERRORS as e:
        return Verdict("detected", e)
    except C.ProtocolError as e:
        raise SilentCorruption(
            f"{protocol} n={n} under {plan!r} completed with corrupt "
            f"delivery: {e}"
        ) from e
    return Verdict("tolerated")


# ---------------------------------------------------------------------------
# State-machine mirror for the runtime watchdogs
# ---------------------------------------------------------------------------

#: Maps a runtime collective family to its protocol state machine.
FAMILY_PROTOCOL = {
    "broadcast": "all_reduce",   # bcast rides the masked all-reduce ring
    "reduce": "all_reduce",
    "allreduce": "all_reduce",
    "scatter": "reduce_scatter",
    "gather": "all_gather",
    "stream": "neighbour_stream",
    "transfer": "neighbour_stream",
}


def _protocol_generators(protocol: str, n: int, chunks: int):
    if protocol == "all_gather":
        return [C.all_gather_rank(r, n, f"chunk{r}") for r in range(n)]
    if protocol == "all_reduce":
        return [
            C.all_reduce_rank(r, n, frozenset([r]), lambda a, b: a | b)
            for r in range(n)
        ]
    if protocol == "reduce_scatter":
        return [
            C.reduce_scatter_rank(
                r, n, [frozenset([(r, b)]) for b in range(n)],
                lambda a, b: a | b,
            )
            for r in range(n)
        ]
    if protocol == "neighbour_stream":
        return [
            C.neighbour_stream_rank(r, n, [(r, c) for c in range(chunks)])
            for r in range(n)
        ]
    raise ValueError(f"unknown protocol {protocol!r}; known: {PROTOCOLS}")


def mirror_stall_dump(protocol: str, n: int, chunks: int = 4) -> Dict:
    """Per-rank protocol state when no remote traffic ever completes.

    The watchdogs' state-machine mirror: advance every rank of the
    named protocol as far as it can go without landing a single DMA,
    then dump where each stands — the protocol-level silhouette of an
    indefinite device hang (every rank parked at its first wait that
    needed the wire). Deterministic; pure Python; cheap enough to build
    inside an error path.
    """
    if protocol in FAMILY_PROTOCOL:
        protocol = FAMILY_PROTOCOL[protocol]
    sim = C.RingSimulator(
        _protocol_generators(protocol, n, chunks), C.Strategy(0)
    )
    for _ in range(100_000):
        ranks = [c for c in sim._runnable() if c[0] == "rank"]
        if not ranks:
            break
        sim._execute_rank(ranks[0][1])
    return sim.state_dump()


def mirror_state_provider(family: str, n: int, chunks: int = 4,
                          structured: bool = False):
    """A zero-arg callable producing the formatted mirror dump — the
    ``state_provider`` shape :mod:`smi_tpu.utils.watchdog` consumes.

    With ``structured=True`` the callable returns ``(text, dump)``:
    the watchdog attaches the raw dump dict to
    ``WatchdogTimeout.state`` so programmatic recovery
    (:func:`smi_tpu.parallel.recovery.failed_ranks_of`) can read the
    per-rank states instead of re-parsing the formatted text.
    """

    def provide():
        protocol = FAMILY_PROTOCOL.get(family, family)
        try:
            dump = mirror_stall_dump(protocol, n, chunks)
        except Exception as e:  # the mirror must never mask the timeout
            text = f"(state mirror unavailable: {type(e).__name__}: {e})"
            return (text, None) if structured else text
        text = (
            f"protocol mirror [{protocol}, n={n}] with no remote "
            f"traffic completing:\n" + C.format_state_dump(dump)
        )
        return (text, dump) if structured else text

    return provide
