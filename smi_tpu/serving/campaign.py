"""Chaos under load: open-loop traffic cells and the seeded campaign.

Every robustness layer before this one ran against a single batch job;
these cells run the front-end against *sustained open-loop traffic* —
arrivals keep coming whether or not the system keeps up — and assert
the overload story end to end. Three cell shapes:

- **overload** — 2x the service capacity, no faults: admission must
  shed lowest-class-first (brownout ceilings), queue occupancy must
  stay inside the structural bound, interactive p99 admission latency
  must hold, and every accepted stream must still be delivered
  bit-identically;
- **kill** — a seeded kill-one-rank *during* the traffic: phi-accrual
  must confirm the death inside the watchdog budget, tenant routes
  must fail over to heirs, accepted in-flight streams must replay and
  complete bit-identically, straggler traffic from the dead
  incarnation must be rejected by epoch (counted; zero leaks);
- **backpressure** — one rank's consumer stalls (alive, heartbeating:
  the *saturated* half of the dead-vs-saturated distinction): the
  stall must propagate to the admission edge as named shedding, must
  NOT trigger any membership transition beyond a cleared suspicion,
  and every accepted stream must complete once the stall lifts.

Gates per cell (the campaign exit is nonzero if any fails):
zero silent corruption, zero lost-accepted, zero stale-epoch leaks,
bounded queue occupancy, lowest-class-first shedding (brownout sheds
ordered best_effort >= batch >= interactive, with zero interactive
brownout sheds), and interactive p99 admission wait <=
:data:`~smi_tpu.serving.qos.INTERACTIVE_P99_TICKS`. Deterministic per
seed — a red campaign reproduces from its JSON alone
(``smi-tpu chaos --load --seed N``).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from smi_tpu.parallel import faults as F
from smi_tpu.parallel.membership import WATCHDOG_TICKS
from smi_tpu.serving.admission import DEFAULT_POOL
from smi_tpu.serving.frontend import ServingFrontend
from smi_tpu.serving.qos import (
    CLASS_ADMISSION_WAIT_TICKS,
    INTERACTIVE_P99_TICKS,
    QOS_CLASSES,
    AdmissionRejected,
    percentile,
)

#: Traffic mix (weights) and chunks-per-request per class: interactive
#: requests are small and frequent, best_effort large and patient.
CLASS_MIX = {"interactive": 3, "batch": 3, "best_effort": 4}
CLASS_CHUNKS = {"interactive": 2, "batch": 4, "best_effort": 6}

#: Minimum campaign cell duration: every seeded fault the campaign can
#: draw (kill at tick 60, SlowConsumer from_tick <= 69) must land
#: INSIDE the traffic schedule with room for its effects to reach the
#: admission edge — a shorter run would report a misleading
#: "fault never fired" gate failure instead of a usage error.
MIN_CAMPAIGN_DURATION = 120


def _payload(tenant: str, stream_seq: int, chunk: int) -> str:
    """Deterministic, content-addressed chunk payload — bit-identity
    of delivery is checked against exactly this."""
    return f"{tenant}/s{stream_seq}/c{chunk}"


def campaign_recorder(duration: int, n: int):
    """A flight recorder sized to retain a WHOLE cell's event stream
    (the r15 span builder refuses a wrapped ring): generous per-tick
    estimate times the schedule, plus a drain cushion.
    ``$SMI_TPU_OBS_RING`` outranks the estimate — the operator's word
    stands, and an undersized override surfaces as a named
    span-exactness problem, never a silent truncation."""
    from smi_tpu.obs.events import FlightRecorder, ring_capacity

    estimate = duration * (n * 8 + 24) + 8192
    return FlightRecorder(capacity=ring_capacity(default=estimate))


def span_fields(fe, report: Dict, problems: List[str]) -> None:
    """Fold the span/blame payload into a cell report and extend the
    gate problems with any span-exactness failure (the bit-identity
    criterion: event-stream component sums == the front-end's own
    measured admission-to-delivery latencies)."""
    from smi_tpu.obs.spans import campaign_fields

    fields, span_problems = campaign_fields(fe)
    report.update(fields)
    problems.extend(span_problems)


def open_loop_traffic(
    seed: int,
    tenants: int,
    duration: int,
    requests_per_tick: float,
):
    """Seeded open-loop arrival schedule: a list per tick of
    ``(tenant, qos)`` submissions. Open-loop means the schedule never
    consults the system's state — arrivals continue regardless of
    shedding, which is what makes overload expressible at all."""
    rng = random.Random(f"traffic:{seed}")
    classes = [c for c in QOS_CLASSES for _ in range(CLASS_MIX[c])]
    schedule: List[List[Tuple[str, str]]] = []
    acc = 0.0
    for _ in range(duration):
        acc += requests_per_tick
        burst = []
        while acc >= 1.0:
            acc -= 1.0
            tenant = f"t{rng.randrange(tenants)}"
            burst.append((tenant, rng.choice(classes)))
        schedule.append(burst)
    return schedule


def run_load_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 240,
    overload: float = 1.0,
    kill_rank: Optional[int] = None,
    kill_at: int = 60,
    stall_rank: Optional[int] = None,
    stall_at: int = 40,
    stall_ticks: int = 60,
    tenants: int = 6,
    pool: int = DEFAULT_POOL,
    plan: Optional[F.FaultPlan] = None,
    return_frontend: bool = False,
):
    """One chaos-under-load cell: open-loop traffic, optional fault,
    full drain, gates evaluated. Deterministic per (shape, seed).

    Faults come either as explicit knobs (``kill_rank``/``kill_at``,
    ``stall_rank``/...) or as a :class:`~smi_tpu.parallel.faults.FaultPlan`
    carrying serving-level faults: each
    :class:`~smi_tpu.parallel.faults.SlowConsumer` maps onto a
    consumer stall in ticks (the seeded draw
    ``FaultPlan.random("slow_consumer", n, seed)`` is how the campaign
    sweeps the class). ``return_frontend=True`` returns
    ``(report, frontend)`` — the span/trace consumers need the live
    recorder, not just the report."""
    fe = ServingFrontend(n, seed=seed, pool=pool,
                         recorder=campaign_recorder(duration, n))
    if plan is not None:
        if plan.slow_consumers and stall_rank is not None:
            raise ValueError(
                "pass a stall either explicitly or via the plan, "
                "not both"
            )
        if len(plan.slow_consumers) > 1:
            raise ValueError(
                f"run_load_cell drives one SlowConsumer per cell; "
                f"the plan carries {len(plan.slow_consumers)} — "
                f"sweep more cells instead"
            )
        for f in plan.slow_consumers:
            stall_rank, stall_at = f.rank, f.from_tick
            stall_ticks = f.stall_ticks
    if kill_rank is not None and kill_at >= duration:
        raise ValueError(
            f"kill_at={kill_at} never fires inside a {duration}-tick "
            f"schedule — raise duration past the fault tick"
        )
    if stall_rank is not None and stall_at >= duration:
        raise ValueError(
            f"stall at tick {stall_at} never fires inside a "
            f"{duration}-tick schedule — raise duration past the "
            f"fault tick"
        )
    mean_chunks = (
        sum(CLASS_MIX[c] * CLASS_CHUNKS[c] for c in QOS_CLASSES)
        / sum(CLASS_MIX.values())
    )
    capacity = n * fe.consume_rate  # chunks/tick
    requests_per_tick = overload * capacity / mean_chunks
    schedule = open_loop_traffic(seed, tenants, duration,
                                 requests_per_tick)
    tenant_seq: Dict[str, int] = {}
    submitted = 0
    verdict = "ok"
    try:
        for tick, burst in enumerate(schedule):
            now = fe.clock.now()
            if kill_rank is not None and tick == kill_at:
                fe.kill(kill_rank)
            if stall_rank is not None and tick == stall_at:
                fe.stall_consumer(stall_rank, now + stall_ticks)
            for tenant, qos in burst:
                submitted += 1
                seq = tenant_seq.get(tenant, 0)
                tenant_seq[tenant] = seq + 1
                chunks = tuple(
                    _payload(tenant, seq, c)
                    for c in range(CLASS_CHUNKS[qos])
                )
                try:
                    fe.submit(tenant, qos, chunks)
                except AdmissionRejected:
                    pass  # named + recorded by the gate
            fe.step()
        fe.drain()
    except Exception as e:  # a watchdog/assert firing IS the verdict
        verdict = f"{type(e).__name__}: {e}"

    report = fe.report()
    report.update({
        "seed": seed,
        "duration": duration,
        "overload": overload,
        "kill_rank": kill_rank,
        "stall_rank": stall_rank,
        "plan": plan.describe() if plan is not None else [],
        "submitted_total": submitted,
        "offered_chunks_per_tick": round(
            requests_per_tick * mean_chunks, 3
        ),
        "capacity_chunks_per_tick": capacity,
        # the deterministic metrics snapshot (smi_tpu.obs): its
        # admitted/shed counters are incremented at the gate's own
        # accounting sites, so they EQUAL the report's bookkeeping —
        # tested, and the `--metrics` CLI surfaces quote it
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates ----------------------------------------------------------
    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    if report["silent_corruptions"]:
        problems.append(
            f"silent corruption: {report['silent_corruptions']} "
            f"stream(s) delivered wrong bits"
        )
    if report["lost_accepted"]:
        problems.append(
            f"lost accepted: {report['lost_accepted']} admitted "
            f"stream(s) never delivered"
        )
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    if report["max_queue_depth"] > report["queue_bound"]:
        problems.append(
            f"queue occupancy {report['max_queue_depth']} exceeded "
            f"bound {report['queue_bound']}"
        )
    brownout = {
        c: sum(v for k, v in report["shed"][c].items()
               if k.startswith("brownout") or k == "admission-timeout")
        for c in QOS_CLASSES
    }
    report["brownout_shed"] = brownout
    # destination-unavailability sheds (per-route backpressure) are a
    # separate, named category: class-blind by design, so they are
    # excluded from the lowest-class-first ordering gate
    report["backpressure_shed"] = {
        c: sum(v for k, v in report["shed"][c].items()
               if k.startswith("backpressure:"))
        for c in QOS_CLASSES
    }
    if kill_rank is None and brownout["interactive"] > 0:
        # fair weather and saturation: interactive never browns out.
        # During a kill's detection blackout the pool can genuinely
        # exhaust (stalled streams hold their credits by design), so
        # there the guarantee is ORDERING + the bounded wait cap.
        problems.append(
            f"interactive brownout-shed {brownout['interactive']} "
            f"(> 0): shedding is not lowest-class-first"
        )
    if (brownout["best_effort"] < brownout["batch"]
            or brownout["batch"] < brownout["interactive"]):
        problems.append(
            "shedding not lowest-class-first: best_effort "
            f"{brownout['best_effort']} / batch {brownout['batch']} / "
            f"interactive {brownout['interactive']}"
        )
    waits = report["admission_waits"]["interactive"]
    p99 = percentile(waits, 0.99)
    report["admission_latency"] = {
        c: {
            "p50": percentile(report["admission_waits"][c], 0.50),
            "p99": percentile(report["admission_waits"][c], 0.99),
        }
        for c in QOS_CLASSES
    }
    # the p99 bound: tight in fair weather, the structural wait cap
    # during a kill's detection blackout (bounded either way — the
    # admission edge sheds rather than queue past the cap)
    p99_bound = (INTERACTIVE_P99_TICKS if kill_rank is None
                 else CLASS_ADMISSION_WAIT_TICKS["interactive"])
    report["interactive_p99_bound"] = p99_bound
    if p99 is not None and p99 > p99_bound:
        problems.append(
            f"interactive p99 admission latency {p99:g} ticks "
            f"exceeds the {p99_bound}-tick bound"
        )
    if kill_rank is not None:
        if report["confirmed"] != [kill_rank]:
            problems.append(
                f"kill of rank {kill_rank} not confirmed "
                f"(confirmed: {report['confirmed']})"
            )
        elif report["detect_ticks"] is None or (
            report["detect_ticks"] > WATCHDOG_TICKS
        ):
            problems.append(
                f"detect latency {report['detect_ticks']} ticks "
                f"outside the {WATCHDOG_TICKS}-tick watchdog budget"
            )
        if not report["stale_epoch_rejections"]:
            problems.append("straggler from dead incarnation was "
                            "never presented/rejected")
    if stall_rank is not None:
        if report["confirmed"]:
            problems.append(
                f"stalled-but-alive consumer confirmed dead: "
                f"{report['confirmed']} (saturation mistaken for "
                f"death)"
            )
        if not any(report["backpressure_shed"].values()):
            problems.append(
                "consumer stall never propagated to the admission "
                "edge (zero backpressure sheds)"
            )
    # the r15 span layer: per-request span trees from the event
    # stream, the tail-latency blame verdict, and the bit-identity
    # exactness gate (span-component sums == measured latencies)
    span_fields(fe, report, problems)
    # drop the unhashed per-request wait lists from the shipped report
    # (the percentiles above carry the evidence)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


def load_campaign(
    seed: int = 0,
    n: int = 4,
    duration: int = 240,
    trials: int = 1,
    retune: bool = False,
) -> Dict:
    """The seeded chaos-under-load campaign: one overload cell, one
    kill-one-rank cell, and one backpressure cell per trial, each
    deterministic per seed. Exit gate: every cell ``ok``.

    ``duration`` below :data:`MIN_CAMPAIGN_DURATION` is a loud
    ``ValueError``: the seeded fault ticks would fall outside the
    schedule and report as (bogus) detection failures."""
    if duration < MIN_CAMPAIGN_DURATION:
        raise ValueError(
            f"campaign duration {duration} is below the "
            f"{MIN_CAMPAIGN_DURATION}-tick minimum: the seeded kill "
            f"(tick 60) and consumer-stall (from_tick <= 69) cells "
            f"need the fault inside the traffic schedule"
        )
    cells: List[Dict] = []
    for trial in range(trials):
        base = random.Random(f"load:{seed}:{trial}").randrange(1 << 30)
        kill = random.Random(f"kill:{seed}:{trial}").randrange(n)
        stall_plan = F.FaultPlan.random(
            "slow_consumer", n,
            random.Random(f"stall:{seed}:{trial}").randrange(1 << 30),
        )
        shapes = [
            ("overload", dict(overload=2.0)),
            ("kill", dict(overload=1.0, kill_rank=kill, kill_at=60)),
            ("backpressure", dict(overload=1.0, plan=stall_plan)),
        ]
        for name, kwargs in shapes:
            report = run_load_cell(
                n=n, seed=base, duration=duration, **kwargs
            )
            report["cell"] = name
            report["trial"] = trial
            cells.append(report)
        if retune:
            # the r14 cell: the payload distribution shifts mid-run
            # and the online tuner must hot-swap to the offline-sweep
            # pick with bit-identical delivery
            report = run_retune_cell(n=n, seed=base, duration=duration)
            report["cell"] = "retune-shift"
            report["trial"] = trial
            cells.append(report)
    failures = [c for c in cells if not c["ok"]]
    return {
        "seed": seed,
        "n": n,
        "duration": duration,
        "trials": trials,
        "cells": len(cells),
        "outcomes": {
            c["cell"]: ("ok" if c["ok"] else "failed") for c in cells
        },
        "failures": [
            {"cell": c["cell"], "trial": c["trial"],
             "verdict": c["verdict"]}
            for c in failures
        ],
        "silent_corruptions": sum(
            c["silent_corruptions"] for c in cells
        ),
        "lost_accepted": sum(c["lost_accepted"] for c in cells),
        "stale_epoch_leaks": sum(
            c["stale_epoch_leaks"] for c in cells
        ),
        "reports": cells,
        "ok": not failures,
    }


def run_retune_cell(
    n: int = 4,
    seed: int = 0,
    duration: int = 240,
    tenants: int = 4,
    pool: int = DEFAULT_POOL,
    slices: Optional[int] = None,
    small_kb: int = 64,
    large_kb: int = 4096,
    kill_rank: Optional[int] = None,
    kill_at: int = 60,
    return_frontend: bool = False,
):
    """The seeded payload-shift retuning cell (ROADMAP item 3's gate).

    A front-end runs with the online tuner wired
    (``ServingFrontend(retune=)``); every admitted request stands for
    one allreduce whose live timing is the Hockney pricing of the
    ACTIVE plan at that payload (the credits simulator's wire tiers)
    with seeded ±5% noise — exactly the measurement
    ``tracing.timed(sink=tuner)`` would stream on hardware, made
    deterministic. The tenants' payload distribution shifts mid-run
    (``small_kb`` → ``large_kb``), invalidating a STALE offline sweep
    entry that pinned the fused ring for the large bucket: the tuner
    must shadow-compare, propose, quiesce (drain the proposing
    tenant's in-flight streams), hot-swap the entry under a bumped
    plan epoch + revision, and converge to the plan a fresh offline
    sweep would pick for the new distribution (rs+ag flat,
    hierarchical on a ``slices``-pod) — with bit-identical delivery
    throughout, zero lost-accepted, zero stale-plan leaks, and zero
    swaps before the shift (the noise-can't-flip thresholds).
    """
    from smi_tpu.tuning import cost_model as cm
    from smi_tpu.tuning.cache import CacheEntry, PlanCache
    from smi_tpu.tuning.engine import _collective_topology
    from smi_tpu.tuning.online import OnlineTuner, priced_sample_us
    from smi_tpu.tuning.plan import PlanKey, payload_bucket

    if duration < MIN_CAMPAIGN_DURATION:
        raise ValueError(
            f"retune cell duration {duration} is below the "
            f"{MIN_CAMPAIGN_DURATION}-tick minimum: the payload shift "
            f"(mid-run) and the post-shift sample window both need "
            f"room inside the schedule"
        )
    if kill_rank is not None and kill_at >= duration:
        raise ValueError(
            f"kill_at={kill_at} never fires inside a {duration}-tick "
            f"schedule — raise duration past the fault tick"
        )
    if slices is not None:
        if slices < 2 or 8 % slices:
            raise ValueError(
                f"slices={slices} does not tier an 8-rank pod "
                f"(need a divisor >= 2)"
            )
        topo = cm.TopologySpec(n=8, inner=8 // slices, outer=slices)
    else:
        topo = cm.TopologySpec(n=8)
    device_kind = "live-sim"
    small_bytes, large_bytes = small_kb * 1024, large_kb * 1024
    if payload_bucket(small_bytes) == payload_bucket(large_bytes):
        raise ValueError(
            f"small_kb={small_kb} and large_kb={large_kb} land in the "
            f"same payload bucket — no distribution shift to retune on"
        )

    # the stale offline artifact: yesterday's sweep (run under the
    # small-payload mix this tenant no longer sends) pinned the fused
    # ring for the large bucket — the entry the live tuner must retire
    cache = PlanCache()
    topology = _collective_topology(topo)
    large_key = PlanKey("all_reduce", payload_bucket(large_bytes),
                        "float32", device_kind, topology)
    cache.put(large_key, CacheEntry(
        {"algorithm": "ring"},
        cost_us=round(priced_sample_us(
            "all_reduce", "ring", small_bytes, topo), 3),
        provenance="sweep:stale-offline",
    ))
    tuner = OnlineTuner(cache=cache, topo=topo,
                        device_kind=device_kind)
    fe = ServingFrontend(n, seed=seed, pool=pool, retune=tuner,
                         recorder=campaign_recorder(duration, n))

    # what a FRESH offline sweep would measure best for the new
    # distribution: the model's top candidate (samples are priced by
    # the same tables, so measurement and model agree here by
    # construction — the deterministic analog of the ATLAS claim)
    expected = cm.allreduce_candidates(large_bytes, topo)[0].name

    shift_at = duration // 2
    noise = random.Random(f"retune-noise:{seed}")
    mean_chunks = (
        sum(CLASS_MIX[c] * CLASS_CHUNKS[c] for c in QOS_CLASSES)
        / sum(CLASS_MIX.values())
    )
    capacity = n * fe.consume_rate
    requests_per_tick = capacity / mean_chunks
    schedule = open_loop_traffic(seed, tenants, duration,
                                 requests_per_tick)
    tenant_seq: Dict[str, int] = {}
    submitted = 0
    swap_tick = None
    early_swaps = 0
    verdict = "ok"
    try:
        for tick, burst in enumerate(schedule):
            if kill_rank is not None and tick == kill_at:
                fe.kill(kill_rank)
            payload = small_bytes if tick < shift_at else large_bytes
            for tenant, qos in burst:
                submitted += 1
                seq = tenant_seq.get(tenant, 0)
                tenant_seq[tenant] = seq + 1
                chunks = tuple(
                    _payload(tenant, seq, c)
                    for c in range(CLASS_CHUNKS[qos])
                )
                try:
                    fe.submit(tenant, qos, chunks)
                except AdmissionRejected:
                    # shed at the edge: the allreduce this request
                    # stood for never ran, so there is no timing to
                    # record — a rejected request must not inflate a
                    # cell's sample count toward the min_samples gate
                    continue
                # the live timing of the allreduce this request
                # drives, under whatever plan is ACTIVE right now
                entry = tuner.active_entry(
                    tuner.plan_key("all_reduce", payload)
                )
                algorithm = (
                    str(entry.knobs["algorithm"]) if entry is not None
                    else cm.allreduce_candidates(payload, topo)[0].name
                )
                us = priced_sample_us(
                    "all_reduce", algorithm, payload, topo
                ) * (1.0 + (noise.random() - 0.5) * 0.1)
                tuner.record("all_reduce", us * 1e-6,
                             payload_bytes=payload, tenant=tenant)
            fe.step()
            if tuner.swaps and swap_tick is None:
                swap_tick = tick
                if tick < shift_at:
                    early_swaps += 1
        fe.drain()
    except Exception as e:  # a watchdog/assert firing IS the verdict
        verdict = f"{type(e).__name__}: {e}"

    report = fe.report()
    final = tuner.active_entry(large_key)
    converged_algorithm = (
        str(final.knobs["algorithm"]) if final is not None else None
    )
    report.update({
        "seed": seed,
        "duration": duration,
        "shift_at": shift_at,
        "small_kb": small_kb,
        "large_kb": large_kb,
        "slices": slices,
        "kill_rank": kill_rank,
        "submitted_total": submitted,
        "expected_algorithm": expected,
        "converged_algorithm": converged_algorithm,
        "converged_revision": final.revision if final else None,
        "swap_tick": swap_tick,
        "convergence_ticks": (swap_tick - shift_at
                              if swap_tick is not None else None),
        "metrics": fe.metrics.snapshot(),
    })

    # -- gates ----------------------------------------------------------
    problems: List[str] = []
    if verdict != "ok":
        problems.append(verdict)
    if report["silent_corruptions"]:
        problems.append(
            f"silent corruption: {report['silent_corruptions']} "
            f"stream(s) delivered wrong bits"
        )
    if report["lost_accepted"]:
        problems.append(
            f"lost accepted: {report['lost_accepted']} admitted "
            f"stream(s) never delivered"
        )
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    rt = report["retune"]
    if rt["stale_plan_leaks"]:
        problems.append("stale-plan traffic accepted")
    if report["max_queue_depth"] > report["queue_bound"]:
        problems.append(
            f"queue occupancy {report['max_queue_depth']} exceeded "
            f"bound {report['queue_bound']}"
        )
    if early_swaps:
        problems.append(
            f"{early_swaps} swap(s) fired BEFORE the payload shift — "
            f"noise flipped a plan the thresholds should hold"
        )
    if rt["swaps"] < 1:
        problems.append(
            "the tuner never swapped: the stale offline entry "
            "survived the shifted distribution"
        )
    elif converged_algorithm != expected:
        problems.append(
            f"converged to {converged_algorithm!r} but a fresh "
            f"offline sweep of the shifted distribution picks "
            f"{expected!r}"
        )
    if rt["swaps"] >= 1 and not rt["stale_plan_rejections"]:
        problems.append(
            "post-swap straggler was never presented/rejected"
        )
    if rt["rollbacks"]:
        problems.append(
            f"{rt['rollbacks']} rollback(s) in the seeded cell — "
            f"quiesce did not drain inside its window"
        )
    if kill_rank is not None and report["confirmed"] != [kill_rank]:
        problems.append(
            f"kill of rank {kill_rank} not confirmed "
            f"(confirmed: {report['confirmed']})"
        )
    waits = report["admission_waits"]
    report["admission_latency"] = {
        c: {
            "p50": percentile(waits[c], 0.50),
            "p99": percentile(waits[c], 0.99),
        }
        for c in QOS_CLASSES
    }
    span_fields(fe, report, problems)
    del report["admission_waits"]
    report["verdict"] = "; ".join(problems) if problems else "ok"
    report["ok"] = not problems
    if return_frontend:
        return report, fe
    return report


def retune_selftest(seed: int = 0) -> Dict:
    """The ``smi-tpu serve --selftest --retune`` smoke: the seeded
    payload-shift cell at a fast shape — the tuner must converge to
    the offline-sweep pick with bit-identical delivery."""
    return run_retune_cell(n=4, seed=seed, duration=160)


#: Model-checker property -> the campaign gate it instantiates. The
#: model tier (:mod:`smi_tpu.analysis.model`) checks these same gates
#: exhaustively at small scope; a counterexample trace replayed here
#: must fail with the matching campaign verdict — differential
#: soundness in both directions (tests/test_serving.py pins it).
MODEL_GATES = {
    "queue-bound": "queue occupancy exceeded bound",
    "stream-credit": "stream-credit conservation violated",
    "starvation": "ready stream starved past the aging bound",
    "epoch-safety": "stale-epoch traffic accepted",
    "lost-accepted": "lost accepted",
    "plan-epoch-safety": "stale-plan traffic accepted",
    "swap-lost-accepted": "plan swap lost the active plan",
}


def replay_model_trace(scope, trace, mutant: Optional[str] = None) -> Dict:
    """Re-execute a model-checker counterexample as a campaign cell.

    ``scope`` is an :class:`~smi_tpu.analysis.model.Scope`, a scope
    dict (the JSON report's ``scope`` field), or a ``--scope`` spec
    string; ``trace`` the finding's action list (tuples or the JSON
    report's lists); ``mutant`` the control-plane mutant the trace was
    found under (None replays against the clean world). The trace is
    driven through a fresh :class:`~smi_tpu.analysis.model.World` —
    the same real gate/scheduler/membership/WAL objects — and the
    cell's gates are evaluated on the resulting state. A
    counterexample must come back ``ok=False`` with the matching
    :data:`MODEL_GATES` verdict; any trace of a clean world must come
    back ``ok=True``.
    """
    from smi_tpu.analysis import model as M
    from smi_tpu.analysis import model_mutant_world
    from smi_tpu.analysis.properties import check_state, check_terminal

    if isinstance(scope, str):
        scope = M.parse_scope(scope)
    elif isinstance(scope, dict):
        scope = M.Scope(**scope)
    factory = M.World if mutant is None else model_mutant_world(mutant)
    world = factory(scope)
    for action in trace:
        action = tuple(action)
        enabled = world.enabled_actions()
        if action not in enabled:
            raise ValueError(
                f"trace step {action!r} is not enabled in the replayed "
                f"state (enabled: {enabled}) — the trace does not "
                f"belong to this scope/mutant"
            )
        world.apply(action)
    violations = check_state(world)
    if not violations and not world.enabled_actions():
        violations = check_terminal(world)
    report = world.report()
    problems = [
        f"{MODEL_GATES[prop]}: {message}"
        for prop, message in violations
    ]
    report.update({
        "cell": "model-replay",
        "mutant": mutant,
        "trace_steps": len(list(trace)),
        "verdict": "; ".join(problems) if problems else "ok",
        "ok": not problems,
    })
    return report


def serve_selftest(seed: int = 0, return_frontend: bool = False):
    """The ``smi-tpu serve --selftest`` smoke: a deterministic CPU
    admit -> stream -> shed -> drain pass (overload cell at a fast
    shape) whose gates must all hold. Returns the cell report
    (``ok=False`` on any gate failure); ``return_frontend=True``
    returns ``(report, frontend)`` — the ONE selftest shape, shared
    with ``trace --serve`` so the exported trace can never drift from
    the run the selftest gates."""
    return run_load_cell(
        n=4, seed=seed, duration=160, overload=2.0,
        return_frontend=return_frontend,
    )


def bench_fields(seed: int = 0) -> Dict:
    """The additive ``serving`` field for ``bench.py``: a small
    deterministic front-end smoke (pure Python, milliseconds) whose
    offered load, per-class accept/shed counts, and admission-latency
    percentiles ride next to the headline number — the serving regime
    the build would sustain, measured, not asserted."""
    rep = run_load_cell(n=4, seed=seed, duration=120, overload=2.0)
    return {
        "offered_chunks_per_tick": rep["offered_chunks_per_tick"],
        "capacity_chunks_per_tick": rep["capacity_chunks_per_tick"],
        "accepted": rep["accepted"],
        "shed": {c: sum(rep["shed"][c].values())
                 for c in QOS_CLASSES},
        "admission_latency": rep["admission_latency"],
        "ok": rep["ok"],
    }
