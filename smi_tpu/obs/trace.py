"""Perfetto/Chrome-trace export of the timestamped simulator.

The PR 6 cost-model simulator prices a protocol run; the PR 11
decomposer attributes every clock advance to alpha / beta /
serialization / idle. This module renders that attribution as a
Chrome-trace-event JSON (the format Perfetto and ``chrome://tracing``
both open): one track per rank, one complete span per attributed
component interval, each span's ``args`` naming the producing event
the decomposer blamed.

Exactness contract (asserted at export time, pinned by
``tests/test_obs.py``):

- a rank's spans **tile** ``[0, clock[rank]]`` — consecutive span
  boundaries are the simulator's own float timestamps, so the last
  span's end is the rank's clock *bit-identically* (no duration
  arithmetic, no rounding on the checked path);
- the max over ranks is therefore bit-identical to
  ``RingSimulator.elapsed_seconds()``;
- every span's component label comes from the decomposer's
  classification (:class:`smi_tpu.analysis.perf._TimedReplay` — the
  same ``_book`` calls that build ``lint --perf``'s report), so the
  trace and the static report can never tell different stories about
  the same run.

Determinism: the replay is deterministic per (protocol, shape,
payload, seed); :func:`trace_to_json_bytes` serializes with sorted
keys — same seed, byte-identical file.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from smi_tpu.analysis import perf as P
from smi_tpu.analysis.verifier import (
    DEFAULT_SHAPES,
    AnalysisError,
    build_generators,
    verify_generators,
)
from smi_tpu.parallel import credits as C

#: Pinned Chrome-trace schema version for this exporter's payloads —
#: bumped on any shape change; :func:`validate_chrome_trace` and the
#: tests check it. v2 (r15): payloads carry ``trace_kind`` —
#: ``protocol`` (the simulator decomposition, unchanged) or
#: ``serving`` (request span trees on per-tenant track groups).
TRACE_SCHEMA_VERSION = 2

#: Chronological order of a jump's components inside its wait window:
#: idle is time before the producer even issued, then the latency
#: window (serialization for control signals, alpha for data), then
#: the bandwidth window.
_COMPONENT_ORDER = {"idle": 0, "serialization": 1, "alpha": 1, "beta": 2}


class _TraceReplay(P._TimedReplay):
    """The decomposer's replay plus per-jump span capture.

    The base class books each jump's split through ``_book`` (alpha /
    beta / idle for a DMA wait, serialization / idle for a signal
    wait); this subclass groups those calls per ``_classify`` and lays
    them out chronologically inside the jump's ``[before, after]``
    window, forcing the final boundary to ``after`` exactly — which is
    what makes span tiling bit-identical to the rank clocks.
    """

    def __init__(self, generators, strategy, costs):
        #: rank -> [{"t0", "t1", "component", "tier", ...}] in time order
        self.spans: Dict[int, List[dict]] = {}
        self._jump_parts: Optional[List[Tuple[str, str, float]]] = None
        super().__init__(generators, strategy, costs)

    def _book(self, r, tier, component, s):
        if self._jump_parts is not None:
            self._jump_parts.append((tier, component, s))
        super()._book(r, tier, component, s)

    def _classify(self, r, step, action, before, after):
        self._jump_parts = []
        try:
            super()._classify(r, step, action, before, after)
        finally:
            parts, self._jump_parts = self._jump_parts, None
        parts = [p for p in parts if p[2] > 0.0]
        parts.sort(key=lambda p: _COMPONENT_ORDER[p[1]])
        jump = self._last_jump.get(r)
        spans = self.spans.setdefault(r, [])
        t = before
        for i, (tier, component, s) in enumerate(parts):
            # interior boundaries accumulate; the LAST boundary is the
            # simulator's own post-wait clock — the tiling invariant
            end = after if i == len(parts) - 1 else t + s
            span = {
                "t0": t, "t1": end, "component": component,
                "tier": tier,
            }
            if jump is not None:
                span["producer"] = str(jump["producer"])
                span["waiter"] = str(jump["waiter"])
                span["lane"] = list(jump["lane"])
            spans.append(span)
            t = end

    def rank_span_end(self, r: int) -> float:
        """The rank's last span boundary (0.0 when it never waited on
        a priced event) — asserted ``== clock[r]`` bit-identically."""
        spans = self.spans.get(r)
        return spans[-1]["t1"] if spans else 0.0


def trace_protocol(
    protocol: str, n: int, chunks: int = 3, slices: int = 2,
    payload_bytes: float = float(P.PERF_PAYLOAD_BYTES), seed: int = 0,
    verify: bool = True,
) -> dict:
    """Export one registered protocol instance as a Chrome-trace JSON.

    Mirrors :func:`smi_tpu.analysis.perf.decompose_protocol`'s shape
    and pricing conventions exactly (same ``_costs_for``, same
    verifier pre-pass) and asserts the span-tiling contract before
    returning — a payload this function returns has already proven
    its span sums against ``elapsed_seconds()``.
    """
    shape: Dict[str, int] = {"n": n}
    if protocol in ("neighbour_stream", "all_reduce_chunked"):
        shape["chunks"] = chunks
    if protocol in ("allreduce_pod", "all_to_all_pod",
                    "all_reduce_quantized"):
        shape["slices"] = slices
    if verify:
        safety = verify_generators(
            lambda: build_generators(protocol, n, chunks=chunks,
                                     slices=slices),
            protocol=protocol, shape=shape,
        )
        if not safety.ok:
            raise AnalysisError(
                f"{protocol}: cannot trace an unsafe protocol — the "
                f"static verifier found: "
                + "; ".join(f.check for f in safety.findings)
            )
    costs, message, _pipeline = P._costs_for(protocol, shape,
                                             payload_bytes)
    replay = _TraceReplay(
        build_generators(protocol, n, chunks=chunks, slices=slices),
        C.Strategy(seed), costs,
    )
    replay.run()
    makespan = replay.elapsed_seconds()

    # -- the exactness contract, asserted at the source ----------------
    for r in range(replay.n):
        end = replay.rank_span_end(r)
        if end != replay.clock[r]:
            raise AnalysisError(
                f"{protocol} rank {r}: span tiling ends at {end!r} but "
                f"the simulator clock reads {replay.clock[r]!r} — the "
                f"exporter and the simulator disagree about the same "
                f"run"
            )
    span_makespan = max(
        (replay.rank_span_end(r) for r in range(replay.n)), default=0.0
    )
    if span_makespan != makespan:
        raise AnalysisError(
            f"{protocol}: max span end {span_makespan!r} != "
            f"elapsed_seconds() {makespan!r}"
        )

    events: List[dict] = [{
        "ph": "M", "pid": 0, "tid": 0, "name": "process_name",
        "args": {"name": f"smi_tpu {protocol}"},
    }]
    for r in range(replay.n):
        events.append({
            "ph": "M", "pid": 0, "tid": r, "name": "thread_name",
            "args": {"name": f"rank {r}"},
        })
    per_rank: List[dict] = []
    for r in range(replay.n):
        components = {
            tier: {k: round(v * 1e6, 6) for k, v in comps.items()}
            for (rank, tier), comps in replay._parts.items()
            if rank == r
        }
        per_rank.append({
            "rank": r,
            "clock_us": replay.clock[r] * 1e6,
            "span_end_us": replay.rank_span_end(r) * 1e6,
            "spans": len(replay.spans.get(r, ())),
            "components_us": components,
        })
        for span in replay.spans.get(r, ()):
            args = {
                "tier": span["tier"],
                "component": span["component"],
            }
            for key in ("producer", "waiter", "lane"):
                if key in span:
                    args[key] = span[key]
            events.append({
                "ph": "X", "pid": 0, "tid": r,
                "name": f"{span['component']} ({span['tier']})",
                "cat": span["component"],
                "ts": span["t0"] * 1e6,
                "dur": (span["t1"] - span["t0"]) * 1e6,
                "args": args,
            })
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "otherData": {
            "schema_version": TRACE_SCHEMA_VERSION,
            "trace_kind": "protocol",
            "protocol": protocol,
            "shape": dict(shape),
            "ranks": replay.n,
            "seed": seed,
            "payload_bytes": payload_bytes,
            "message_bytes": message,
            "makespan_us": makespan * 1e6,
            "span_makespan_us": span_makespan * 1e6,
            "per_rank": per_rank,
        },
    }


def trace_all(
    protocols: Optional[Sequence[str]] = None,
    payload_bytes: float = float(P.PERF_PAYLOAD_BYTES),
    seed: int = 0,
    verify: bool = True,
) -> List[dict]:
    """Trace every registered protocol (or the named subset) over the
    verifier's DEFAULT_SHAPES grid — the ``smi-tpu trace`` engine."""
    known = list(DEFAULT_SHAPES)
    if protocols is None:
        protocols = known
    else:
        unknown = [p for p in protocols if p not in known]
        if unknown:
            raise ValueError(
                f"unknown protocol(s) {unknown}; known: {known}"
            )
    out = []
    for protocol in protocols:
        for shape in DEFAULT_SHAPES[protocol]:
            out.append(trace_protocol(
                protocol, payload_bytes=payload_bytes, seed=seed,
                verify=verify, **shape
            ))
    return out


def trace_name(payload: dict) -> str:
    """Deterministic file stem for one trace payload:
    ``<protocol>_n<k>[_chunks<c>][_slices<s>]`` for protocol traces,
    ``serve_<label>_seed<s>`` for serving traces."""
    other = payload["otherData"]
    if other.get("trace_kind") == "serving":
        return f"serve_{other['label']}_seed{other['seed']}"
    shape = other["shape"]
    stem = f"{other['protocol']}_n{shape['n']}"
    for key in ("chunks", "slices"):
        if key in shape:
            stem += f"_{key}{shape[key]}"
    return stem


def trace_serving(span_report, seed: int = 0,
                  label: str = "selftest") -> dict:
    """Render a serving run's request span trees as a Chrome trace.

    Per-tenant track groups: each tenant is one Chrome-trace
    *process* (``pid``), each of its requests one *thread* (``tid`` =
    the per-tenant stream sequence), so Perfetto renders a serving
    run as grouped request spans rather than simulator primitives.
    Component spans carry their component as ``cat``; annotation
    spans (parks, sheds, retune-quiesce windows) carry
    ``annotation``. Timestamps are step-clock ticks rendered as
    microseconds — a logical clock, honestly labeled in ``otherData``.
    Deterministic: same seed, byte-identical file through
    :func:`trace_to_json_bytes`.
    """
    from smi_tpu.obs.spans import COMPONENTS, SpanReport

    if not isinstance(span_report, SpanReport):
        raise TypeError(
            f"trace_serving takes a SpanReport (build_spans' "
            f"output), got {type(span_report).__name__}"
        )
    tenants = sorted({t.tenant for t in span_report.requests.values()})
    pid_of = {tenant: i for i, tenant in enumerate(tenants)}
    events: List[dict] = []
    for tenant in tenants:
        events.append({
            "ph": "M", "pid": pid_of[tenant], "tid": 0,
            "name": "process_name",
            "args": {"name": f"tenant {tenant}"},
        })
    components_ticks = {c: 0 for c in COMPONENTS}
    makespan = 0
    delivered = shed = 0
    for key in sorted(span_report.requests):
        tree = span_report.requests[key]
        pid = pid_of[tree.tenant]
        if tree.completed is not None:
            delivered += 1
        elif tree.shed_reason is not None:
            shed += 1
        events.append({
            "ph": "M", "pid": pid, "tid": tree.seq,
            "name": "thread_name",
            "args": {"name": f"s{tree.seq} ({tree.qos}) "
                             f"{tree.outcome}"},
        })
        for span in tree.spans:
            cat = (span.component if span.kind == "component"
                   else "annotation")
            if span.kind == "component":
                components_ticks[span.component] += span.duration
            args = {"tenant": tree.tenant, "seq": tree.seq,
                    "qos": tree.qos, "kind": span.kind}
            args.update(span.detail)
            events.append({
                "ph": "X", "pid": pid, "tid": tree.seq,
                "name": span.component, "cat": cat,
                "ts": float(span.t0), "dur": float(span.duration),
                "args": args,
            })
            makespan = max(makespan, span.t1)
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "otherData": {
            "schema_version": TRACE_SCHEMA_VERSION,
            "trace_kind": "serving",
            "label": label,
            "seed": seed,
            "time_unit": "step-clock ticks (rendered as us)",
            "tenants": len(tenants),
            "requests": len(span_report.requests),
            "delivered": delivered,
            "shed": shed,
            "makespan_ticks": makespan,
            "components_ticks": {
                c: components_ticks[c] for c in COMPONENTS
                if components_ticks[c]
            },
            "total_events": span_report.total_events,
            "dropped_events": span_report.dropped_events,
        },
    }


def trace_to_json_bytes(payload: dict) -> bytes:
    """Deterministic serialization: sorted keys, fixed separators,
    trailing newline — same seed, byte-identical file (the
    determinism claim the tests pin)."""
    import json

    return (json.dumps(payload, sort_keys=True,
                       separators=(",", ": "), indent=1) + "\n").encode()


def validate_chrome_trace(payload: dict) -> None:
    """Pinned structural validation of an exported payload — the
    schema the tests (and any downstream consumer) can rely on.
    Raises ``ValueError`` naming the first violation."""
    if not isinstance(payload, dict):
        raise ValueError(f"trace payload must be a dict, got "
                         f"{type(payload).__name__}")
    for key in ("displayTimeUnit", "traceEvents", "otherData"):
        if key not in payload:
            raise ValueError(f"trace payload missing {key!r}")
    other = payload["otherData"]
    if other.get("schema_version") != TRACE_SCHEMA_VERSION:
        raise ValueError(
            f"trace schema_version {other.get('schema_version')!r} != "
            f"pinned {TRACE_SCHEMA_VERSION}"
        )
    kind = other.get("trace_kind", "protocol")
    if kind == "serving":
        from smi_tpu.obs.spans import COMPONENTS

        for key in ("label", "seed", "tenants", "requests",
                    "makespan_ticks", "components_ticks",
                    "dropped_events"):
            if key not in other:
                raise ValueError(f"otherData missing {key!r}")
        allowed_cats = tuple(COMPONENTS) + ("annotation",)
    elif kind == "protocol":
        for key in ("protocol", "shape", "ranks", "seed",
                    "makespan_us", "span_makespan_us", "per_rank"):
            if key not in other:
                raise ValueError(f"otherData missing {key!r}")
        allowed_cats = ("alpha", "beta", "serialization", "idle")
    else:
        raise ValueError(f"unknown trace_kind {kind!r}")
    events = payload["traceEvents"]
    if not isinstance(events, list) or not events:
        raise ValueError("traceEvents must be a non-empty list")
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph not in ("M", "X"):
            raise ValueError(f"traceEvents[{i}] has unknown ph {ph!r}")
        for key in ("pid", "tid", "name"):
            if key not in e:
                raise ValueError(f"traceEvents[{i}] missing {key!r}")
        if ph == "X":
            for key in ("ts", "dur", "cat", "args"):
                if key not in e:
                    raise ValueError(
                        f"traceEvents[{i}] (complete span) missing "
                        f"{key!r}"
                    )
            if e["dur"] < 0:
                raise ValueError(f"traceEvents[{i}] has negative dur")
            if e["cat"] not in allowed_cats:
                raise ValueError(
                    f"traceEvents[{i}] has unknown component "
                    f"{e['cat']!r}"
                )
