"""Ring collectives and neighbour streaming as explicit ICI RDMA kernels.

Reference parity: the CK_S/CK_R NoC moves packets neighbour-to-neighbour
over serial links with credit flow control (``codegen/templates/cks.cl``,
``ckr.cl``); chain/ring topologies are the routing substrate of the
microbenchmarks (``test/p2p/p2p.json``, ``bandwidth.json``). On TPU the
same neighbour streaming is ``pltpu.make_async_remote_copy`` over ICI,
double-buffered so the send of chunk *k* overlaps the integration of
chunk *k-1*.

This module is the framework's **"ring" collective backend**: the rooted
collectives (:mod:`smi_tpu.parallel.collectives`) and P2P channels
(:mod:`smi_tpu.parallel.channels`) dispatch here when called with
``backend="ring"`` — the explicit-schedule tier next to the default XLA
tier, mirroring how the reference's NoC *is* its data plane.

Flow control: a writer may only RDMA into a remote buffer slot after the
remote granted it (credit semaphore). Without this a fast rank could
clobber a slow neighbour's unconsumed chunk. The protocol is specified
and exhaustively schedule-tested as a pure-Python state machine in
:mod:`smi_tpu.parallel.credits`; the kernels below are its TPU
realization, and they run it in **every** mode:

- compiled on real TPU chips;
- interpreted on the CPU fake mesh via Pallas TPU interpret mode
  (``pltpu.InterpretParams``), which simulates the remote DMAs and
  semaphores with real cross-device semantics — the analog of the
  reference's strict-channel-depth emulator (``CMakeLists.txt:188-191``)
  — so the credit path is exercised by the regular test suite.

Credit accounting is exact: every grant is eventually consumed, so all
semaphores are zero at kernel exit (interpret mode verifies this and
reports leaks; leaked counts would poison the next collective reusing
the semaphores).

All kernels are written per-shard (called inside ``shard_map``). A ring
may span one mesh axis, several (flattened row-major, the communicator's
rank order), or a strict subset of the mesh's axes — pass ``mesh_axes``
so remote device ids resolve to the right global position (see
:func:`_logical_id_fn`).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from smi_tpu.ops.types import SmiOp
from smi_tpu.parallel.backend import combine_fn as _combine_fn
from smi_tpu.parallel.mesh import Communicator

#: ``collective_id`` base per kernel family. The barrier semaphore is
#: keyed by the collective id, so rings that may run concurrently must
#: not share one. The id space is ``family_base * STREAMS + stream``:
#: the *stream* slot comes from the program model's port allocation
#: (``ops/program.py``) — the runtime consumer of the port->stream deal:
#: collectives on distinct ports land on distinct streams and therefore
#: distinct semaphore domains, the TPU analog of the reference's
#: per-port support kernels owning their own hardware FIFOs
#: (``multi_collectives.cl``'s overlap guarantee).
RING_STREAMS = 4
_CID_ALL_GATHER = 0
_CID_ALL_REDUCE = 1
_CID_REDUCE_SCATTER = 2
_CID_NEIGHBOUR_STREAM = 3


def ring_collective_id(family_base: int, stream: int = 0) -> int:
    """Barrier-semaphore id for a ring collective on a given stream."""
    if not (0 <= stream < RING_STREAMS):
        raise ValueError(
            f"stream must be in [0, {RING_STREAMS}), got {stream}"
        )
    return family_base * RING_STREAMS + stream


def _compiler_params(family_base: int, stream: int, flow_control: bool):
    """Mosaic compiler params for a ring kernel.

    ``collective_id`` names the cross-device **barrier** semaphore — and
    only that. Mosaic rejects a kernel that declares a ``collective_id``
    but never touches the barrier ("collective_id has to be unspecified
    ... when not using a custom barrier"), so the id is attached only in
    flow-control mode, the only mode that opens the kernel with
    :func:`_neighbour_barrier`. The no-flow-control tier uses plain
    remote DMAs whose send/recv semaphores are kernel-local scratch and
    need no global id. (Caught by the AOT topology tier,
    ``tests/test_aot_tpu.py``: interpret mode accepted the stray id,
    real lowering does not.)
    """
    from smi_tpu.utils.compile import pallas_compiler_params

    if flow_control:
        return pallas_compiler_params(
            collective_id=ring_collective_id(family_base, stream),
            has_side_effects=True,
        )
    return pallas_compiler_params(has_side_effects=True)


#: ring axes: a single mesh axis name, or an ordered tuple of names the
#: ring spans (row-major rank significance, matching Communicator.rank)
RingAxes = Union[str, Tuple[str, ...]]
#: full mesh structure as ordered (name, size) pairs — required whenever
#: the ring does NOT span the whole mesh in mesh-axis order
MeshAxes = Optional[Tuple[Tuple[str, int], ...]]


def _normalize_axes(axis_name: RingAxes) -> Tuple[str, ...]:
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


def _ring_rank(ring_axes: Sequence[str], ring_sizes: dict):
    """Flattened rank over the ring axes (row-major, = Communicator.rank)."""
    r = lax.axis_index(ring_axes[0])
    for name in ring_axes[1:]:
        r = r * jnp.int32(ring_sizes[name]) + lax.axis_index(name)
    return jnp.int32(r)


def _logical_id_fn(ring_axes: Tuple[str, ...], mesh_axes: MeshAxes):
    """Map a flattened *ring* rank to the global LOGICAL device id.

    ``DeviceIdType.LOGICAL`` addresses the linearized position in the
    FULL shard_map mesh — not the position along the collective's own
    axis. A ring spanning only some axes of a larger mesh (e.g. the
    ``sy`` rings of a 2-D stencil mesh, one per row) must therefore
    rebuild the global id from the target's ring coordinates plus the
    caller's own coordinates on every non-ring axis. Passing the
    axis-local index instead signals a *different row's* device — the
    cross-ring semaphore corruption the interpret tier reports as
    "Semaphore ... non-zero at kernel exit" (and a silent data race on
    hardware). Identity when the ring spans the whole mesh in mesh
    order — the historical single-axis case.
    """
    if mesh_axes is None or tuple(n for n, _ in mesh_axes) == ring_axes:
        return lambda target: target
    sizes = dict(mesh_axes)
    missing = [n for n in ring_axes if n not in sizes]
    if missing:
        raise ValueError(
            f"ring axes {missing} not present in mesh axes "
            f"{[n for n, _ in mesh_axes]}"
        )

    def to_logical(target):
        coords = {}
        rem = target
        for name in reversed(ring_axes):
            s = jnp.int32(sizes[name])
            coords[name] = lax.rem(rem, s)
            rem = rem // s
        lid = jnp.int32(0)
        for name, s in mesh_axes:
            idx = coords.get(name)
            if idx is None:
                idx = lax.axis_index(name)
            lid = lid * jnp.int32(s) + jnp.int32(idx)
        return lid

    return to_logical



#: Bound on the memoized ring contexts. The working set of a real
#: program is a handful of (axes, n, mesh) triples; the bound exists so
#: a long-lived process sweeping many mesh shapes (the tuning sweep
#: driver, a notebook building meshes in a loop) cannot grow the memo
#: without limit — the r3 unbounded ``maxsize=None`` was a slow leak.
#: Eviction is LRU: a rebuilt context is correct (all inputs are in the
#: key), merely re-paid. Eviction/rehit-tested in tests/test_overlap.py.
RING_CONTEXT_CACHE_MAX = 64


@functools.lru_cache(maxsize=RING_CONTEXT_CACHE_MAX)
def _ring_context_cached(ring_axes: Tuple[str, ...], n: int,
                         mesh_axes: MeshAxes):
    if mesh_axes is not None:
        sizes = dict(mesh_axes)
        ring_sizes = {a: sizes[a] for a in ring_axes if a in sizes}
    else:
        ring_sizes = {ring_axes[0]: n} if len(ring_axes) == 1 else None
        if ring_sizes is None:
            raise ValueError(
                "multi-axis rings need mesh_axes=((name, size), ...) to "
                "derive per-axis extents and logical device ids"
            )
    return ring_axes, ring_sizes, _logical_id_fn(ring_axes, mesh_axes)


def _ring_context(axis_name: RingAxes, n: int, mesh_axes: MeshAxes):
    """(ring_axes, ring_sizes, to_logical) shared by the four wrappers.

    ``ring_sizes`` carries the per-axis extents a flattened multi-axis
    rank needs; for a single axis only ``n`` matters. ``mesh_axes``
    (ordered (name, size) of the FULL mesh) is REQUIRED whenever the
    ring does not span the whole mesh in mesh order — see
    :func:`_logical_id_fn`.

    Memoized per ``(ring axes, n, mesh_axes)`` — every traced
    collective call used to rebuild the context and its
    :func:`_logical_id_fn` closure (a multi-hop channel retraces this
    dozens of times per program); all inputs are hashable statics, the
    closure is trace-pure (it reads ``lax.axis_index`` of the CALLING
    trace), so one instance serves every retrace. Hit-counted by
    ``tests/test_overlap.py``.
    """
    return _ring_context_cached(
        _normalize_axes(axis_name), n,
        tuple(mesh_axes) if mesh_axes is not None else None,
    )


def _planned_ring_chunks(x: jax.Array, n: int) -> int:
    """Plan-engine default for the chunked ring all-reduce's pipeline
    depth: a measured cache entry for this device kind, else 1 (the
    unchunked kernel — today's behavior). Never errors."""
    try:
        from smi_tpu.tuning.engine import planned_chunks

        payload = int(x.size) * x.dtype.itemsize if x.ndim else 0
        return planned_chunks("ring_all_reduce", payload, n,
                              str(x.dtype))
    except Exception:
        return 1


def mesh_axes_of(comm: Communicator) -> Tuple[Tuple[str, int], ...]:
    """Full-mesh (name, size) pairs for a communicator's mesh — what the
    ring kernels need to resolve LOGICAL device ids when the ring spans
    a subset (or reordering) of the mesh axes."""
    return tuple(
        (name, int(comm.mesh.shape[name]))
        for name in comm.mesh.axis_names
    )


def _check_reducible(x: jax.Array, interpret: bool) -> None:
    """Reducing ring kernels cannot lower 8-bit arithmetic on TPU.

    Mosaic has no 8-bit vector ALU path ("Only vector<i16> and
    vector<i32> are supported, but got 'i8'") — caught by the AOT
    topology tier; interpret mode happily adds i8 and would hide the
    failure until a real pod hits it. Movement kernels (all_gather,
    neighbour_stream) carry 8-bit payloads fine; reductions must widen
    to >=16 bits or use the XLA tier (``lax.psum`` handles int8).
    """
    if not interpret and jnp.dtype(x.dtype).itemsize == 1:
        raise NotImplementedError(
            f"ring-tier reductions cannot compile for 8-bit dtype "
            f"{x.dtype} (Mosaic has no 8-bit vector arithmetic); widen "
            f"the payload to int16/int32 or use backend='xla'"
        )


def interpret_available() -> bool:
    """Whether this JAX can emulate the ring tier on CPU (Pallas TPU
    interpret mode with cross-device remote DMA semantics)."""
    return getattr(pltpu, "InterpretParams", None) is not None


def _interpret_arg(interpret: bool):
    """Pallas ``interpret=`` argument for the requested mode.

    ``True`` selects TPU interpret mode (``pltpu.InterpretParams``) rather
    than plain interpret mode: only the former simulates remote DMA +
    semaphore semantics across the fake-mesh devices, which the credit
    protocol needs. It also checks that semaphores drain to zero.

    A JAX without TPU interpret mode cannot emulate the ring tier on
    CPU at all (the plain interpreter rejects remote semaphore signals)
    — gate with a named error rather than an AttributeError mid-kernel.
    """
    if not interpret:
        return False
    params = getattr(pltpu, "InterpretParams", None)
    if params is None:
        raise NotImplementedError(
            "this JAX has no Pallas TPU interpret mode "
            "(pltpu.InterpretParams), which the ring tier's CPU "
            "emulation requires; run on real TPU chips or use "
            "backend='xla' — the protocol itself is still validated "
            "hardware-free by smi_tpu.parallel.credits/faults"
        )
    return params()


def _neighbour_barrier(me, n: int, to_logical):
    """Block until both ring neighbours entered the kernel, so no RDMA
    lands in a buffer that is still being initialized."""
    barrier = pltpu.get_barrier_semaphore()
    nn = jnp.int32(n)  # keep arithmetic in int32 even under jax_enable_x64
    left = lax.rem(me - 1 + nn, nn)
    right = lax.rem(me + 1, nn)
    pltpu.semaphore_signal(
        barrier, inc=1, device_id=to_logical(left),
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    pltpu.semaphore_signal(
        barrier, inc=1, device_id=to_logical(right),
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    pltpu.semaphore_wait(barrier, 2)


def _grant_slot(credit_sem, slot, me, n: int, to_logical):
    """Tell the left neighbour (the writer into our comm_buf) that
    ``slot`` is free to be overwritten."""
    left = lax.rem(me - 1 + jnp.int32(n), jnp.int32(n))
    pltpu.semaphore_signal(
        credit_sem.at[slot], inc=1, device_id=to_logical(left),
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )


def _lift_payload(x: jax.Array) -> jax.Array:
    """Give a 1-D payload a unit row axis so VMEM buffers built from it
    are >=3-D once a slot/unit axis is prepended.

    Mosaic tiles the trailing two dims of a VMEM buffer; a dynamic slice
    along the *sublane* dim of a 2-D buffer must be tile-aligned, which
    a traced slot index can never prove ("Slice shape along dimension 0
    must be aligned to tiling"). Every ring kernel therefore keeps its
    dynamically-indexed axes (double-buffer slots, gather units, chunk
    rows) strictly ahead of a >=2-D payload, where slicing is untiled
    and alignment-free — caught by the AOT topology tier
    (``tests/test_aot_tpu.py``); interpret mode has no tiling and hides
    this class of bug.
    """
    return x.reshape(1, -1) if x.ndim < 2 else x


#: Mosaic lane-tile width: the last dim of every VMEM buffer is padded
#: to a multiple of this, and the kernels' slot/unit slices must match
#: the padded width exactly.
_LANES = 128


def _pad_lanes(payload: jax.Array) -> Tuple[jax.Array, Tuple[int, int]]:
    """Zero-pad the payload's trailing tile dims to Mosaic alignment.

    Two constraints, both invisible to interpret mode and both caught
    by the AOT topology tier (``tests/test_aot_tpu.py``):

    - the lane (last) dim must be a multiple of 128, or the kernels'
      slot/unit slices are rejected ("Slice shape along dimension 2
      must be aligned to tiling (128)") — caught on the
      corner-complete halo program, whose extended slabs are
      ``W + 2*depth`` wide;
    - for sub-32-bit dtypes Mosaic packs ``32 / bitwidth`` sublanes
      per tile row, so the sublane (second-to-last) dim must be a
      multiple of that packing factor or the slot slice lands mid-tile
      — caught on ``ring_all_reduce_bf16``, whose lifted ``(1, W)``
      payload has a 1-sublane dim inside a 2-per-row bf16 tiling.

    The wrappers pad here and slice the result back to the logical
    shape, so callers may stream any payload shape/dtype. The padding
    is dead data: receivers only ever see their neighbours' equally-
    padded buffers, and the pad region is dropped before any reduction
    result is returned (safe for MAX/MIN, not just ADD).

    Returns ``(padded, (logical_sublanes, logical_width))``.
    """
    sub, width = payload.shape[-2], payload.shape[-1]
    packing = max(1, 32 // (jnp.dtype(payload.dtype).itemsize * 8))
    pad_sub = (-sub) % packing
    pad_w = (-width) % _LANES
    if pad_sub == 0 and pad_w == 0:
        return payload, (sub, width)
    widths = (
        [(0, 0)] * (payload.ndim - 2) + [(0, pad_sub)] + [(0, pad_w)]
    )
    return jnp.pad(payload, widths), (sub, width)


# ---------------------------------------------------------------------------
# All-gather
# ---------------------------------------------------------------------------


def _ring_all_gather_kernel(
    x_ref, o_ref, comm_buf, send_sem, recv_sem, credit_sem,
    *, ring_axes, ring_sizes, to_logical, n: int, flow_control: bool
):
    """Each device forwards the chunk it most recently received to its
    right neighbour; after n-1 steps everyone holds every chunk.

    Unit-block layout: ``x_ref`` is this rank's whole chunk as ONE unit
    ``(1, *payload)``, ``o_ref`` is ``(n, *payload)``, and all dynamic
    indexing (rank slots, double-buffer slots) happens on the untiled
    leading axes (see :func:`_lift_payload`).

    Protocol model: ``credits.all_gather_rank`` — slot 1 is granted at
    start (empty), and each slot is re-granted once its content has been
    forwarded onward (send complete), except on the final step, whose
    grant nobody would consume (credit balance must end at zero).
    """
    me = _ring_rank(ring_axes, ring_sizes)
    if flow_control:
        _neighbour_barrier(me, n, to_logical)
    o_ref[pl.ds(me, 1), ...] = x_ref[...]
    comm_buf[0] = x_ref[...]
    if flow_control:
        _grant_slot(credit_sem, 1, me, n, to_logical)  # slot 1 starts empty

    def step(s, _):
        nn = jnp.int32(n)
        src_rank = lax.rem(me - s - 1 + nn, nn)  # whose chunk arrives now
        dst = lax.rem(me + 1, nn)
        slot, nslot = s % 2, (s + 1) % 2
        if flow_control:
            # wait until the remote says its slot `nslot` is reusable
            pltpu.semaphore_wait(credit_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=to_logical(dst),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        if flow_control:
            # our slot has been sent onward: grant it upstream — except on
            # the last step, where no further send would consume the credit
            @pl.when(s < n - 2)
            def _():
                _grant_slot(credit_sem, slot, me, n, to_logical)
        o_ref[pl.ds(src_rank, 1), ...] = comm_buf[nslot]
        return ()

    lax.fori_loop(0, n - 1, step, ())


def ring_all_gather(
    x: jax.Array,
    axis_name: RingAxes,
    n: int,
    interpret: bool = False,
    flow_control: bool = True,
    stream: int = 0,
    mesh_axes: MeshAxes = None,
) -> jax.Array:
    """All-gather ``x`` (this shard's chunk) along a ring.

    Call inside ``shard_map``; returns the ``(n * chunk, ...)`` gathered
    array on every rank. Equivalent to ``lax.all_gather(..., tiled=True)``
    but with an explicit neighbour-ring schedule.
    """
    if n == 1:
        return x
    payload, logical = _pad_lanes(_lift_payload(x))
    xu = payload[None]  # (1, *payload): one unit per rank
    out_shape = jax.ShapeDtypeStruct((n,) + payload.shape, x.dtype)
    ring_axes, ring_sizes, to_logical = _ring_context(axis_name, n, mesh_axes)
    kernel = functools.partial(
        _ring_all_gather_kernel, ring_axes=ring_axes,
        ring_sizes=ring_sizes, to_logical=to_logical, n=n,
        flow_control=flow_control,
    )
    gathered = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, 1) + payload.shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=_compiler_params(
            _CID_ALL_GATHER, stream, flow_control,
        ),
        interpret=_interpret_arg(interpret),
    )(xu)
    if logical != payload.shape[-2:]:
        gathered = gathered[..., : logical[0], : logical[1]]
    return gathered.reshape((n * x.shape[0],) + x.shape[1:])


# ---------------------------------------------------------------------------
# All-reduce
# ---------------------------------------------------------------------------


def _ring_all_reduce_kernel(
    x_ref, o_ref, comm_buf, send_sem, recv_sem, credit_sem,
    *, ring_axes, ring_sizes, to_logical, n: int, op: SmiOp,
    flow_control: bool
):
    """Circulating-partial ring reduce: every rank simultaneously streams
    its running partial to its right neighbour and folds its own
    contribution into what arrives; after n-1 hops every rank holds the
    full reduction (each via a rotated association order)."""
    combine = _combine_fn(op)
    me = _ring_rank(ring_axes, ring_sizes)
    if flow_control:
        _neighbour_barrier(me, n, to_logical)
    comm_buf[0] = x_ref[...]
    if flow_control:
        _grant_slot(credit_sem, 1, me, n, to_logical)

    # After step s each rank's live slot holds the combine of the s+2
    # contributions x_{me-s-1} ... x_{me}; after n-1 steps that is the
    # full reduction on every rank simultaneously.
    def step(s, _):
        slot, nslot = s % 2, (s + 1) % 2
        dst = lax.rem(me + 1, jnp.int32(n))
        if flow_control:
            pltpu.semaphore_wait(credit_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=to_logical(dst),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        if flow_control:
            @pl.when(s < n - 2)
            def _():
                _grant_slot(credit_sem, slot, me, n, to_logical)
        comm_buf[nslot] = combine(comm_buf[nslot], x_ref[...])
        return ()

    lax.fori_loop(0, n - 1, step, ())
    final_slot = (n - 1) % 2
    o_ref[...] = comm_buf[final_slot]


def _ring_all_reduce_chunked_kernel(
    x_ref, o_ref, comm_buf, send_sem, recv_sem, credit_sem,
    *, ring_axes, ring_sizes, to_logical, n: int, op: SmiOp,
    chunks: int, flow_control: bool
):
    """Software-pipelined chunked ring reduce.

    The payload is split into ``chunks`` leading rows, each circulating
    the ring on its own double-buffered VMEM slot pair (flat slot layout
    ``2*c + parity``). Every ring step runs three phases over the static
    chunk unroll: START all chunk RDMAs, then COMBINE each arrival (so
    chunk ``c``'s fold runs while chunks ``c+1..`` are still in flight —
    the in-kernel rendition of SMI's asynchronicity degree), then
    re-grant the emptied slots once their onward sends completed. The
    per-chunk credit protocol is byte-identical to the unchunked
    kernel's; all chunks share this stream's barrier-semaphore domain.
    Protocol model: ``credits.all_reduce_chunked_rank`` (exhaustively
    schedule-fuzzed; the kernel mirrors it one primitive per yield).
    """
    combine = _combine_fn(op)
    me = _ring_rank(ring_axes, ring_sizes)
    if flow_control:
        _neighbour_barrier(me, n, to_logical)
    for c in range(chunks):
        comm_buf[2 * c] = x_ref[c]
        if flow_control:
            _grant_slot(credit_sem, 2 * c + 1, me, n, to_logical)

    def step(s, _):
        slot, nslot = s % 2, (s + 1) % 2
        dst = lax.rem(me + 1, jnp.int32(n))
        rdmas = []
        for c in range(chunks):
            if flow_control:
                pltpu.semaphore_wait(credit_sem.at[2 * c + nslot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[2 * c + slot],
                dst_ref=comm_buf.at[2 * c + nslot],
                send_sem=send_sem.at[2 * c + slot],
                recv_sem=recv_sem.at[2 * c + nslot],
                device_id=to_logical(dst),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdmas.append(rdma)
        for c, rdma in enumerate(rdmas):
            rdma.wait_recv()
            comm_buf[2 * c + nslot] = combine(
                comm_buf[2 * c + nslot], x_ref[c]
            )
        for c, rdma in enumerate(rdmas):
            rdma.wait_send()
            if flow_control:
                # the slot's content is fully sent onward: its writer
                # may reuse it — except on the last step, whose grant
                # nobody would consume (credit balance ends at zero)
                @pl.when(s < n - 2)
                def _():
                    _grant_slot(credit_sem, 2 * c + slot, me, n,
                                to_logical)
        return ()

    lax.fori_loop(0, n - 1, step, ())
    final_slot = (n - 1) % 2
    for c in range(chunks):
        o_ref[c] = comm_buf[2 * c + final_slot]


def ring_all_reduce(
    x: jax.Array,
    axis_name: RingAxes,
    n: int,
    op: Union[str, SmiOp] = SmiOp.ADD,
    interpret: bool = False,
    flow_control: bool = True,
    stream: int = 0,
    mesh_axes: MeshAxes = None,
    chunks: Optional[int] = None,
) -> jax.Array:
    """ADD/MAX/MIN all-reduce along a ring with explicit neighbour RDMA.

    Each rank's partial makes a full circuit: after ``n-1`` hops every
    rank has folded in all ``n`` contributions (each rank accumulates a
    rotated order, so float sums match up to reassociation).

    ``chunks > 1`` splits the payload's leading axis into that many
    pipeline rows, each on its own double-buffered VMEM slot pair, with
    chunk ``c+1``'s RDMA in flight while chunk ``c`` combines (see
    :func:`_ring_all_reduce_chunked_kernel`). Zero rows pad the split
    evenly; the pad is identical on every rank and sliced off the
    result, so it is safe for MAX/MIN as well as ADD. VMEM cost grows
    with ``chunks`` (2 slots per chunk) — keep it small (2-8).
    ``chunks=None`` (the default) consults the plan engine's cache for
    this device kind (:mod:`smi_tpu.tuning`), falling back to the
    unchunked kernel — explicit ints are used as-is.
    """
    if n == 1:
        return x
    _check_reducible(x, interpret)
    if chunks is None:
        chunks = _planned_ring_chunks(x, n)
    chunks = max(1, min(int(chunks), x.shape[0] if x.ndim else 1))
    ring_axes, ring_sizes, to_logical = _ring_context(axis_name, n, mesh_axes)
    if chunks > 1:
        rows = x.shape[0]
        per = -(-rows // chunks)
        pad = per * chunks - rows
        xp = x
        if pad:
            xp = jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)]
            )
        if x.ndim == 1:
            xu = xp.reshape(chunks, 1, per)
        else:
            xu = xp.reshape((chunks, per) + x.shape[1:])
        xu, logical = _pad_lanes(xu)
        block = xu.shape[1:]
        kernel = functools.partial(
            _ring_all_reduce_chunked_kernel, ring_axes=ring_axes,
            ring_sizes=ring_sizes, to_logical=to_logical, n=n,
            op=SmiOp.parse(op), chunks=chunks, flow_control=flow_control,
        )
        reduced = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(xu.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((2 * chunks,) + block, x.dtype),
                pltpu.SemaphoreType.DMA((2 * chunks,)),
                pltpu.SemaphoreType.DMA((2 * chunks,)),
                pltpu.SemaphoreType.REGULAR((2 * chunks,)),
            ],
            compiler_params=_compiler_params(
                _CID_ALL_REDUCE, stream, flow_control,
            ),
            interpret=_interpret_arg(interpret),
        )(xu)
        if logical != xu.shape[-2:]:
            reduced = reduced[..., : logical[0], : logical[1]]
        return reduced.reshape((chunks * per,) + x.shape[1:])[
            :rows
        ].reshape(x.shape)
    payload, logical = _pad_lanes(_lift_payload(x))
    kernel = functools.partial(
        _ring_all_reduce_kernel, ring_axes=ring_axes,
        ring_sizes=ring_sizes, to_logical=to_logical, n=n,
        op=SmiOp.parse(op), flow_control=flow_control,
    )
    reduced = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(payload.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2,) + payload.shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=_compiler_params(
            _CID_ALL_REDUCE, stream, flow_control,
        ),
        interpret=_interpret_arg(interpret),
    )(payload)
    if logical != payload.shape[-2:]:
        reduced = reduced[..., : logical[0], : logical[1]]
    return reduced.reshape(x.shape)


# ---------------------------------------------------------------------------
# Reduce-scatter
# ---------------------------------------------------------------------------


def _ring_reduce_scatter_kernel(
    x_ref, o_ref, comm_buf, send_sem, recv_sem, credit_sem,
    *, ring_axes, ring_sizes, to_logical, n: int, op: SmiOp,
    flow_control: bool
):
    """Standard ring reduce-scatter: at step ``s`` rank ``r`` sends the
    accumulated partial of chunk ``(r - s - 1) % n`` rightward and folds
    its own contribution into the arriving partial of chunk
    ``(r - s - 2) % n``; after ``n-1`` steps rank ``r`` holds the full
    reduction of chunk ``r``.

    Unit-block layout: ``x_ref`` is ``(n, *block)`` (one unit per
    destination rank), so block selection is a unit slice of the untiled
    leading axis (see :func:`_lift_payload`)."""
    combine = _combine_fn(op)
    me = _ring_rank(ring_axes, ring_sizes)
    nn = jnp.int32(n)

    def my_block(idx):
        return x_ref[pl.ds(idx, 1), ...]

    if flow_control:
        _neighbour_barrier(me, n, to_logical)
    comm_buf[0] = my_block(lax.rem(me - 1 + nn, nn))
    if flow_control:
        _grant_slot(credit_sem, 1, me, n, to_logical)

    def step(s, _):
        slot, nslot = s % 2, (s + 1) % 2
        dst = lax.rem(me + 1, nn)
        if flow_control:
            pltpu.semaphore_wait(credit_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=to_logical(dst),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        if flow_control:
            @pl.when(s < n - 2)
            def _():
                _grant_slot(credit_sem, slot, me, n, to_logical)
        # arriving partial is for chunk (me - s - 2) % n; fold our share in
        idx = lax.rem(me - s - 2 + 2 * nn, nn)
        comm_buf[nslot] = combine(comm_buf[nslot], my_block(idx))
        return ()

    lax.fori_loop(0, n - 1, step, ())
    o_ref[...] = comm_buf[(n - 1) % 2]


def ring_reduce_scatter(
    x: jax.Array,
    axis_name: RingAxes,
    n: int,
    op: Union[str, SmiOp] = SmiOp.ADD,
    interpret: bool = False,
    flow_control: bool = True,
    stream: int = 0,
    mesh_axes: MeshAxes = None,
) -> jax.Array:
    """Reduce-scatter along a ring: rank ``r`` returns the reduction of
    every rank's ``r``-th leading block of ``x``.

    ``x.shape[0]`` must be divisible by ``n``; the result has leading
    dimension ``x.shape[0] // n``. Equivalent to ``lax.psum_scatter(...,
    tiled=True)`` (for ADD) with an explicit neighbour-ring schedule.
    """
    if x.shape[0] % n != 0:
        raise ValueError(
            f"reduce-scatter leading dim {x.shape[0]} not divisible by "
            f"ring size {n}"
        )
    if n == 1:
        return x
    _check_reducible(x, interpret)
    chunk = x.shape[0] // n
    if x.ndim == 1:
        xu = x.reshape(n, 1, chunk)
    else:
        xu = x.reshape((n, chunk) + x.shape[1:])
    xu, logical = _pad_lanes(xu)
    block = xu.shape[1:]
    out_shape = jax.ShapeDtypeStruct((1,) + block, x.dtype)
    ring_axes, ring_sizes, to_logical = _ring_context(axis_name, n, mesh_axes)
    kernel = functools.partial(
        _ring_reduce_scatter_kernel, ring_axes=ring_axes,
        ring_sizes=ring_sizes, to_logical=to_logical, n=n,
        op=SmiOp.parse(op), flow_control=flow_control,
    )
    scattered = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, 1) + block, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=_compiler_params(
            _CID_REDUCE_SCATTER, stream, flow_control,
        ),
        interpret=_interpret_arg(interpret),
    )(xu)
    if logical != xu.shape[-2:]:
        scattered = scattered[..., : logical[0], : logical[1]]
    return scattered.reshape((chunk,) + x.shape[1:])


# ---------------------------------------------------------------------------
# Neighbour P2P streaming
# ---------------------------------------------------------------------------


def _neighbour_stream_kernel(
    x_ref, o_ref, comm_buf, send_sem, recv_sem, credit_sem,
    *, ring_axes, ring_sizes, to_logical, n: int, chunks: int,
    direction: int, flow_control: bool
):
    """Stream ``chunks`` chunks one hop around the ring, double-buffered.

    Every rank simultaneously sends its chunk ``c`` to ``me + direction``
    while receiving chunk ``c`` from ``me - direction`` — the TPU analog
    of the reference's Push loop feeding a neighbour's Pop loop through
    the NoC (``templates/push.cl``/``pop.cl``), with the send of chunk
    ``c`` overlapping the receive/consume of the same step.

    Credit protocol (see :mod:`smi_tpu.parallel.credits`): both slots
    start empty (implicitly granted), so waits begin at chunk 2; the
    receiver re-grants a slot to its upstream after copying it out, except
    for the final two chunks whose grants nobody would consume.
    """
    me = _ring_rank(ring_axes, ring_sizes)
    nn = jnp.int32(n)
    dst = lax.rem(me + direction + 2 * nn, nn)
    upstream = lax.rem(me - direction + 2 * nn, nn)
    if flow_control:
        _neighbour_barrier(me, n, to_logical)

    def step(c, _):
        slot = c % 2
        if flow_control:
            # both slots start granted (empty); wait from chunk 2 on
            @pl.when(c >= 2)
            def _():
                pltpu.semaphore_wait(credit_sem.at[slot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref.at[c],
            dst_ref=comm_buf.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=to_logical(dst),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait_recv()  # chunk c arrived from upstream into our slot
        o_ref[c] = comm_buf[slot]
        if flow_control:
            # slot consumed: grant it back to the upstream writer, unless
            # no later chunk would wait on the credit
            @pl.when(c + 2 < chunks)
            def _():
                pltpu.semaphore_signal(
                    credit_sem.at[slot], inc=1,
                    device_id=to_logical(upstream),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
        rdma.wait_send()
        return ()

    lax.fori_loop(0, chunks, step, ())


def neighbour_stream(
    x: jax.Array,
    axis_name: RingAxes,
    n: int,
    direction: int = 1,
    interpret: bool = False,
    flow_control: bool = True,
    stream: int = 0,
    mesh_axes: MeshAxes = None,
) -> jax.Array:
    """Stream ``x`` chunk-by-chunk to the ring neighbour ``me+direction``.

    ``x`` has shape ``(chunks, ...)`` — one leading row per chunk; each
    chunk is one bounded in-flight unit (the channel's asynchronicity
    degree decides the chunking, ``channels.py``). Returns the upstream
    neighbour's ``x``. Multi-hop P2P transfers compose this hop-by-hop,
    exactly as the reference NoC forwards packets through intermediate
    devices (``ckr.cl:50-60``).
    """
    if direction not in (1, -1):
        raise ValueError(f"direction must be +1 or -1, got {direction}")
    if n == 1:
        return x
    chunks = x.shape[0]
    # per-chunk payloads must be >=2-D so the chunk/slot axes stay
    # untiled (see _lift_payload), and lane-aligned (see _pad_lanes)
    xu = x.reshape(chunks, 1, -1) if x.ndim < 3 else x
    xu, logical = _pad_lanes(xu)
    ring_axes, ring_sizes, to_logical = _ring_context(axis_name, n, mesh_axes)
    kernel = functools.partial(
        _neighbour_stream_kernel, ring_axes=ring_axes,
        ring_sizes=ring_sizes, to_logical=to_logical, n=n,
        chunks=chunks, direction=direction, flow_control=flow_control,
    )
    streamed = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(xu.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2,) + xu.shape[1:], x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=_compiler_params(
            _CID_NEIGHBOUR_STREAM, stream, flow_control,
        ),
        interpret=_interpret_arg(interpret),
    )(xu)
    if logical != xu.shape[-2:]:
        streamed = streamed[..., : logical[0], : logical[1]]
    return streamed.reshape(x.shape)


# ---------------------------------------------------------------------------
# Jitted wrappers
# ---------------------------------------------------------------------------


def make_ring_all_gather(comm: Communicator, interpret: bool = False):
    """Jitted wrapper: sharded input chunks → replicated gathered array."""
    axis = comm.axis_names if len(comm.axis_names) > 1 else comm.axis_names[0]
    n = comm.size
    mesh_axes = mesh_axes_of(comm)

    def shard(x):
        return ring_all_gather(x, axis, n, interpret=interpret,
                               mesh_axes=mesh_axes)

    return jax.jit(
        jax.shard_map(
            shard, mesh=comm.mesh, in_specs=P(axis), out_specs=P(None),
            check_vma=False,
        )
    )


def make_ring_all_reduce(comm: Communicator, interpret: bool = False,
                         op: Union[str, SmiOp] = SmiOp.ADD):
    axis = comm.axis_names if len(comm.axis_names) > 1 else comm.axis_names[0]
    n = comm.size
    mesh_axes = mesh_axes_of(comm)

    def shard(x):
        if x.shape[0] != 1:
            raise ValueError(
                f"make_ring_all_reduce expects one row per shard (global "
                f"leading dim == comm size {n}); got local shape {x.shape}"
            )
        return ring_all_reduce(x[0], axis, n, op=op, interpret=interpret,
                               mesh_axes=mesh_axes)[None]

    return jax.jit(
        jax.shard_map(
            shard, mesh=comm.mesh, in_specs=P(axis), out_specs=P(axis),
            check_vma=False,
        )
    )


def make_ring_reduce_scatter(comm: Communicator, interpret: bool = False,
                             op: Union[str, SmiOp] = SmiOp.ADD):
    """Jitted wrapper: replicated (n*chunk, ...) input → sharded chunks."""
    axis = comm.axis_names if len(comm.axis_names) > 1 else comm.axis_names[0]
    n = comm.size
    mesh_axes = mesh_axes_of(comm)

    def shard(x):
        return ring_reduce_scatter(x, axis, n, op=op, interpret=interpret,
                                   mesh_axes=mesh_axes)

    return jax.jit(
        jax.shard_map(
            shard, mesh=comm.mesh, in_specs=P(None), out_specs=P(axis),
            check_vma=False,
        )
    )
