"""All-to-all protocol family: transport, analysis, plan engine, CLI.

The first non-ring/tree traffic shape, proven at every tier:

- the three credits-simulator state machines (pairwise / Bruck /
  two-tier pod) deliver correctly under random, adversarial, and
  exhaustive schedules; flow control OFF admits a reachable clobber
  (the credits' existence proof on a rotating-partner schedule);
- the fault matrix holds: in-flight damage is a named IntegrityError
  on framed transport and provable SilentCorruption on bare transport,
  dropped grants deadlock, delays are tolerated, DCN cuts are named;
- the simulated wall-clock comparisons are the acceptance numbers:
  the two-tier variant beats flat pairwise on a 2x2 pod at >= 1 MiB
  per-destination blocks, and Bruck beats pairwise on small blocks
  while losing on large ones;
- the consolidated registry (credits.all_protocol_registries) is the
  one source of truth the fault layer re-exports and every analysis
  tier enumerates — and the seed-pinned chaos draw set (PROTOCOLS)
  did not grow;
- the XLA-tier ``all_to_all`` is bit-identical across all three
  algorithms and dtypes, resolves ``algorithm=None`` through the
  env -> cache -> model -> heuristic ladder, and compiles untuned
  byte-identically to the explicit pairwise form;
- degenerate shapes hold: n=1 is the identity, empty per-destination
  payloads survive the framing, uneven per-rank counts reassemble.
"""

import json
import warnings

import pytest

from smi_tpu.parallel import credits as C
from smi_tpu.parallel import faults as F
from smi_tpu.parallel.routing import alltoall_pairwise_schedule
from smi_tpu.tuning import cost_model as cm
from smi_tpu.tuning.cache import CacheEntry, PlanCache
from smi_tpu.tuning.engine import (
    ALLTOALL_MODEL_MARGIN,
    PlanEngine,
    set_engine,
)
from smi_tpu.tuning.plan import PlanKey, payload_bucket

pytestmark = pytest.mark.alltoall


@pytest.fixture(autouse=True)
def _fresh_engine():
    yield
    set_engine(None)


def _engine(entries=None):
    cache = PlanCache()
    for key, knobs in (entries or {}).items():
        cache.put(key, CacheEntry(knobs, cost_us=10.0,
                                  provenance="test"))
    return PlanEngine(cache=cache, device_kind="testdev")


# ---------------------------------------------------------------------------
# 1. Protocol state machines under the simulator
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 8])
def test_pairwise_delivery_random_schedules(n):
    for seed in range(8):
        C.simulate_all_to_all(n, C.Strategy(seed))


@pytest.mark.parametrize("n", [1, 2, 4, 8])
def test_bruck_delivery_random_schedules(n):
    for seed in range(8):
        C.simulate_all_to_all(n, C.Strategy(seed), variant="bruck")


@pytest.mark.parametrize("shape", [(1, 1), (1, 3), (3, 1), (2, 2),
                                   (2, 3), (3, 2)])
def test_pod_delivery_random_schedules(shape):
    slices, per_slice = shape
    for seed in range(8):
        C.simulate_all_to_all_pod(slices, per_slice, C.Strategy(seed))


def test_adversarial_schedules_hold():
    for n in (3, 4, 5):
        for seed in range(6):
            C.simulate_all_to_all(n, C.DelayDmaStrategy(seed))
            C.simulate_all_to_all(n, C.FavourRankStrategy(0, seed))
    for seed in range(6):
        C.simulate_all_to_all(4, C.DelayDmaStrategy(seed),
                              variant="bruck")
        C.simulate_all_to_all_pod(2, 2, C.FavourSetStrategy({0, 1},
                                                            seed))


def test_exhaustive_tiny_spaces():
    """Every schedule of the tiniest instances holds — the same
    exhaustive bar the ring protocols clear."""
    assert C.explore_all_schedules(
        lambda: C.all_to_all_generators(2)
    ) > 1
    assert C.explore_all_schedules(
        lambda: C.all_to_all_generators(2, "bruck")
    ) > 1
    assert C.explore_all_schedules(
        lambda: C.all_to_all_pod_generators(2, 1)
    ) > 1
    assert C.explore_all_schedules(
        lambda: C.all_to_all_pod_generators(1, 2)
    ) > 1


def test_budgeted_dfs_on_larger_spaces():
    """Beyond-exhaustive spaces: the first N schedules in DFS order
    hold, loudly truncated (the allow_budget honesty contract)."""
    for make in (
        lambda: C.all_to_all_generators(3),
        lambda: C.all_to_all_generators(4, "bruck"),
        lambda: C.all_to_all_pod_generators(2, 2),
    ):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            count = C.explore_all_schedules(make, max_schedules=4000,
                                            allow_budget=True)
        assert count >= 4000


def test_flow_control_off_admits_a_clobber():
    """The credits' existence proof on the rotating-partner schedule:
    slot reuse starts at n=4 (step 3 reuses step 1's slot), and with
    flow control off some schedule clobbers it."""
    with pytest.raises(C.ProtocolError):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            C.explore_all_schedules(
                lambda: C.all_to_all_generators(4, flow_control=False),
                max_schedules=200_000, allow_budget=True,
            )


def test_identity_shapes():
    """n=1 (and the 1x1 pod) deliver the local blocks untouched."""
    out = C.RingSimulator(C.all_to_all_generators(1),
                          C.Strategy(0)).run()
    assert out == [{0: "b0->0"}]
    out = C.RingSimulator(C.all_to_all_pod_generators(1, 1),
                          C.Strategy(0)).run()
    assert out == [{("slice", 0): ("b0->0",)}]


def test_empty_per_destination_payloads_survive_the_framing():
    """A tenant routing zero tokens to an expert is an EMPTY block,
    not a missing one: empty payloads move, verify, and deliver —
    and in-flight damage to one is still a named IntegrityError."""
    n = 3

    def gens():
        return [
            C.all_to_all_rank(r, n, ["" for _ in range(n)])
            for r in range(n)
        ]

    outputs = C.RingSimulator(
        [C.verified_steps(g, r) for r, g in enumerate(gens())],
        C.Strategy(0),
    ).run()
    for r in range(n):
        assert outputs[r] == {src: "" for src in range(n)}
    plan = F.FaultPlan.single(F.BitFlipPayload(0, nth=0))
    with pytest.raises(C.IntegrityError) as err:
        C.RingSimulator(
            [C.verified_steps(g, r) for r, g in enumerate(gens())],
            C.Strategy(0), faults=plan,
        ).run()
    assert err.value.kind == "checksum"


def test_uneven_blocks_deliver():
    """Uneven per-destination splits (with remainder): payload sizes
    per (src, dst) pair differ and every one still lands at its
    destination intact."""
    n = 4

    def block(src, dst):
        return f"b{src}->{dst}" * ((src + dst) % 3)   # some empty

    gens = [
        C.all_to_all_rank(r, n, [block(r, d) for d in range(n)])
        for r in range(n)
    ]
    outputs = C.RingSimulator(gens, C.Strategy(1)).run()
    for r in range(n):
        assert outputs[r] == {src: block(src, r) for src in range(n)}


# ---------------------------------------------------------------------------
# 2. Fault matrix
# ---------------------------------------------------------------------------

A2A = ("all_to_all", "all_to_all_bruck", "all_to_all_pod")


@pytest.mark.parametrize("protocol", A2A)
@pytest.mark.parametrize("fault_class", F.INTEGRITY_FAULT_CLASSES)
def test_integrity_faults_detected_framed(protocol, fault_class):
    for seed in range(4):
        plan = F.FaultPlan.random(fault_class, 4, seed)
        verdict = F.run_under_faults(protocol, 4, plan, verified=True)
        assert verdict.detected, (protocol, fault_class, seed)
        assert verdict.error_name == "IntegrityError"


@pytest.mark.parametrize("protocol", A2A)
def test_bare_transport_is_silent_corruption(protocol):
    """The framing's existence proof, per variant: the same bit flip
    on bare transport completes with wrong delivery."""
    plan = F.FaultPlan.random("bit_flip_payload", 4, 3)
    with pytest.raises(F.SilentCorruption):
        F.run_under_faults(protocol, 4, plan, verified=False)


def test_dropped_grant_deadlocks_the_credited_variants():
    for protocol in ("all_to_all", "all_to_all_bruck"):
        plan = F.FaultPlan.single(F.DroppedGrant(0, nth=0))
        verdict = F.run_under_faults(protocol, 4, plan)
        assert verdict.detected
        assert verdict.error_name == "DeadlockError"
        assert verdict.error.state is not None


def test_delays_and_down_links():
    for protocol in A2A:
        v = F.run_under_faults(
            protocol, 4,
            F.FaultPlan.single(F.DelayedDma(1, nth=0, hold=50)),
        )
        assert v.tolerated, protocol
        v = F.run_under_faults(
            protocol, 4, F.FaultPlan.single(F.DownLink(0, 1)),
        )
        assert v.detected and v.error_name == "DeadlockError", protocol


def test_dcn_faults_on_the_pod_variant():
    """The DCN tier's characteristic faults against the two-tier
    exchange: a severed slice pair deadlocks with a named dump, a
    cross-slice-only delay is tolerated."""
    v = F.run_under_faults(
        "all_to_all_pod", 4,
        F.FaultPlan.single(F.DcnLinkDown(0, 1, per_slice=2)),
    )
    assert v.detected and v.error_name == "DeadlockError"
    v = F.run_under_faults(
        "all_to_all_pod", 4,
        F.FaultPlan.single(F.DcnDelay(0, nth=0, hold=60, per_slice=2)),
    )
    assert v.tolerated


def test_bruck_refuses_non_power_of_two_loudly():
    with pytest.raises(ValueError, match="power-of-two"):
        F.run_under_faults("all_to_all_bruck", 6, None)
    with pytest.raises(ValueError, match="power-of-two"):
        C.all_to_all_generators(6, variant="bruck")
    with pytest.raises(ValueError, match="power-of-two"):
        cm.bruck_alltoall_us(1 << 20, 6, cm.LinkModel())


# ---------------------------------------------------------------------------
# 3. Registry consolidation
# ---------------------------------------------------------------------------


def test_fault_layer_reexports_the_consolidated_registry():
    """faults.* are the SAME tuple objects credits declares — one
    source of truth, no drift possible."""
    assert F.PROTOCOLS is C.PROTOCOLS
    assert F.CHUNKED_PROTOCOLS is C.CHUNKED_PROTOCOLS
    assert F.POD_PROTOCOLS is C.POD_PROTOCOLS
    assert F.ALLTOALL_PROTOCOLS is C.ALLTOALL_PROTOCOLS
    assert F.QUANTIZED_PROTOCOLS is C.QUANTIZED_PROTOCOLS
    flat = C.registered_protocols()
    assert flat == (F.PROTOCOLS + F.CHUNKED_PROTOCOLS
                    + F.POD_PROTOCOLS + F.ALLTOALL_PROTOCOLS
                    + F.QUANTIZED_PROTOCOLS)
    # the seed-pinned chaos draw set did not grow
    assert C.PROTOCOLS == ("all_gather", "all_reduce",
                           "reduce_scatter", "neighbour_stream")
    assert not set(C.ALLTOALL_PROTOCOLS) & set(C.PROTOCOLS)


def test_unknown_protocol_error_names_the_registry():
    with pytest.raises(ValueError, match="all_to_all_bruck"):
        F.run_under_faults("ghost", 4, None)


# ---------------------------------------------------------------------------
# 4. Static verifier differential (mutants on the new family)
# ---------------------------------------------------------------------------


def test_mutants_convict_on_the_pairwise_exchange():
    """dropped_wait starves the schedule (static AND dynamic agree);
    reused_slot aliases the double buffer (a race the fuzzer sees as
    a clobber)."""
    from smi_tpu import analysis as A

    rep = A.verify_generators(
        lambda: A.mutant_generators("all_to_all", 3,
                                    mutant="dropped_wait"),
        protocol="all_to_all[dropped_wait]",
    )
    assert not rep.ok
    # the dropped grant is both a conservation deficit (one unit short)
    # and a guaranteed starvation — both named
    assert "deadlock" in {f.check for f in rep.findings}
    with pytest.raises(C.DeadlockError):
        C.RingSimulator(
            A.mutant_generators("all_to_all", 3, mutant="dropped_wait"),
            C.Strategy(0), coarse=True,
        ).run()

    rep = A.verify_generators(
        lambda: A.mutant_generators("all_to_all", 4,
                                    mutant="reused_slot"),
        protocol="all_to_all[reused_slot]",
    )
    assert not rep.ok
    assert "slot-race" in {f.check for f in rep.findings}


# ---------------------------------------------------------------------------
# 5. Wall-clock acceptance
# ---------------------------------------------------------------------------


def test_two_tier_beats_flat_pairwise_on_a_2x2_pod(monkeypatch):
    """THE acceptance number: at >= 1 MiB per-destination blocks the
    two-tier exchange beats flat pairwise on a 2x2 pod — the DCN
    alphas drop from (n - per_slice) to (slices - 1) per rank, and
    the slow tier is crossed with aggregated bundles."""
    monkeypatch.delenv(cm.DCN_BETA_ENV, raising=False)
    for block in (1 << 20, 4 << 20):
        rep = C.alltoall_wallclock_comparison(2, 2, float(block))
        assert rep["hierarchical_s"] < rep["pairwise_s"], rep
    rep = C.alltoall_wallclock_comparison(2, 2, float(1 << 20))
    assert round(rep["pairwise_s"] * 1e6, 1) == 1548.6
    assert round(rep["hierarchical_s"] * 1e6, 1) == 957.4


def test_bruck_beats_pairwise_small_and_loses_large(monkeypatch):
    """The Bruck crossover the plan engine's model layer prices:
    alpha-bound small blocks go log-step, volume-bound large blocks
    go pairwise."""
    monkeypatch.delenv(cm.DCN_BETA_ENV, raising=False)
    small = C.alltoall_variant_wallclocks(8, 1024.0)
    assert small["bruck_s"] < small["pairwise_s"], small
    big = C.alltoall_variant_wallclocks(8, float(4 << 20))
    assert big["pairwise_s"] < big["bruck_s"], big


def test_wallclock_comparisons_are_deterministic(monkeypatch):
    monkeypatch.delenv(cm.DCN_BETA_ENV, raising=False)
    a = C.alltoall_wallclock_comparison(2, 3, float(1 << 18))
    b = C.alltoall_wallclock_comparison(2, 3, float(1 << 18))
    assert a == b


# ---------------------------------------------------------------------------
# 6. The pairwise step schedule (routing/mesh exposure)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8])
def test_pairwise_schedule_covers_every_ordered_pair_once(n):
    steps = alltoall_pairwise_schedule(n)
    assert len(steps) == n - 1
    seen = set()
    for step in steps:
        srcs = [s for s, _ in step]
        dsts = [d for _, d in step]
        # within a step each rank sends once and receives once
        assert sorted(srcs) == list(range(n))
        assert sorted(dsts) == list(range(n))
        seen.update(step)
    assert seen == {(s, d) for s in range(n) for d in range(n)
                    if s != d}


def test_pairwise_schedule_matches_the_protocol():
    """The exposed schedule IS the protocol's rotation: step s sends
    to (g + s) % n."""
    n = 5
    steps = alltoall_pairwise_schedule(n)
    for s, step in enumerate(steps, start=1):
        assert step == [(g, (g + s) % n) for g in range(n)]


def test_schedule_rejects_zero_ranks():
    with pytest.raises(ValueError):
        alltoall_pairwise_schedule(0)


# ---------------------------------------------------------------------------
# 7. Cost model + plan engine
# ---------------------------------------------------------------------------


def test_candidate_table_orders_by_modeled_cost():
    link = cm.LinkModel()
    small = cm.alltoall_candidates(4 << 10, cm.TopologySpec(n=8),
                                   link=link)
    assert small[0].name == "bruck"   # alpha-bound: log-step wins
    large = cm.alltoall_candidates(64 << 20, cm.TopologySpec(n=8),
                                   link=link)
    assert large[0].name == "pairwise"   # volume-bound
    assert not small.excluded and not large.excluded


def test_candidate_table_excludes_bruck_loudly_off_pow2():
    cands = cm.alltoall_candidates(1 << 20, cm.TopologySpec(n=6))
    assert [c.name for c in cands] == ["pairwise"]
    assert len(cands.excluded) == 1
    assert cands.excluded[0].name == "bruck"
    assert "power of two" in cands.excluded[0].note


def test_candidate_table_prices_the_pod():
    topo = cm.TopologySpec(n=4, inner=2, outer=2)
    cands = cm.alltoall_candidates(4 << 20, topo)
    names = [c.name for c in cands]
    assert set(names) == {"pairwise", "bruck", "hierarchical"}
    assert cands[0].name == "hierarchical"
    assert cm.alltoall_advantage(4 << 20, topo) > 1.0
    # off-pod: never advised
    assert cm.alltoall_advantage(4 << 20, cm.TopologySpec(n=4)) == 0.0


def test_engine_ladder_env_cache_model_heuristic():
    topo8 = cm.TopologySpec(n=8)
    eng = _engine()
    # heuristic: inside the confidence band the fused pairwise wins
    assert eng.use_alltoall(1 << 20, topo8) == ("pairwise", "heuristic")
    # env override decides alone
    assert eng.use_alltoall(1 << 20, topo8, algorithm="bruck") == (
        "bruck", "env",
    )
    # model: (n-1)/log2(n) crosses the 4x margin at n=32, alpha-bound
    topo32 = cm.TopologySpec(n=32)
    algo, layer = eng.use_alltoall(4 << 10, topo32)
    assert (algo, layer) == ("bruck", "model")
    # cache outranks the model
    key = PlanKey("all_to_all", payload_bucket(4 << 10), "float32",
                  "testdev", "n32")
    eng = _engine({key: {"algorithm": "pairwise"}})
    assert eng.use_alltoall(4 << 10, topo32) == ("pairwise", "cache")
    # the Bruck comparison also applies ON a pod when the two-tier
    # form did not confidently win (review fix: the flat candidates
    # are priced at the DCN tier that gates them there)
    pod32 = cm.TopologySpec(n=32, inner=16, outer=2)
    assert _engine().use_alltoall(4 << 10, pod32) == ("bruck", "model")


def test_engine_cache_entry_falls_through_on_impossible_shapes():
    """A cache entry naming an algorithm the current shape cannot run
    (bruck on n=6) is skipped, not an error — and the fall-through
    lands on the heuristic, never a silent bruck."""
    key = PlanKey("all_to_all", payload_bucket(1 << 20), "float32",
                  "testdev", "n6")
    eng = _engine({key: {"algorithm": "bruck"}})
    assert eng.use_alltoall(1 << 20, cm.TopologySpec(n=6)) == (
        "pairwise", "heuristic",
    )


def test_alltoall_plan_names_exclusions_and_provenance():
    eng = _engine()
    plan = eng.alltoall_plan(1 << 20, cm.TopologySpec(n=6))
    assert plan.knobs["algorithm"] == "pairwise"
    assert plan.decided_by["algorithm"] == "heuristic"
    assert any("excluded bruck" in r for r in plan.rationale)
    key = PlanKey("all_to_all", payload_bucket(1 << 20), "float32",
                  "testdev", "n8")
    eng = _engine({key: {"algorithm": "bruck"}})
    plan = eng.alltoall_plan(1 << 20, cm.TopologySpec(n=8))
    assert plan.knobs["algorithm"] == "bruck"
    assert plan.decided_by["algorithm"] == "cache"
    bruck_row = next(c for c in plan.candidates if c.name == "bruck")
    assert bruck_row.measured_us == 10.0


def test_planned_alltoall_never_raises(monkeypatch):
    from smi_tpu.tuning import engine as E

    monkeypatch.setattr(E, "get_engine",
                        lambda: (_ for _ in ()).throw(RuntimeError()))
    assert E.planned_alltoall(1 << 20, 8, 8, 1, "float32") == "pairwise"
    assert E.planned_alltoall(1 << 20, 8, 8, 1, "float32",
                              algorithm="bruck") == "bruck"


def test_explain_text_covers_alltoall():
    eng = _engine()
    text = eng.explain_text("all_to_all", n=8)
    assert "pairwise" in text and "bruck" in text
    assert "[heuristic]" in text
    text = eng.explain_text("alltoall", n=6)
    assert "excluded bruck" in text
    text = eng.explain_text("all_to_all", n=8, slices=2)
    assert "hierarchical" in text and "ICI x DCN pod" in text
    with pytest.raises(ValueError, match="do not split"):
        eng.explain_text("all_to_all", n=7, slices=2)


# ---------------------------------------------------------------------------
# 8. The XLA-tier collective (fake mesh, 8 CPU devices)
# ---------------------------------------------------------------------------

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
from jax.sharding import PartitionSpec as P                 # noqa: E402

import smi_tpu.__main__ as cli                              # noqa: E402
from smi_tpu.parallel import collectives as coll            # noqa: E402
from smi_tpu.parallel.mesh import (                         # noqa: E402
    make_communicator,
    make_hybrid_communicator,
)

DTYPES = [jnp.float32, jnp.bfloat16, jnp.int32]
COUNTS = [1, 3, 7]   # odd per-destination counts: uneven tails


def _run_alltoall(comm, x_host, algorithm, dtype=jnp.float32):
    spec = (P(tuple(comm.axis_names)) if len(comm.axis_names) > 1
            else P(comm.axis_names[0]))

    def shard_fn(x):
        return coll.all_to_all(x, comm, algorithm=algorithm)

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm.mesh, in_specs=spec, out_specs=spec,
        check_vma=False,
    ))
    return np.asarray(fn(jnp.asarray(x_host, dtype)))


@pytest.mark.parametrize("dtype", DTYPES,
                         ids=[d.__name__ for d in DTYPES])
@pytest.mark.parametrize("count", COUNTS)
def test_xla_variants_bit_identical(dtype, count):
    """All three algorithms are pure routing: bit-identical results
    across dtypes and odd per-destination counts, and the delivered
    layout is exactly 'output block s == rank s's input block r'."""
    comm = make_communicator()
    n = comm.size
    x = np.arange(n * n * count * 2, dtype=np.float32).reshape(
        n * n * count, 2
    )
    pair = _run_alltoall(comm, x, "pairwise", dtype)
    bruck = _run_alltoall(comm, x, "bruck", dtype)
    assert np.array_equal(pair, bruck)
    pu = pair.reshape(n, n, count, 2)
    xu = np.asarray(jnp.asarray(x, dtype)).reshape(n, n, count, 2)
    for r in range(n):
        for s in range(n):
            assert np.array_equal(pu[r, s], xu[s, r]), (r, s)


@pytest.mark.multislice
def test_xla_hierarchical_bit_identical_on_the_pod():
    hcomm = make_hybrid_communicator(n_slices=2)
    n = hcomm.size
    x = np.arange(n * n * 3, dtype=np.float32).reshape(n * n * 3, 1)
    pair = _run_alltoall(hcomm, x, "pairwise")
    hier = _run_alltoall(hcomm, x, "hierarchical")
    assert np.array_equal(pair, hier)


def test_untuned_compiles_byte_identically_to_pairwise():
    """THE invariant: ``all_to_all(x, comm)`` with no env, no cache,
    and the model inside its confidence band compiles the exact HLO
    of an explicit ``algorithm='pairwise'`` call."""
    comm = make_communicator()
    n = comm.size
    x = jnp.arange(n * n * 2, dtype=jnp.float32)

    def lower(algorithm):
        def shard_fn(v):
            return coll.all_to_all(v, comm, algorithm=algorithm)

        fn = jax.jit(jax.shard_map(
            shard_fn, mesh=comm.mesh, in_specs=P(comm.axis_names[0]),
            out_specs=P(comm.axis_names[0]), check_vma=False,
        ))
        return fn.lower(x).compile().as_text()

    assert lower(None) == lower("pairwise")


def test_xla_loud_errors(monkeypatch):
    comm = make_communicator()
    n = comm.size
    x = jnp.arange(n * 2.0)
    with pytest.raises(ValueError, match="ring"):
        coll.all_to_all(x, comm, backend="ring")
    with pytest.raises(ValueError, match="unknown all_to_all"):
        coll.all_to_all(x, comm, algorithm="ghost")
    with pytest.raises(ValueError, match="not\ndivisible|not divisible"):
        coll.all_to_all(jnp.arange(float(n + 1)), comm)
    monkeypatch.setenv(coll.ALLTOALL_ALGO_ENV, "fastest")
    with pytest.raises(ValueError, match="SMI_TPU_ALLTOALL_ALGO"):
        coll.all_to_all(x, comm)


def test_env_override_is_the_operators_word(monkeypatch):
    """$SMI_TPU_ALLTOALL_ALGO decides alone — including loudly
    refusing a structurally impossible pin instead of silently
    degrading to pairwise."""
    comm = make_communicator()
    n = comm.size
    x = np.arange(n * n * 2, dtype=np.float32).reshape(n * n, 2)
    monkeypatch.setenv(coll.ALLTOALL_ALGO_ENV, "bruck")
    out = _run_alltoall(comm, x, None)
    assert np.array_equal(out, _run_alltoall(comm, x, "bruck"))
    # a bruck pin on a non-power-of-two comm refuses loudly at trace
    if n == 8:
        sub = make_communicator()   # fake 8-dev mesh: build a 6-rank
        # check at the validation layer directly (no 6-device mesh
        # here): the explicit-algorithm path raises before tracing
        with pytest.raises(ValueError, match="power-of-two"):
            coll.all_to_all(
                jnp.arange(18.0),
                type("FakeComm", (), {
                    "size": 6, "axis_names": sub.axis_names,
                    "mesh": sub.mesh,
                })(),
                algorithm="bruck",
            )


# ---------------------------------------------------------------------------
# 9. Shrink/regrow compatibility of the step schedule
# ---------------------------------------------------------------------------


def test_mesh_schedule_follows_membership_changes():
    comm = make_communicator()
    n = comm.size
    assert comm.alltoall_schedule() == alltoall_pairwise_schedule(n)
    shrunk = comm.shrink([1, 5])
    sched = shrunk.alltoall_schedule()
    assert sched == alltoall_pairwise_schedule(n - 2)
    # every ordered survivor pair exactly once — the schedule follows
    # the CURRENT size, so a regrown communicator recovers the full
    # rotation
    seen = {p for step in sched for p in step}
    m = n - 2
    assert seen == {(s, d) for s in range(m) for d in range(m)
                    if s != d}
    # regrow is called on the ORIGINAL communicator (the holder of the
    # full rank order): the regrown schedule recovers the full rotation
    regrown = comm.regrow([1, 5], [1, 5])
    assert regrown.alltoall_schedule() == alltoall_pairwise_schedule(n)


# ---------------------------------------------------------------------------
# 10. CLI
# ---------------------------------------------------------------------------


def run_cli(*argv) -> int:
    return cli.main(list(argv))


def test_cli_tune_explain_alltoall(capsys):
    assert run_cli("tune", "--explain", "all_to_all") == 0
    out = capsys.readouterr().out
    assert "pairwise" in out and "bruck" in out
    assert "[heuristic]" in out or "[cache]" in out
    assert run_cli("tune", "--explain", "alltoall", "--ranks", "6") == 0
    assert "excluded bruck" in capsys.readouterr().out
    assert run_cli("tune", "--explain", "all_to_all",
                   "--slices", "2") == 0
    assert "hierarchical" in capsys.readouterr().out


def test_cli_tune_ops_alltoall_is_sweepable(capsys):
    # unknown ops name the sweepable set including alltoall
    assert run_cli("tune", "--ops", "ghost", "--cache",
                   "/tmp/_nope.json") == 2
    assert "alltoall" in capsys.readouterr().err


def test_cli_lint_covers_the_family(capsys):
    assert run_cli("lint", "--protocol", "all_to_all",
                   "--protocol", "all_to_all_bruck",
                   "--protocol", "all_to_all_pod", "--json") == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True
    names = {p["protocol"] for p in payload["protocols"]}
    assert names == {"all_to_all", "all_to_all_bruck", "all_to_all_pod"}


def test_cli_route_check_lint_names_bruck_shape(capsys):
    from smi_tpu.__main__ import _check_lint

    assert _check_lint(None, list(range(6))) == 0
    out = capsys.readouterr().out
    # the Bruck job was capped to the largest power of two and NAMED
    assert "all_to_all_bruck[n=4]" in out
    assert "all_to_all" in out
