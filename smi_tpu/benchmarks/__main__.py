"""CLI: ``python -m smi_tpu.benchmarks <name> [--ranks N] [--runs N] ...``

Mirrors the reference benchmark hosts' getopt interface (e.g.
``bandwidth_benchmark.cpp`` -b/-r/-k flags) with argparse. Add ``--cpu
--fake-ranks 8`` to run on the emulator-tier fake mesh.
"""

import argparse
import contextlib
import os
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(prog="smi_tpu.benchmarks")
    parser.add_argument("name", help="benchmark name, or 'all'")
    parser.add_argument("--ranks", type=int, default=None,
                        help="communicator size (default: all devices)")
    parser.add_argument("--runs", type=int, default=10)
    parser.add_argument("--root", type=int, default=None,
                        help="collective root (collectives only)")
    parser.add_argument("--elements", type=int, default=None)
    parser.add_argument("--size-kb", type=int, default=None,
                        help="bandwidth payload")
    parser.add_argument("--eager", action="store_true",
                        help="pipeline: disable rendezvous chunking")
    parser.add_argument("--window", type=int, default=None,
                        help="ring attention: sliding-window size")
    parser.add_argument("--seq-per-rank", type=int, default=None,
                        help="ring attention: tokens per rank")
    parser.add_argument("--out-dir", default=None,
                        help="write .dat/.json result files here")
    parser.add_argument("--trace", default=None, metavar="DIR",
                        help="collect a JAX profiler trace into DIR")
    parser.add_argument("--backend", default="xla",
                        choices=("xla", "ring"),
                        help="communication tier: XLA collectives or the "
                             "explicit credit-flow ring RDMA kernels")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend")
    parser.add_argument("--fake-ranks", type=int, default=None,
                        help="virtual CPU device count (implies --cpu)")
    args = parser.parse_args(argv)

    if args.fake_ranks:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_ranks}"
        ).strip()
    import jax

    if args.cpu or args.fake_ranks:
        jax.config.update("jax_platforms", "cpu")

    from smi_tpu.benchmarks.micro import BENCHMARKS, run_benchmark
    from smi_tpu.parallel.mesh import make_communicator

    comm = make_communicator(n_devices=args.ranks)
    names = sorted(BENCHMARKS) if args.name == "all" else [args.name]
    params = {"runs": args.runs}
    if args.backend != "xla":
        params["backend"] = args.backend
    if args.root is not None:
        params["root"] = args.root
    if args.elements is not None:
        params["elements"] = args.elements

    for name in names:
        p = dict(params)
        if name.startswith("bandwidth"):
            p.pop("root", None)
            p.pop("elements", None)
            if args.size_kb is not None:
                p["size_kb"] = args.size_kb
        elif name in ("latency", "injection", "multi_collectives"):
            p.pop("root", None)
            if name in ("latency", "injection"):
                p.pop("elements", None)
        elif name == "pipeline":
            p.pop("root", None)
            p["rendezvous"] = not args.eager
        elif name == "pipeline_double_rail":
            p.pop("root", None)
        elif name == "overlap":
            p.pop("root", None)
            p.pop("elements", None)
            if args.size_kb is not None:
                p["size_kb"] = args.size_kb
        elif name.startswith("app_"):
            p.pop("root", None)
            p.pop("elements", None)
            if p.pop("backend", "xla") != "xla":
                # app benchmarks have no ring tier; never record an
                # XLA measurement under a requested non-default tier
                # (run_benchmark's own guard, reachable from the
                # Python API, enforces the same rule)
                msg = (f"{name}: no backend tiers — skipping under "
                       f"backend={args.backend!r}")
                if args.name == "all":
                    print(msg, file=sys.stderr)
                    continue
                print(f"error: {msg}", file=sys.stderr)
                return 1
            if name.startswith("app_ring_attention"):
                if args.window is not None:
                    p["window"] = args.window
                if args.seq_per_rank is not None:
                    p["seq_per_rank"] = args.seq_per_rank
        if args.trace:
            from smi_tpu.utils.tracing import trace

            ctx = trace(args.trace)
        else:
            ctx = contextlib.nullcontext()
        try:
            with ctx:
                run_benchmark(name, comm=comm, out_dir=args.out_dir, **p)
        except ValueError as e:
            # an 'all' sweep keeps going past benchmarks whose device
            # requirements this host cannot meet
            if args.name != "all":
                raise
            print(f"{name}: skipped ({e})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
