"""The model-checked control-plane properties.

Each check is a pure predicate over a :class:`~smi_tpu.analysis.model.World`
state — it reads the REAL objects (the gate's occupancy, the lanes'
credit windows, the scheduler's skip counters, the view's epoch, the
WAL) and returns ``(property, message)`` violations. The model
checker runs :func:`check_state` on every reachable state and
:func:`check_terminal` on every terminal one; the first violation (in
BFS order) becomes the minimal counterexample.

The properties are the campaign gates of
:mod:`smi_tpu.serving.campaign` and the elastic soak, turned from
sampled assertions into exhaustively-checked invariants:

- **queue-bound** — stream-credit occupancy never exceeds the pool,
  each pending queue stays inside its cap, and total queue depth stays
  inside ``pool * (1 + classes)`` (the campaigns' bounded-occupancy
  gate, checked on every transition instead of at the end of a run).
- **stream-credit** — conservation end to end: credits held by the
  admission pool equal the accepted-but-incomplete streams (per class
  and in total), and every wire lane's window balances
  (``credits + in_flight + landed == WIRE_CREDITS``). A completed
  stream whose credit never returned — or a lane that minted or lost
  a wire credit — is caught at the first state it happens.
- **starvation** — the aging bound: an eligible stream is never
  passed over more than ``max_starve_rounds`` times plus one slot per
  concurrently active stream (the structural worst case of the
  starved-first ordering; see ``StreamScheduler._order``).
- **epoch-safety** — epoch monotonicity (the view's epoch never
  regresses), zero stale-epoch leaks (every stale presentation —
  straggler, rejoin request, pre-failover chunk — raised
  ``StaleEpochError``), and the shrink discipline: after a failover,
  no active stream retains deliveries recorded at its dead
  destination under an old lane epoch (``void_deliveries`` must have
  run before the replay).
- **lost-accepted** — an accepted stream is delivered bit-identically
  or the run fails loudly: zero silent corruptions, no zombie
  heartbeats (a killed rank that still beats pins its streams on a
  destination the detector will never confirm dead), and at every
  terminal state zero incomplete accepted streams, zero parked
  requests, and zero held credits.
- **plan-epoch-safety** (``retune`` scopes) — the r14 plan-swap arc
  is exactly as safe as a membership change: the plan epoch never
  regresses, every stale-plan presentation raised
  :class:`~smi_tpu.tuning.swap.StalePlanError`, and no active stream
  still carries a pre-swap plan epoch once the swap installed — the
  quiesce (drain) step can never be skipped
  (the ``swap_without_quiesce`` mutant's conviction).
- **swap-lost-accepted** (``retune`` scopes) — a swap or an aborted
  swap never loses the plan traffic is keyed to: the plan cache
  always holds the entry the swap machine's outcome dictates
  (pre-proposal entry until the swap, the rival after it, the
  pre-proposal entry again after a rollback) — the
  ``rollback_discards_entry`` mutant's conviction.
- **migration-lost-accepted** (``migrate`` scopes) — a live tenant
  migration never loses delivered state: the cutover restores every
  frozen stream's progress from the checkpoint shard packed at
  handoff, so ``mig_lost`` (delivered chunks that did not cross) is
  always zero — the ``cutover_without_handoff`` mutant's conviction.
- **placement-epoch-safety** (``migrate`` scopes) — capacity changes
  never strand accepted work: every active stream's destination is a
  current member (a scale-in with residents would park the rank their
  frames route to, unreachable under the new epoch) — the
  ``scale_in_with_residents`` mutant's conviction.
- **no-split-brain** (``partition`` scopes) — never two primaries for
  one tenant in one epoch: while a cut is in flight, the isolated
  side's stale claim to a tenant must never coexist with a different
  rank currently owning that tenant's route — the
  ``accept_in_minority`` mutant's conviction (its stale-side accept
  collides with the majority's post-failover heir).
- **fenced-actuation** (``partition`` scopes) — no epoch-advancing
  actuator fires without a majority quorum: every actuation recorded
  under the partition arc must have censused at least
  ``quorum_size(members)`` reachable members when it pulled the
  trigger — the ``actuate_without_quorum`` mutant's conviction (it
  fails a rank over from a minority census).
- **kv-shard-safety** (``infer`` scopes) — every accepted request's
  KV-shard set is resident at exactly one live epoch-current rank
  (the rank its route names, under its current lane epoch), or is
  inside a fenced in-flight handoff — the
  ``decode_failover_without_kv_handoff`` mutant's conviction (its
  failover reroutes the transport but strands the resident shards on
  the dead decode rank).
- **generation-lost-accepted** (``infer`` scopes) — a KV handoff
  never rolls back accepted tokens: the cutover resumes each decode
  from the token cursor packed in the handoff shard, so
  ``kv_lost_tokens`` (tokens emitted during the drain that the
  resumed decode forgot) is always zero — the
  ``stale_kv_after_cutover`` mutant's conviction (it resumes from the
  propose-time pre-handoff shards).
"""

from __future__ import annotations

from typing import List, Tuple

from smi_tpu.serving.qos import QOS_CLASSES
from smi_tpu.serving.scheduler import WIRE_CREDITS

#: The checked properties, in reporting order. docs/analysis.md's
#: property table must name every one (drift-guarded by
#: tests/test_perf_docs.py). The two ``plan-*``/``swap-*`` properties
#: engage only on ``retune`` scopes (worlds without a swap machine
#: satisfy them vacuously).
PROPERTIES = ("queue-bound", "stream-credit", "starvation",
              "epoch-safety", "lost-accepted",
              "plan-epoch-safety", "swap-lost-accepted",
              "migration-lost-accepted", "placement-epoch-safety",
              "no-split-brain", "fenced-actuation",
              "kv-shard-safety", "generation-lost-accepted")

Violation = Tuple[str, str]


def check_queue_bound(world) -> List[Violation]:
    out: List[Violation] = []
    gate = world.gate
    occ = gate.occupancy()
    if occ > gate.pool:
        out.append((
            "queue-bound",
            f"stream-credit occupancy {occ} exceeds pool {gate.pool}",
        ))
    for qos, q in gate.pending.items():
        if len(q) > gate.pending_bound:
            out.append((
                "queue-bound",
                f"pending queue for {qos} grew to {len(q)} "
                f"(bound {gate.pending_bound})",
            ))
    bound = gate.pool * (1 + len(QOS_CLASSES))
    depth = gate.queue_depth()
    if depth > bound:
        out.append((
            "queue-bound",
            f"queue depth {depth} exceeds the structural bound {bound}",
        ))
    return out


def check_stream_credit(world) -> List[Violation]:
    out: List[Violation] = []
    gate = world.gate
    active_by_class = {c: 0 for c in QOS_CLASSES}
    for st in world.active:
        active_by_class[st.request.qos] += 1
    for qos in QOS_CLASSES:
        if gate.held[qos] != active_by_class[qos]:
            out.append((
                "stream-credit",
                f"pool holds {gate.held[qos]} {qos} credit(s) but "
                f"{active_by_class[qos]} {qos} stream(s) are "
                f"accepted-and-incomplete — a stream credit "
                f"{'leaked' if gate.held[qos] > active_by_class[qos] else 'was double-released'}",
            ))
    for lane in world.lanes:
        window = lane.credits + len(lane.in_flight) + len(lane.landed)
        if window != WIRE_CREDITS:
            out.append((
                "stream-credit",
                f"rank {lane.rank}'s wire lane balances to {window} "
                f"credit(s) instead of {WIRE_CREDITS} — the credit "
                f"window {'minted' if window > WIRE_CREDITS else 'lost'}"
                f" a wire credit",
            ))
    return out


def check_starvation(world) -> List[Violation]:
    out: List[Violation] = []
    bound = world.scheduler.max_starve_rounds + len(world.active)
    for st in world.active:
        if st.next_to_send >= st.total_chunks:
            continue  # fully sent: no longer competing for the lane
        if st.skips > bound:
            out.append((
                "starvation",
                f"stream {st.request.stream_id} ({st.request.qos}) "
                f"was passed over {st.skips} times — past the aging "
                f"bound {world.scheduler.max_starve_rounds} plus the "
                f"{len(world.active)} concurrent stream(s); the "
                f"starved-first ordering is not engaging",
            ))
    return out


def check_epoch_safety(world) -> List[Violation]:
    out: List[Violation] = []
    if world.view.epoch < world._epoch_watermark:
        out.append((
            "epoch-safety",
            f"membership epoch regressed from "
            f"{world._epoch_watermark} to {world.view.epoch}",
        ))
    if world.stale_leaks:
        out.append((
            "epoch-safety",
            f"{world.stale_leaks} stale-epoch presentation(s) were "
            f"accepted instead of raising StaleEpochError — traffic "
            f"from a dead incarnation folded into the current epoch",
        ))
    for st in world.active:
        meta = world.delivery_meta.get(st.index, {})
        for seq, (rank, lane_epoch) in meta.items():
            if rank != st.dst or lane_epoch != st.lane_epoch:
                out.append((
                    "epoch-safety",
                    f"stream {st.request.stream_id} retains chunk "
                    f"{seq} delivered at rank {rank} under lane "
                    f"epoch {lane_epoch}, but the stream now routes "
                    f"to rank {st.dst} at lane epoch "
                    f"{st.lane_epoch} — the epoch bump did not void "
                    f"the dead consumer's deliveries "
                    f"(ProgressLog.void_deliveries never ran)",
                ))
                return out
    return out


def check_lost_accepted(world) -> List[Violation]:
    out: List[Violation] = []
    if world.corruptions:
        out.append((
            "lost-accepted",
            f"{world.corruptions} accepted stream(s) completed with "
            f"wrong bits — delivery is not bit-identical to the "
            f"submission",
        ))
    for st in world.active:
        if st.dst in world.zombie_beats:
            out.append((
                "lost-accepted",
                f"accepted stream {st.request.stream_id} targets "
                f"killed rank {st.dst}, which heartbeated AFTER the "
                f"kill — the detector will never confirm the death, "
                f"so the stream can never complete or fail over",
            ))
            return out
    return out


def check_plan_epoch_safety(world) -> List[Violation]:
    """The r14 swap arc: plan-epoch monotonicity, loud stale-plan
    rejection, and the quiesce discipline — after a swap installs, no
    active stream may still be keyed to the retired plan epoch.
    Vacuous on worlds without a swap machine (non-``retune`` scopes)."""
    swap = getattr(world, "swap", None)
    if swap is None:
        return []
    out: List[Violation] = []
    if swap.plan_epoch < world._plan_epoch_watermark:
        out.append((
            "plan-epoch-safety",
            f"plan epoch regressed from "
            f"{world._plan_epoch_watermark} to {swap.plan_epoch}",
        ))
    if world.stale_plan_leaks:
        out.append((
            "plan-epoch-safety",
            f"{world.stale_plan_leaks} stale-plan presentation(s) "
            f"were accepted instead of raising StalePlanError — "
            f"traffic planned under a retired entry folded into the "
            f"live plan",
        ))
    for st in world.active:
        stamp = world.stream_plan_epoch.get(st.index, swap.plan_epoch)
        if stamp != swap.plan_epoch:
            out.append((
                "plan-epoch-safety",
                f"stream {st.request.stream_id} is still in flight "
                f"under plan epoch {stamp} but the active plan is at "
                f"epoch {swap.plan_epoch} — the swap installed "
                f"without draining the streams keyed to the old plan "
                f"(quiesce never ran)",
            ))
            return out
    return out


def check_swap_lost_accepted(world) -> List[Violation]:
    """Zero lost-accepted ACROSS a swap or rollback: the plan cache
    must always hold the entry the swap machine's outcome dictates —
    a rolled-back swap that dropped (or mis-restored) the pre-proposal
    entry leaves accepted traffic keyed to a plan that no longer
    exists. (The explorer drives aborts from the pre-swap states only,
    matching the front-end's quiesce-timeout path; PlanSwap's
    post-swap restore branch is unit-tested, not exhaustively
    explored.) Vacuous on worlds without a swap machine."""
    swap = getattr(world, "swap", None)
    if swap is None:
        return []
    expected = world.swap_expected_entry
    got = world.plan_cache.lookup(swap.key)
    if got is None:
        return [(
            "swap-lost-accepted",
            f"the plan cache no longer holds an entry for "
            f"{swap.key.signature()} (swap state {swap.state!r}) — a "
            f"rolled-back swap must restore the pre-proposal plan, or "
            f"the traffic keyed to it is lost",
        )]
    if expected is not None and (
        got.knobs.get("algorithm") != expected.knobs.get("algorithm")
    ):
        return [(
            "swap-lost-accepted",
            f"the active entry for {swap.key.signature()} names "
            f"{got.knobs.get('algorithm')!r} but the swap machine's "
            f"outcome (state {swap.state!r}) dictates "
            f"{expected.knobs.get('algorithm')!r} — commit/rollback "
            f"and the cache disagree",
        )]
    return []


def check_migration_lost_accepted(world) -> List[Violation]:
    """The r16 migration arc: delivered state always crosses the
    cutover — ``mig_lost`` counts chunks whose delivery record did not
    come back out of the handoff shard. Vacuous on non-``migrate``
    scopes (the counter only moves inside the migration arc)."""
    scope = getattr(world, "scope", None)
    if scope is None or not getattr(scope, "migrate", 0):
        return []
    if world.mig_lost:
        return [(
            "migration-lost-accepted",
            f"{world.mig_lost} delivered chunk(s) were lost across "
            f"the migration cutover — the handoff shard was never "
            f"packed (or never restored), so the destination restarts "
            f"the stream(s) from nothing and 'accepted' silently "
            f"stopped being durable",
        )]
    return []


def check_placement_epoch_safety(world) -> List[Violation]:
    """The r16 capacity arc: a scale-in may only park a rank with
    zero residents — every active stream's destination must be a
    current member. Vacuous on non-``migrate`` scopes (kill scopes
    reroute inside the same failover action, so only the elasticity
    actuators can strand a destination)."""
    scope = getattr(world, "scope", None)
    if scope is None or not getattr(scope, "migrate", 0):
        return []
    for st in world.active:
        if st.dst not in world.view.members:
            return [(
                "placement-epoch-safety",
                f"active stream {st.request.stream_id} is destined to "
                f"rank {st.dst}, which is not a member (members: "
                f"{sorted(world.view.members)}) — a capacity change "
                f"parked a rank that still holds residents, so their "
                f"frames route to a destination the new epoch cannot "
                f"reach",
            )]
    return []


def check_no_split_brain(world) -> List[Violation]:
    """The r17 partition arc: never two primaries for one tenant in
    one epoch. The isolated side's stale claim (a ``minority_accept``
    only a lying ``_accept_ok`` enables) must never coexist with a
    DIFFERENT rank currently owning the tenant's route — once the
    majority fails the cut rank over, the heir and the stale claimant
    would both be accepting the same tenant's streams. Vacuous on
    non-``partition`` scopes (the claims map only moves inside the
    partition arc)."""
    scope = getattr(world, "scope", None)
    if scope is None or not getattr(scope, "partition", 0):
        return []
    for tenant, claimed in sorted(world.minority_claims.items()):
        owner = world._route(tenant)
        if owner != claimed:
            return [(
                "no-split-brain",
                f"tenant t{tenant} has two primaries in epoch "
                f"{world.view.epoch}: rank {claimed} (the partitioned "
                f"side's stale claim) and rank {owner} (the current "
                f"route owner) — the minority accepted a new stream "
                f"while cut off, so both sides are serving the same "
                f"tenant",
            )]
    return []


def check_fenced_actuation(world) -> List[Violation]:
    """The r17 partition arc: no epoch-advancing actuator fires
    without a majority quorum. Every actuation censused under the arc
    must have reached at least ``quorum_size(members)`` members when
    it pulled the trigger. Vacuous on non-``partition`` scopes (the
    actuation log only moves inside the partition arc)."""
    scope = getattr(world, "scope", None)
    if scope is None or not getattr(scope, "partition", 0):
        return []
    from smi_tpu.parallel.membership import quorum_size

    for what, reachable, members in world.actuations:
        needed = quorum_size(members)
        if reachable < needed:
            return [(
                "fenced-actuation",
                f"actuation {what!r} fired with only {reachable} of "
                f"{members} member(s) reachable — a majority quorum "
                f"needs {needed}, so a minority-side census mutated "
                f"membership state it had no mandate over",
            )]
    return []


def check_kv_shard_safety(world) -> List[Violation]:
    """The r20 inference arc: an accepted request's resident KV-shard
    set lives at exactly one live epoch-current rank — the rank its
    route names, under its current lane epoch — or sits inside a
    fenced in-flight handoff (``handoff``/``cutover`` arc states,
    where the source's decode is frozen and the shards are mid-
    transport by design). A failover that reroutes the request
    without restoring its shards at the heir strands the KV on a
    dead rank the new epoch cannot reach. Vacuous on non-``infer``
    scopes (the residency map only moves inside the inference arc)."""
    scope = getattr(world, "scope", None)
    if scope is None or not getattr(scope, "infer", 0):
        return []
    arc = world.kv_arc
    for st in world.active:
        idx = st.index
        res = world.kv_resident.get(idx)
        if res is None:
            continue  # prefill transport still in flight: no shards yet
        if (arc is not None and arc["state"] in ("handoff", "cutover")
                and idx in arc["streams"]):
            continue  # fenced in-flight handoff: mid-move is legal
        rank, ep = res
        if rank not in world.view.members:
            return [(
                "kv-shard-safety",
                f"accepted request {st.request.stream_id}'s KV shards "
                f"are resident at rank {rank}, which is not a member "
                f"(members: {sorted(world.view.members)}) — the "
                f"failover rerouted the request to rank {st.dst} but "
                f"never handed its shards off, so generation resumes "
                f"against KV stranded on a dead decode rank",
            )]
        if rank != st.dst or ep != st.lane_epoch:
            return [(
                "kv-shard-safety",
                f"accepted request {st.request.stream_id} routes to "
                f"rank {st.dst} at lane epoch {st.lane_epoch} but its "
                f"KV shards are resident at rank {rank} under epoch "
                f"{ep} — route and residency moved apart outside any "
                f"fenced handoff",
            )]
    return []


def check_generation_lost_accepted(world) -> List[Violation]:
    """The r20 inference arc: a KV handoff never rolls back accepted
    tokens — ``kv_lost_tokens`` counts tokens emitted during the
    drain that the cutover's resumed decode forgot (a resume from
    pre-handoff shards instead of the handoff blob). Vacuous on
    non-``infer`` scopes (the counter only moves at a KV cutover)."""
    scope = getattr(world, "scope", None)
    if scope is None or not getattr(scope, "infer", 0):
        return []
    if world.kv_lost_tokens:
        return [(
            "generation-lost-accepted",
            f"{world.kv_lost_tokens} accepted token(s) were rolled "
            f"back across the KV handoff cutover — the destination "
            f"resumed generation from pre-handoff shards instead of "
            f"the shard set packed at handoff, so tokens already "
            f"emitted (and possibly streamed to the caller) were "
            f"silently re-generated or lost",
        )]
    return []


def check_state(world) -> List[Violation]:
    """All per-state invariants, in property order."""
    out: List[Violation] = []
    out.extend(check_queue_bound(world))
    out.extend(check_stream_credit(world))
    out.extend(check_starvation(world))
    out.extend(check_epoch_safety(world))
    out.extend(check_lost_accepted(world))
    out.extend(check_plan_epoch_safety(world))
    out.extend(check_swap_lost_accepted(world))
    out.extend(check_migration_lost_accepted(world))
    out.extend(check_placement_epoch_safety(world))
    out.extend(check_no_split_brain(world))
    out.extend(check_fenced_actuation(world))
    out.extend(check_kv_shard_safety(world))
    out.extend(check_generation_lost_accepted(world))
    return out


def check_terminal(world) -> List[Violation]:
    """Terminal states additionally owe completion: every accepted
    stream delivered (its WAL holding every chunk), nothing parked,
    and every stream credit back in the pool."""
    out = check_state(world)
    if world.active:
        stuck = ", ".join(
            f"{st.request.stream_id} ({len(st.delivered)}/"
            f"{st.total_chunks} delivered at rank {st.dst})"
            for st in world.active
        )
        out.append((
            "lost-accepted",
            f"terminal state with {len(world.active)} accepted "
            f"stream(s) undelivered: {stuck}",
        ))
    pending = sum(len(q) for q in world.gate.pending.values())
    if pending:
        out.append((
            "lost-accepted",
            f"terminal state with {pending} request(s) still parked "
            f"at the admission edge — neither admitted nor shed",
        ))
    if not world.active and world.gate.occupancy():
        out.append((
            "stream-credit",
            f"terminal state holds {world.gate.occupancy()} stream "
            f"credit(s) with zero active streams — credits leaked",
        ))
    for st in world.completed:
        missing = st.wal.missing(
            (st.index, seq) for seq in range(st.total_chunks)
        )
        if missing:
            out.append((
                "lost-accepted",
                f"completed stream {st.request.stream_id}'s WAL is "
                f"missing delivery record(s) {sorted(missing)} — the "
                f"durable log disagrees with the delivery",
            ))
    return out
