"""smi_tpu — a TPU-native streaming message interface.

A brand-new JAX/XLA/Pallas framework with the capabilities of SMI
(Streaming Message Interface, SC'19): an MPI-like communication model for
accelerator kernels where transient point-to-point channels (``Push``/``Pop``)
and collectives (``Bcast``/``Reduce``/``Scatter``/``Gather``) are addressed by
logical *ports* and overlap with pipelined computation.

Where the reference implementation (``/root/reference``) synthesizes an
on-FPGA packet-switched NoC over QSFP serial links, this framework maps the
same programming model onto TPUs idiomatically:

- the device *mesh* + named axes replace ranks and the routing NoC
  (XLA routes over the ICI torus; reference: ``codegen/routing_table.py``),
- masked ``jax.lax.ppermute`` inside ``shard_map`` replaces the CK_S/CK_R
  P2P path (reference: ``codegen/templates/{cks,ckr}.cl``),
- XLA collectives (``psum``/``all_gather``/``psum_scatter``) replace the
  per-port collective support kernels (reference: ``codegen/templates/
  {bcast,reduce,scatter,gather}.cl``),
- Pallas kernels with overlapped remote DMA replace streaming-into-pipeline
  semantics (reference: the concurrent bridge kernels of
  ``examples/kernels/stencil_smi.cl:236-386``),
- a CPU fake-mesh ``jax.jit`` path replaces the Intel FPGA emulator for
  hardware-free testing (reference: ``CMakeLists.txt:188-191``).

Public API (mirrors ``include/smi.h``; see each submodule for details)::

    import smi_tpu as smi

    prog = smi.Program([smi.Push(0, "float"), smi.Pop(0, "float")])
    comm = smi.make_communicator(n_devices=8)

    @smi.smi_kernel(comm, out_specs=P("smi"), program=prog)
    def app(ctx, x):
        ch = ctx.open_channel(port=0, src=0, dst=1, count=N, dtype="float")
        received = ctx.transfer(ch, x)   # Push at src, Pop at dst, fused
        return ctx.bcast(received, root=1)[None]
"""

from smi_tpu.utils.compile import install_jax_compat as _install_jax_compat

# older pinned JAX: alias jax.experimental.shard_map to jax.shard_map
# (the API every module and example targets) before anything traces
_install_jax_compat()

from smi_tpu.ops.types import (
    SmiDtype,
    SmiOp,
    SMI_ADD,
    SMI_MAX,
    SMI_MIN,
    dtype_to_jnp,
)
from smi_tpu.ops.operations import (
    SmiOperation,
    Push,
    Pop,
    Broadcast,
    Reduce,
    Scatter,
    Gather,
    OP_REGISTRY,
)
from smi_tpu.ops.program import (
    Program,
    Device,
    ProgramMapping,
    allocate_ports,
    combined_program,
)
from smi_tpu.ops.serialization import (
    parse_program,
    serialize_program,
    parse_topology_file,
)
from smi_tpu.parallel.mesh import (
    Communicator,
    make_communicator,
    make_hybrid_communicator,
    mesh_from_topology,
)
from smi_tpu.parallel.channels import FrameCheck, P2PChannel, stream_concurrent
from smi_tpu.parallel.context import SmiContext, smi_kernel
from smi_tpu.parallel.credits import IntegrityError
from smi_tpu.parallel.faults import FaultPlan
from smi_tpu.parallel.checkpoint import (
    CheckpointIntegrityError,
    CheckpointStore,
    run_iterative,
)
from smi_tpu.parallel.membership import (
    ConfirmedDead,
    MembershipView,
    PhiAccrualDetector,
    PodRingPlan,
    StaleEpochError,
    SuspectRank,
    elastic_campaign,
    plan_pod_rings,
    pod_campaign,
)
from smi_tpu.parallel.recovery import (
    ProgressLog,
    RecoveryOutcome,
    WalCorruptionError,
    chaos_campaign,
    recover_communicator,
    run_with_recovery,
)
from smi_tpu.parallel.routing import FailureSet, RouteCutError
from smi_tpu.utils.watchdog import Deadline, WatchdogTimeout

__version__ = "0.1.0"

__all__ = [
    "SmiDtype",
    "SmiOp",
    "SMI_ADD",
    "SMI_MAX",
    "SMI_MIN",
    "dtype_to_jnp",
    "SmiOperation",
    "Push",
    "Pop",
    "Broadcast",
    "Reduce",
    "Scatter",
    "Gather",
    "OP_REGISTRY",
    "Program",
    "Device",
    "ProgramMapping",
    "allocate_ports",
    "combined_program",
    "parse_program",
    "serialize_program",
    "parse_topology_file",
    "Communicator",
    "make_communicator",
    "make_hybrid_communicator",
    "mesh_from_topology",
    "P2PChannel",
    "FrameCheck",
    "IntegrityError",
    "stream_concurrent",
    "SmiContext",
    "smi_kernel",
    "FaultPlan",
    "FailureSet",
    "RouteCutError",
    "ProgressLog",
    "RecoveryOutcome",
    "WalCorruptionError",
    "chaos_campaign",
    "recover_communicator",
    "run_with_recovery",
    "CheckpointIntegrityError",
    "CheckpointStore",
    "run_iterative",
    "ConfirmedDead",
    "MembershipView",
    "PodRingPlan",
    "PhiAccrualDetector",
    "StaleEpochError",
    "SuspectRank",
    "elastic_campaign",
    "plan_pod_rings",
    "pod_campaign",
    "Deadline",
    "WatchdogTimeout",
]
