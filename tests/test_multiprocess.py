"""Multi-process control-plane integration test.

Reference: the integration suites launch 8 real MPI processes against the
emulator (``test/CMakeLists.txt:46-50``). The TPU framework's control
plane is ``jax.distributed`` (``parallel/bootstrap.py``); this test
exercises it for real: two localhost CPU processes bootstrap through
``distributed_options`` → ``jax.distributed.initialize``, import the
*generated* ``SmiInit_*`` host module produced by the route/host pipeline,
build one global communicator spanning both processes, run a collective
over it, and verify payloads — the full L5 host-runtime path beyond
option parsing.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from smi_tpu import __main__ as cli
from smi_tpu.ops.operations import Broadcast, Pop, Push
from smi_tpu.ops.program import Program
from smi_tpu.ops.serialization import serialize_program

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = textwrap.dedent(
    '''
    import os, sys
    # one CPU device per process so the 2-device global mesh genuinely
    # spans both processes
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid, port, outdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from smi_tpu.parallel.bootstrap import distributed_options, init_distributed

    # two distinct "nodes" that both resolve to this machine
    opts = distributed_options(
        "localhost  # device-0, rank 0\\n127.0.0.1  # device-1, rank 1\\n",
        process_id=pid, coordinator_port=port,
    )
    assert opts.num_processes == 2, opts
    assert opts.coordinator_address.startswith("localhost:"), opts
    init_distributed(opts)
    assert jax.process_count() == 2
    assert jax.device_count() == 2
    assert jax.local_device_count() == 1

    sys.path.insert(0, outdir)
    import smi_generated_host as host

    comm, program = host.SmiInit_app(
        rank=pid, ranks=2, routing_dir=os.path.join(outdir, "smi-routes")
    )
    assert comm.size == 2
    assert program.find("push", 0) is not None

    import numpy as np
    from jax.sharding import PartitionSpec as P
    import smi_tpu as smi

    @smi.smi_kernel(comm, in_specs=P(), out_specs=P("smi"), program=program)
    def app(ctx, x):
        shifted = ctx.transfer(
            ctx.open_channel(port=0, src=0, dst=1, count=8, dtype="float"), x
        )
        return ctx.bcast(x + ctx.rank().astype(x.dtype), root=1, port=1)[None] + \\
            shifted[None] * 0

    out = app(np.arange(8, dtype=np.float32))
    local = np.asarray(out.addressable_data(0))
    np.testing.assert_allclose(local[0], np.arange(8) + 1)
    print("OK", pid, flush=True)
    '''
)


CHILD_MPMD = textwrap.dedent(
    '''
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid, port, outdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from smi_tpu.parallel.bootstrap import distributed_options, init_distributed

    opts = distributed_options(
        "localhost\\n127.0.0.1\\n", process_id=pid, coordinator_port=port,
    )
    init_distributed(opts)
    assert jax.process_count() == 2

    sys.path.insert(0, outdir)
    import smi_generated_host as host

    # genuinely multi-controller: each process initializes ITS OWN
    # program (the reference's per-rank bitstreams,
    # bandwidth_0.cl/bandwidth_1.cl) from the generated module
    init = [host.SmiInit_sender, host.SmiInit_receiver][pid]
    comm, my_program = init(
        rank=pid, ranks=2,
        routing_dir=os.path.join(outdir, "smi-routes"),
    )
    kinds = sorted(op.NAME for op in my_program.operations)
    assert kinds == (["push"] if pid == 0 else ["pop"]), kinds

    # the SPMD trace must be identical on both controllers: both build
    # the same union program from the shared topology file
    import smi_tpu as smi
    from smi_tpu.ops.program import combined_program
    topo = smi.parse_topology_file(
        open(os.path.join(outdir, "topo.json")).read(),
        program_paths=[os.path.join(outdir, "sender.json"),
                       os.path.join(outdir, "receiver.json")],
    )
    union = combined_program(topo.mapping)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    @smi.smi_kernel(comm, in_specs=P(), out_specs=P("smi"),
                    program=union)
    def app(ctx, x):
        # sender scales its payload; the receiver contributes zeros
        payload = ctx.select(
            [lambda v: v * 3.0, lambda v: jnp.zeros_like(v)], x
        )
        ch = ctx.open_channel(port=0, src=0, dst=1, count=x.shape[0],
                              dtype="float")
        received = ctx.transfer(ch, payload)
        return received[None]

    out = app(np.arange(8, dtype=np.float32))
    local = np.asarray(out.addressable_data(0))
    # message lands at the receiver (global row 1), zeros at the sender
    expected = (np.arange(8) * 3.0) if pid == 1 else np.zeros(8)
    np.testing.assert_allclose(local[0], expected)
    print("OK", pid, flush=True)
    '''
)


CHILD_8 = textwrap.dedent(
    '''
    import os, sys
    # one CPU device per process: the 8-device global mesh genuinely
    # spans 8 controllers (the reference's mpirun -np 8 shape,
    # test/CMakeLists.txt:46-50)
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid, port, outdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from smi_tpu.parallel.bootstrap import distributed_options, init_distributed

    # eight DISTINCT loopback nodes (the hostfile packs same-node ranks
    # into one process, so 8 processes need 8 node addresses; 127/8 is
    # all loopback on Linux)
    opts = distributed_options(
        "".join(f"127.0.0.{r + 1}  # device-{r}\\n" for r in range(8)),
        process_id=pid, coordinator_port=port,
    )
    assert opts.num_processes == 8, opts
    init_distributed(opts)
    assert jax.process_count() == 8
    assert jax.device_count() == 8
    assert jax.local_device_count() == 1

    sys.path.insert(0, outdir)
    import smi_generated_host as host

    comm, program = host.SmiInit_app(
        rank=pid, ranks=8, routing_dir=os.path.join(outdir, "smi-routes")
    )
    assert comm.size == 8
    assert program.find("push", 0) is not None

    import numpy as np
    from jax.sharding import PartitionSpec as P
    import smi_tpu as smi

    @smi.smi_kernel(comm, in_specs=P(), out_specs=P("smi"), program=program)
    def app(ctx, x):
        # non-adjacent P2P (0 -> 5) + a non-zero-root broadcast: the
        # coordinator/process-id plumbing must hold at every rank
        moved = ctx.transfer(
            ctx.open_channel(port=0, src=0, dst=5, count=8, dtype="float"), x
        )
        return ctx.bcast(x + ctx.rank().astype(x.dtype), root=3,
                         port=1)[None] + moved[None]

    out = app(np.arange(8, dtype=np.float32))
    local = np.asarray(out.addressable_data(0))
    expected = np.arange(8) + 3.0
    if pid == 5:
        expected = expected + np.arange(8)
    np.testing.assert_allclose(local[0], expected)
    print("OK", pid, flush=True)
    '''
)


CHILD_MPMD_8 = textwrap.dedent(
    '''
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid, port, outdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from smi_tpu.parallel.bootstrap import distributed_options, init_distributed

    opts = distributed_options(
        "".join(f"127.0.0.{r + 1}\\n" for r in range(8)),
        process_id=pid, coordinator_port=port,
    )
    init_distributed(opts)
    assert jax.process_count() == 8

    sys.path.insert(0, outdir)
    import smi_generated_host as host

    # 8 controllers, 8 DISTINCT programs: even ranks push on stream
    # pid//2, odd ranks pop it (four disjoint P2P pairs — the
    # reference's per-rank bitstream split at full process count)
    init = getattr(host, f"SmiInit_p{pid}")
    comm, my_program = init(
        rank=pid, ranks=8,
        routing_dir=os.path.join(outdir, "smi-routes"),
    )
    kinds = sorted(op.NAME for op in my_program.operations)
    assert kinds == (["push"] if pid % 2 == 0 else ["pop"]), kinds

    # every controller builds the same union program from the shared
    # topology, keeping the SPMD trace identical
    import smi_tpu as smi
    from smi_tpu.ops.program import combined_program
    topo = smi.parse_topology_file(
        open(os.path.join(outdir, "topo.json")).read(),
        program_paths=[os.path.join(outdir, f"p{r}.json")
                       for r in range(8)],
    )
    union = combined_program(topo.mapping)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    @smi.smi_kernel(comm, in_specs=P(), out_specs=P("smi"),
                    program=union)
    def app(ctx, x):
        branches = []
        for r in range(8):
            if r % 2 == 0:
                branches.append(lambda v, s=float(r + 1): v * s)
            else:
                branches.append(lambda v: jnp.zeros_like(v))
        payload = ctx.select(branches, x)
        total = None
        for i in range(4):
            ch = ctx.open_channel(port=i, src=2 * i, dst=2 * i + 1,
                                  count=8, dtype="float")
            got = ctx.transfer(ch, payload)
            total = got if total is None else total + got
        return total[None]

    out = app(np.arange(8, dtype=np.float32))
    local = np.asarray(out.addressable_data(0))
    # pair 2i -> 2i+1 lands arange * (2i+1) on the odd rank
    expected = (np.arange(8) * pid) if pid % 2 == 1 else np.zeros(8)
    np.testing.assert_allclose(local[0], expected)
    print("OK", pid, flush=True)
    '''
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_program(path, prog):
    serialized = serialize_program(prog)
    if not isinstance(serialized, str):
        serialized = json.dumps(serialized)
    path.write_text(serialized)


def _run_children(tmp_path, script_text, n=2, timeout=200):
    """Launch ``n`` child processes of ``script_text`` and assert each
    exits 0 printing its "OK <pid>" marker."""
    script = tmp_path / "child.py"
    script.write_text(script_text)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [REPO_ROOT, env.get("PYTHONPATH", "")] if p
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port),
             str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in range(n)
    ]
    results = []
    try:
        for p in procs:
            results.append(p.communicate(timeout=timeout))
    finally:
        for p in procs:
            p.kill()
    for pid, (p, (out, err)) in enumerate(zip(procs, results)):
        assert p.returncode == 0, (
            f"process {pid} failed\nstdout:\n{out}\nstderr:\n{err}"
        )
        assert f"OK {pid}" in out


def test_two_process_bootstrap_and_collective(tmp_path):
    # 1. author a program + topology, run the route/host pipeline
    _write_program(tmp_path / "app.json", Program([Push(0), Pop(0),
                                                   Broadcast(1)]))
    topo = tmp_path / "topo.json"
    assert cli.main(["topology", "-n", "2", "-p", "app",
                     "-f", str(topo)]) == 0
    routes = tmp_path / "smi-routes"
    assert cli.main(["route", str(topo), str(routes),
                     str(tmp_path / "app.json")]) == 0
    host_src = tmp_path / "smi_generated_host.py"
    assert cli.main(["host", str(host_src),
                     str(tmp_path / "app.json")]) == 0

    # 2. launch two processes that bootstrap and run a collective
    _run_children(tmp_path, CHILD)


def test_two_process_mpmd_divergent_programs(tmp_path):
    """MPMD across real controllers: each process SmiInit's a DIFFERENT
    program (sender: Push / receiver: Pop — the reference's
    bandwidth_0/bandwidth_1 split), the shared topology's union program
    keeps the SPMD trace identical, and ctx.select diverges the ranks.
    Closes VERDICT r1 weak #5 ("the genuinely multi-controller variant
    has no end-to-end test")."""
    _write_program(tmp_path / "sender.json", Program([Push(0)]))
    _write_program(tmp_path / "receiver.json", Program([Pop(0)]))
    topo = tmp_path / "topo.json"
    assert cli.main(["topology", "-n", "2", "-p", "sender", "receiver",
                     "-f", str(topo)]) == 0
    routes = tmp_path / "smi-routes"
    assert cli.main(["route", str(topo), str(routes),
                     str(tmp_path / "sender.json"),
                     str(tmp_path / "receiver.json")]) == 0
    host_src = tmp_path / "smi_generated_host.py"
    assert cli.main(["host", str(host_src),
                     str(tmp_path / "sender.json"),
                     str(tmp_path / "receiver.json")]) == 0

    _run_children(tmp_path, CHILD_MPMD)


def test_eight_process_bootstrap_and_collective(tmp_path):
    """The reference's full launch shape — 8 real controller processes
    (``mpirun -np 8``, ``test/CMakeLists.txt:46-50``): bootstrap through
    ``jax.distributed``, SmiInit from the generated host module, then a
    non-adjacent P2P plus a rooted broadcast over the 8-process global
    mesh, payloads asserted at every rank."""
    _write_program(tmp_path / "app.json", Program([Push(0), Pop(0),
                                                   Broadcast(1)]))
    topo = tmp_path / "topo.json"
    assert cli.main(["topology", "-n", "8", "-p", "app",
                     "-f", str(topo)]) == 0
    routes = tmp_path / "smi-routes"
    assert cli.main(["route", str(topo), str(routes),
                     str(tmp_path / "app.json")]) == 0
    host_src = tmp_path / "smi_generated_host.py"
    assert cli.main(["host", str(host_src),
                     str(tmp_path / "app.json")]) == 0

    _run_children(tmp_path, CHILD_8, n=8, timeout=400)


def test_eight_process_mpmd_divergent_programs(tmp_path):
    """Divergent MPMD at full process count: 8 controllers each
    SmiInit-ing a DIFFERENT program (four disjoint push/pop pairs), one
    union trace shared by all. Closes VERDICT r4 missing #2 (the
    multi-process tier proved 2 controllers where the reference
    launches 8)."""
    progs = []
    for r in range(8):
        ops = [Push(r // 2)] if r % 2 == 0 else [Pop(r // 2)]
        _write_program(tmp_path / f"p{r}.json", Program(ops))
        progs.append(str(tmp_path / f"p{r}.json"))
    topo = tmp_path / "topo.json"
    assert cli.main(["topology", "-n", "8",
                     "-p", *[f"p{r}" for r in range(8)],
                     "-f", str(topo)]) == 0
    routes = tmp_path / "smi-routes"
    assert cli.main(["route", str(topo), str(routes), *progs]) == 0
    host_src = tmp_path / "smi_generated_host.py"
    assert cli.main(["host", str(host_src), *progs]) == 0

    _run_children(tmp_path, CHILD_MPMD_8, n=8, timeout=400)
