"""Streaming overlap engine: chunked collectives, split halo exchange,
HLO-verified comm/compute overlap.

Four properties, each checked where it is provable without hardware:

- chunked collectives are BIT-identical to unchunked across the
  dtype x size x chunks matrix (chunking is payload splitting — no
  element's reduction tree changes);
- the overlapped Jacobi step is bit-identical to the naive step and its
  compiled CPU HLO carries nonzero compute independent of EVERY halo
  permute, while the naive step's carries ~zero — overlap as a
  statically-checked artifact property (``traffic.overlap_report``);
- the chunked pipelined ring protocol is schedule-safe (exhaustive
  fuzz) and composes with PR 2's verified-transport framing: sequence
  lanes keep advancing across interleaved pipeline chunks, and a
  ``BitFlipPayload`` inside a pipelined chunk raises ``IntegrityError``
  naming the right chunk;
- trace-time caches (ring context, routing context) are hit on
  retrace instead of rebuilt per traced call.

The ring-tier EXECUTION of chunked kernels stays untested here for the
same reason as the rest of the ring tier: this JAX has no Pallas TPU
interpret mode (see ``ring.interpret_available``); the protocol is
validated hardware-free by the credits simulator instead.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import smi_tpu as smi
from smi_tpu.parallel import credits as C
from smi_tpu.parallel import faults as F
from smi_tpu.parallel import traffic as T
from smi_tpu.parallel.collectives import (
    RS_AG_MIN_BYTES,
    _chunk_bounds,
    allreduce,
)

DTYPES = [jnp.float32, jnp.bfloat16, jnp.int32]
LENGTHS = [1, 7, 33]  # odd sizes: chunk splits are deliberately uneven


def _five_collectives(comm, chunks):
    """One kernel running all five collectives at the given chunking."""

    @smi.smi_kernel(comm, in_specs=P(), out_specs=P("smi"))
    def app(ctx, x, big):
        r = ctx.rank().astype(x.dtype)
        return (
            ctx.bcast(x + r, root=3, chunks=chunks)[None],
            ctx.reduce(x * (r + 1), op="max", root=2, chunks=chunks)[None],
            ctx.allreduce(x + r, chunks=chunks)[None],
            ctx.gather(x + r * 100, root=1, chunks=chunks)[None],
            ctx.scatter(big + r, root=0, chunks=chunks)[None],
        )

    return app


@pytest.mark.parametrize("dtype", DTYPES,
                         ids=[jnp.dtype(d).name for d in DTYPES])
@pytest.mark.parametrize("length", LENGTHS)
def test_chunked_collectives_bit_identical(comm8, dtype, length):
    """chunks in {1, 3, length, > elements}: results must be BIT
    identical to the unchunked call for every collective."""
    x = (jnp.arange(length) % 53).astype(dtype)
    big = jnp.tile(x, comm8.size)
    base = [np.asarray(o) for o in _five_collectives(comm8, 1)(x, big)]
    for chunks in sorted({3, length, length + 5} - {1}):
        got = [
            np.asarray(o)
            for o in _five_collectives(comm8, chunks)(x, big)
        ]
        for b, g in zip(base, got):
            assert b.dtype == g.dtype and b.shape == g.shape
            np.testing.assert_array_equal(
                b, g,
                err_msg=f"dtype={dtype} length={length} chunks={chunks}",
            )


def test_chunk_bounds_balanced_and_clamped():
    assert _chunk_bounds(10, 3) == [(0, 4), (4, 7), (7, 10)]
    assert _chunk_bounds(4, 100) == [(0, 1), (1, 2), (2, 3), (3, 4)]
    assert _chunk_bounds(5, 1) == [(0, 5)]
    # every split covers [0, total) exactly once
    for total in (1, 7, 33):
        for k in (1, 2, 3, total, total + 9):
            bounds = _chunk_bounds(total, k)
            assert bounds[0][0] == 0 and bounds[-1][1] == total
            for (_, e1), (s2, _) in zip(bounds, bounds[1:]):
                assert e1 == s2


def test_bad_chunks_rejected(comm8):
    for bad in (0, -2):
        with pytest.raises(ValueError, match="chunks"):
            _five_collectives(comm8, bad)(
                jnp.zeros(4, jnp.float32), jnp.zeros(32, jnp.float32)
            )
    with pytest.raises(TypeError, match="chunks"):
        _five_collectives(comm8, 2.5)(
            jnp.zeros(4, jnp.float32), jnp.zeros(32, jnp.float32)
        )


def test_rs_ag_allreduce_exact_for_ints(comm8):
    """The reduce-scatter + all-gather decomposition is exact integer
    math; forced on (and chunked) it must equal the one-psum result."""

    def run(**kw):
        @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
        def app(ctx, x):
            return ctx.allreduce(x + ctx.rank().astype(x.dtype), **kw)[None]

        return np.asarray(app((jnp.arange(64) % 11).astype(jnp.int32)))

    base = run()
    np.testing.assert_array_equal(base, run(rs_ag=True))
    np.testing.assert_array_equal(base, run(rs_ag=True, chunks=3))


def test_rs_ag_eligibility_errors(comm8):
    """rs_ag=True on an ineligible payload is a loud error, and the
    size heuristic never fires below the threshold."""

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def bad_shape(ctx, x):
        return ctx.allreduce(x, rs_ag=True)[None]

    with pytest.raises(ValueError, match="divisible"):
        bad_shape(jnp.zeros(7, jnp.float32))

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def bad_op(ctx, x):
        return ctx.allreduce(x, op="max", rs_ag=True)[None]

    with pytest.raises(ValueError, match="ADD"):
        bad_op(jnp.zeros(8, jnp.float32))
    # a small payload stays a single psum under the heuristic
    assert 64 * 4 < RS_AG_MIN_BYTES


@pytest.mark.perf
def test_rs_ag_heuristic_switches_hlo(comm8):
    """At the size threshold the compiled artifact really carries the
    reduce-scatter + all-gather pair instead of one all-reduce."""
    import jax

    elems = RS_AG_MIN_BYTES // 4 + comm8.size  # just past the switch
    elems -= elems % comm8.size

    @smi.smi_kernel(comm8, in_specs=P(), out_specs=P("smi"))
    def big(ctx, x):
        return ctx.allreduce(x)[None]

    txt = big.lower(jnp.ones(elems, jnp.float32)).compile().as_text()
    assert "reduce-scatter(" in txt or "reduce-scatter-start(" in txt
    assert "all-gather(" in txt or "all-gather-start(" in txt


# ---------------------------------------------------------------------------
# Split halo exchange + overlapped stencil
# ---------------------------------------------------------------------------


def _mesh24(eight_devices):
    return smi.make_communicator(
        shape=(2, 4), axis_names=("sx", "sy"), devices=eight_devices
    )


def test_halo_start_finish_equals_monolithic(eight_devices):
    import jax
    from smi_tpu.parallel import halo

    comm = _mesh24(eight_devices)

    @smi.smi_kernel(comm, in_specs=P("sx", "sy"),
                    out_specs=(P("sx", "sy"), P("sx", "sy")))
    def both(ctx, block):
        a = halo.halo_exchange_2d(block, comm)
        ex = halo.halo_exchange_start(block, comm)
        b = halo.halo_exchange_finish(ex)
        return (
            halo.pad_with_halos(block, a),
            halo.pad_with_halos(block, b),
        )

    g = jnp.arange(32 * 64, dtype=jnp.float32).reshape(32, 64)
    a, b = both(g)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_corner_halo_start_finish_equals_monolithic(eight_devices):
    from smi_tpu.parallel import halo

    comm = _mesh24(eight_devices)

    @smi.smi_kernel(comm, in_specs=P("sx", "sy"),
                    out_specs=tuple([P("sx", "sy")] * 8))
    def both(ctx, block):
        a = halo.halo_exchange_2d_corners(block, comm, depth=2)
        ex = halo.halo_exchange_2d_corners_start(block, comm, depth=2)
        b = halo.halo_exchange_2d_corners_finish(ex)
        return tuple(a) + tuple(b)

    g = jnp.arange(32 * 64, dtype=jnp.float32).reshape(32, 64)
    out = both(g)
    for x, y in zip(out[:4], out[4:]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_overlapped_step_bit_identical_and_correct(eight_devices):
    from smi_tpu.models import stencil

    comm = _mesh24(eight_devices)
    g = stencil.initial_grid(32, 64)
    g[:, -1] = 2.0
    g[5, 7] = -3.0
    naive = np.asarray(stencil.make_stencil_fn(comm, 7)(jnp.asarray(g)))
    over = np.asarray(
        stencil.make_stencil_fn(comm, 7, overlap=True)(jnp.asarray(g))
    )
    assert (naive == over).all(), "overlap changed the numerics"
    np.testing.assert_allclose(
        over, stencil.reference_stencil(g, 7), rtol=1e-6, atol=1e-6
    )


def test_overlapped_step_tiny_tile_fallback(eight_devices):
    """1-wide tiles have no interior; the overlapped step must fall
    back to the naive sweep, not crash or diverge."""
    from smi_tpu.models import stencil

    comm = smi.make_communicator(
        shape=(2, 2), axis_names=("sx", "sy"), devices=eight_devices
    )
    g = stencil.initial_grid(2, 2)  # 1x1 tiles
    a = np.asarray(stencil.make_stencil_fn(comm, 3)(jnp.asarray(g)))
    b = np.asarray(
        stencil.make_stencil_fn(comm, 3, overlap=True)(jnp.asarray(g))
    )
    np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# HLO-verified overlap (the acceptance criterion)
# ---------------------------------------------------------------------------


@pytest.mark.perf
def test_overlap_report_discriminates_stencil_schedules(eight_devices):
    """Deterministic CPU-HLO check: the overlapped step's compiled
    module carries nonzero compute independent of EVERY halo permute
    (the interior), the naive step's ~zero (loop bookkeeping only)."""
    from smi_tpu.models import stencil

    comm = _mesh24(eight_devices)
    g = jnp.zeros((64, 128), jnp.float32)
    naive = T.overlap_report(
        stencil.make_stencil_fn(comm, 4).lower(g).compile()
    )
    over = T.overlap_report(
        stencil.make_stencil_fn(comm, 4, overlap=True).lower(g).compile()
    )
    assert naive["collectives"] == over["collectives"] == 4
    # the overlapped interior: one (h-2, w-2) f32 block per shard
    assert over["overlappable_bytes"] >= 30 * 30 * 4
    # the naive step has no halo-independent compute beyond scalar
    # loop bookkeeping
    assert naive["overlappable_bytes"] <= 64
    assert naive["overlappable_bytes"] < over["overlappable_bytes"] / 10
    assert over["overlap_fraction"] > naive["overlap_fraction"]


def test_overlap_report_async_pairs_scheduled_between():
    """Async start/done pairs report the compute literally scheduled
    between them (compiled modules are scheduled, so between-ness in
    the text is the schedule)."""
    hlo = """ENTRY %main (p0: f32[1024,256]) -> f32[1024,256] {
  %p0 = f32[1024,256]{1,0} parameter(0)
  %cps = (f32[8,256]{1,0}, f32[8,256]{1,0}, u32[], u32[]) collective-permute-start(%p0), channel_id=3, source_target_pairs={{0,1},{1,2}}
  %interior = f32[1022,256]{1,0} fusion(%p0), kind=kLoop, calls=%fused
  %cpd = f32[8,256]{1,0} collective-permute-done(%cps)
  %out = f32[1024,256]{1,0} fusion(%interior, %cpd), kind=kLoop, calls=%fused2
}"""
    rep = T.overlap_report(hlo_text=hlo)
    assert rep["collectives"] == 1 and rep["async_pairs"] == 1
    (rec,) = rep["per_collective"]
    assert rec["async"] and rec["done"] == "cpd"
    assert rec["scheduled_ops"] == 1
    assert rec["scheduled_bytes"] == 1022 * 256 * 4
    assert rep["overlapped_bytes"] == 1022 * 256 * 4
    # dataflow freedom agrees (the interior consumes no permute data)
    assert rec["independent_bytes"] == 1022 * 256 * 4


def test_overlap_report_excludes_data_movement():
    """pad/slice/concatenate shuffles must not masquerade as hidden
    compute; an independent fusion counts."""
    hlo = """ENTRY %main (p0: f32[64]) -> f32[64] {
  %p0 = f32[64]{0} parameter(0)
  %shuffle = f32[128]{0} pad(%p0), padding=0_64
  %work = f32[64]{0} fusion(%p0), kind=kLoop, calls=%f
  %ar = f32[64]{0} all-reduce(%work), channel_id=1, replica_groups={{0,1}}, to_apply=%add
  %out = f32[64]{0} fusion(%ar), kind=kLoop, calls=%g
}"""
    rep = T.overlap_report(hlo_text=hlo)
    assert rep["collectives"] == 1
    # %shuffle is movement, %work feeds the collective, %out consumes
    # it: nothing is overlappable
    assert rep["overlappable_bytes"] == 0
    hlo_free = hlo.replace("fusion(%p0)", "fusion(%shuffle)").replace(
        "all-reduce(%work)", "all-reduce(%p0)"
    )
    rep2 = T.overlap_report(hlo_text=hlo_free)
    # now %work is independent of the collective and counts
    assert rep2["overlappable_bytes"] == 64 * 4


def test_overlap_report_dedups_overlapping_windows():
    """Compute inside SEVERAL overlapping start/done windows (the
    overlapped stencil's shape: all starts, interior, all dones) must
    book once in the summary, not once per pair."""
    hlo = """ENTRY %main (p0: f32[64,256]) -> f32[64,256] {
  %p0 = f32[64,256]{1,0} parameter(0)
  %cps.1 = (f32[8,256]{1,0}, f32[8,256]{1,0}, u32[], u32[]) collective-permute-start(%p0), channel_id=1, source_target_pairs={{0,1}}
  %cps.2 = (f32[8,256]{1,0}, f32[8,256]{1,0}, u32[], u32[]) collective-permute-start(%p0), channel_id=2, source_target_pairs={{1,0}}
  %interior = f32[62,256]{1,0} fusion(%p0), kind=kLoop, calls=%f
  %cpd.1 = f32[8,256]{1,0} collective-permute-done(%cps.1)
  %cpd.2 = f32[8,256]{1,0} collective-permute-done(%cps.2)
  %out = f32[64,256]{1,0} fusion(%interior, %cpd.1, %cpd.2), kind=kLoop, calls=%g
}"""
    rep = T.overlap_report(hlo_text=hlo)
    assert rep["async_pairs"] == 2
    interior = 62 * 256 * 4
    # each pair sees the interior in its own window...
    for rec in rep["per_collective"]:
        assert rec["scheduled_bytes"] == interior
    # ...but the summary books it once
    assert rep["scheduled_bytes"] == interior
    assert rep["overlapped_bytes"] == interior


def test_rs_ag_rejected_on_ring_tier(comm8):
    """A forced decomposition must never be silently dropped: the ring
    tier has no rs+ag form, so rs_ag=True there is a loud error."""
    with pytest.raises(ValueError, match="ring"):
        allreduce(jnp.zeros(8, jnp.float32), comm8, backend="ring",
                  rs_ag=True)


def test_traffic_cli_overlap_and_records(tmp_path):
    from smi_tpu.__main__ import main

    hlo = tmp_path / "dump.hlo"
    hlo.write_text(
        "%ar.1 = f32[128]{0} all-reduce(%x), channel_id=2, "
        "replica_groups={{0,1,2,3}}, to_apply=%add\n"
        "%free.1 = f32[32]{0} fusion(%y), kind=kLoop, calls=%f\n"
    )
    out = tmp_path / "report.json"
    assert main(["traffic", str(hlo), "--overlap", "-o", str(out)]) == 0
    report = json.loads(out.read_text())
    assert report["collectives"] == 1
    assert report["overlappable_bytes"] == 32 * 4
    # records mode
    assert main(["traffic", str(hlo)]) == 0
    # the CI gate trips on a collective-free dump
    empty = tmp_path / "empty.hlo"
    empty.write_text("%f.1 = f32[8]{0} fusion(%x), kind=kLoop\n")
    assert main(["traffic", str(empty), "--require-overlap"]) == 1
    # and on a missing file
    assert main(["traffic", str(tmp_path / "nope.hlo")]) == 1


# ---------------------------------------------------------------------------
# Chunked ring protocol x verified transport (satellite: framing)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,chunks", [(2, 2), (3, 2), (4, 3), (5, 2)])
def test_chunked_ring_protocol_schedule_fuzz(n, chunks):
    for seed in range(10):
        C.simulate_all_reduce_chunked(n, chunks, C.Strategy(seed))
        C.simulate_all_reduce_chunked(
            n, chunks, C.Strategy(seed), verified=True
        )


def test_chunked_ring_protocol_exhaustive_small():
    """Every scheduler interleaving of the 2-rank 2-chunk pipeline is
    clobber/deadlock/leak-free with correct delivery."""
    explored = C.explore_all_schedules(
        lambda: [
            C.all_reduce_chunked_rank(
                r, 2, [frozenset([(r, c)]) for c in range(2)],
                lambda a, b: a | b,
            )
            for r in range(2)
        ],
        max_schedules=100_000,
    )
    assert explored > 100


def test_chunked_ring_no_flow_control_still_delivers():
    """The pipelined schedule is conservative enough that even without
    credits the reference scheduler delivers (the fuzzer's clobber
    check stays armed; any unsafe interleaving would raise)."""
    for seed in range(5):
        C.simulate_all_reduce_chunked(3, 2, C.Strategy(seed),
                                      flow_control=False)


@pytest.mark.faults
@pytest.mark.parametrize("nth", [0, 1, 3])
def test_bitflip_in_pipelined_chunk_names_the_chunk(nth):
    """A BitFlipPayload inside a pipelined chunk must surface as an
    IntegrityError naming the damaged chunk: per-source wire sequence
    lanes keep advancing across the chunk interleave, so the seq in
    the error maps back to (step, chunk) = divmod(nth, chunks)."""
    chunks = 2
    plan = F.FaultPlan(bit_flips=(F.BitFlipPayload(src=1, nth=nth),))
    verdict = F.run_under_faults(
        "all_reduce_chunked", 3, plan, chunks=chunks
    )
    assert verdict.detected
    err = verdict.error
    assert isinstance(err, C.IntegrityError)
    assert err.kind == "checksum"
    assert err.src == 1
    assert err.seq == nth, "wire seq lane skipped or stalled"
    step, chunk = divmod(nth, chunks)
    assert f"seq={nth}" in str(err)
    # the seq names the right pipeline chunk
    assert chunk == nth % chunks


@pytest.mark.faults
def test_bitflip_in_pipelined_chunk_is_silent_on_bare_transport():
    plan = F.FaultPlan(bit_flips=(F.BitFlipPayload(src=0, nth=1),))
    with pytest.raises(F.SilentCorruption):
        F.run_under_faults(
            "all_reduce_chunked", 3, plan, chunks=2, verified=False
        )


@pytest.mark.faults
def test_reorder_across_pipeline_chunks_detected():
    """Swapping two consecutive frames — which under pipelining means
    two DIFFERENT chunks' payloads — trips the sequence check."""
    plan = F.FaultPlan(reorders=(F.ReorderedChunks(src=2, nth=2),))
    verdict = F.run_under_faults(
        "all_reduce_chunked", 4, plan, chunks=2
    )
    assert verdict.detected
    assert verdict.error.kind == "sequence"


def test_chunked_protocol_registered_but_not_in_default_sweep():
    assert "all_reduce_chunked" in F.CHUNKED_PROTOCOLS
    assert "all_reduce_chunked" not in F.PROTOCOLS  # chaos cells pinned
    with pytest.raises(ValueError, match="all_reduce_chunked"):
        F.run_under_faults("bogus", 3, None)


# ---------------------------------------------------------------------------
# Trace-time caching (satellite)
# ---------------------------------------------------------------------------


def test_ring_context_cache_hit_on_retrace():
    from smi_tpu.kernels import ring as kring

    before = kring._ring_context_cached.cache_info()
    args = (("cx", "cy"), 8, (("cx", 2), ("cy", 4)))
    a = kring._ring_context(*args)
    b = kring._ring_context(*args)
    c = kring._ring_context("cx", 2, (("cx", 2), ("cy", 4)))
    after = kring._ring_context_cached.cache_info()
    assert a is b, "retrace rebuilt the ring context"
    assert c is not a
    assert after.hits >= before.hits + 1
    assert after.misses >= before.misses + 2


def test_ring_context_cache_bounded_eviction_and_rehit():
    """The ring-context memo is BOUNDED (tuning-PR satellite: the r3
    ``maxsize=None`` was a slow leak under mesh-shape sweeps): filling
    past the bound evicts LRU entries, and an evicted key re-misses
    then re-hits — correctness is unaffected, only the rebuild cost."""
    import pytest
    from smi_tpu.kernels import ring as kring

    maxsize = kring.RING_CONTEXT_CACHE_MAX
    assert kring._ring_context_cached.cache_info().maxsize == maxsize
    kring._ring_context_cached.cache_clear()
    for i in range(maxsize + 8):
        kring._ring_context(f"evx{i}", 2, ((f"evx{i}", 2),))
    info = kring._ring_context_cached.cache_info()
    assert info.currsize <= maxsize
    assert info.misses == maxsize + 8
    # the earliest key was evicted: re-request misses (rebuild) ...
    before = kring._ring_context_cached.cache_info()
    a = kring._ring_context("evx0", 2, (("evx0", 2),))
    mid = kring._ring_context_cached.cache_info()
    assert mid.misses == before.misses + 1
    # ... and the rebuild re-enters the memo: the next call hits
    b = kring._ring_context("evx0", 2, (("evx0", 2),))
    after = kring._ring_context_cached.cache_info()
    assert after.hits == mid.hits + 1
    assert a is b
    assert a[1] == {"evx0": 2}, "rebuilt context must be equivalent"
    if maxsize < 8:  # pragma: no cover - config sanity
        pytest.fail("bound too small for real programs")


def test_routing_context_cache_hit_on_rebuild():
    from smi_tpu.parallel import routing as R

    topo = R.grid_topology(2, 3)
    builds0 = R._context_builds
    c1 = R.build_routing_context(topo)
    c2 = R.build_routing_context(topo)
    assert c1 is c2, "same-topology rebuild missed the cache"
    assert R._context_builds == builds0 + 1
    # equal-valued failure sets share one degraded context
    dev = topo.devices[0]
    d1 = R.build_routing_context(
        topo, excluded=R.FailureSet(links=frozenset({(dev, 0)}))
    )
    d2 = R.build_routing_context(
        topo, excluded=R.FailureSet(links=frozenset({(dev, 0)}))
    )
    assert d1 is d2 and d1 is not c1
    # a DIFFERENT topology object never aliases a cached context
    assert R.build_routing_context(R.grid_topology(2, 3)) is not c1


def test_egress_link_toward_reuses_cached_context():
    """The repeated-query path (one call per traced program point)
    must not rebuild the Dijkstra solve each time."""
    from smi_tpu.parallel import routing as R

    topo = R.grid_topology(1, 4)
    ctx = R.build_routing_context(topo)
    builds0 = R._context_builds
    for _ in range(5):
        R.egress_link_toward(topo.devices[0], topo.devices[2], ctx)
    assert R._context_builds == builds0


# ---------------------------------------------------------------------------
# Measurement path (satellite: perf marker + bench schema)
# ---------------------------------------------------------------------------


@pytest.mark.perf
def test_overlap_microbench_runs(comm8, tmp_path):
    from smi_tpu.benchmarks.micro import run_benchmark

    m = run_benchmark(
        "overlap", comm=comm8, out_dir=str(tmp_path),
        size_kb=8, sweep_kb=(4, 8), chunks=3, repeats=2, runs=2,
    )
    assert m.name == "overlap" and m.unit == "x"
    assert len(m.samples) == 2 and m.mean > 0
    sweep = m.config["sweep"]
    assert set(sweep) == {4, 8}
    for cell in sweep.values():
        assert cell["unchunked_mean_s"] > 0
        assert cell["chunked_mean_s"] > 0
    rep = m.config["overlap_report"]
    assert "error" in rep or rep["collectives"] >= 1
    assert (tmp_path / "overlap.dat").exists()


@pytest.mark.perf
def test_bench_line_schema_stays_single_line_parseable():
    """bench.py's stdout contract: ONE json line, legacy keys intact,
    overlap fields strictly additive (the driver's `parsed` extraction
    must keep working)."""
    import bench

    payload = {
        "metric": "stencil_8192x8192_cells_per_sec_per_chip",
        "value": 1.23e11,
        "unit": "cells/s/chip",
        "vs_baseline": 17.1,
        "vs_tpu_roofline": {"hbm": 0.08, "vpu": 0.21, "depth": 16},
        "overlap": {
            "collectives": 4,
            "async_pairs": 4,
            "overlappable_bytes": 4102,
            "overlap_fraction": 0.2,
        },
    }
    line = bench.render_line(payload)
    assert "\n" not in line
    parsed = json.loads(line)
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert parsed[key] == payload[key]
    assert parsed["overlap"]["overlappable_bytes"] == 4102
    # legacy payloads (no overlap field) still render
    legacy = {k: payload[k] for k in
              ("metric", "value", "unit", "vs_baseline")}
    assert json.loads(bench.render_line(legacy)) == legacy
    # dropping a legacy key is a loud error, not silent schema drift
    with pytest.raises(ValueError, match="legacy key"):
        bench.render_line({"metric": "m", "value": 1, "unit": "u"})
