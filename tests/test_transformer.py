"""Long-context transformer-block training on the (dp, sp) fake mesh:
the framework's layers composed — ring attention inside a block, local
autodiff through it, explicit DP+SP gradient psums, SGD update."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import smi_tpu as smi
from smi_tpu.models import transformer as tf


def _mesh(eight_devices, dp, sp):
    return smi.make_communicator(
        shape=(dp, sp), axis_names=("dp", "sp"),
        devices=eight_devices[: dp * sp],
    )


def _data(cfg, b, s, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(b, s, cfg.embed).astype(np.float32))
    y = jnp.asarray(rng.randn(b, s, cfg.embed).astype(np.float32))
    return x, y


@pytest.mark.parametrize("dp,sp", [(2, 2), (1, 4), (4, 1)])
def test_block_matches_reference(eight_devices, dp, sp):
    """The sharded block (batch folded into heads, ring attention over
    sp) equals the single-device reference."""
    from jax.sharding import PartitionSpec as P

    cfg = tf.BlockConfig(embed=64, heads=2, head_dim=128)
    comm = _mesh(eight_devices, dp, sp)
    params = tf.init_params(cfg, seed=1)
    b, s = dp * 2, sp * 8
    x, _ = _data(cfg, b, s)

    fn = jax.jit(jax.shard_map(
        lambda p, xx: tf.block_shard(p, xx, comm, cfg, use_flash=False),
        mesh=comm.mesh,
        in_specs=(P(), P("dp", "sp")), out_specs=P("dp", "sp"),
        check_vma=False,
    ))
    out = np.asarray(fn(params, x))
    ref = tf.reference_block(params, x, cfg)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_block_flash_tier_matches_jnp_tier(eight_devices):
    from jax.sharding import PartitionSpec as P

    cfg = tf.BlockConfig(embed=64, heads=2, head_dim=128, window=12)
    comm = _mesh(eight_devices, 2, 2)
    params = tf.init_params(cfg, seed=2)
    x, _ = _data(cfg, 4, 32, seed=3)

    def run(use_flash, interpret):
        fn = jax.jit(jax.shard_map(
            lambda p, xx: tf.block_shard(
                p, xx, comm, cfg, use_flash=use_flash, interpret=interpret
            ),
            mesh=comm.mesh,
            in_specs=(P(), P("dp", "sp")), out_specs=P("dp", "sp"),
            check_vma=False,
        ))
        return np.asarray(fn(params, x))

    np.testing.assert_allclose(
        run(True, True), run(False, False), rtol=2e-4, atol=2e-4
    )


def test_train_step_gradients_match_serial(eight_devices):
    """One distributed SGD step == the serial step on gathered data."""
    cfg = tf.BlockConfig(embed=32, heads=2, head_dim=128)
    comm = _mesh(eight_devices, 2, 2)
    params = tf.init_params(cfg, seed=4)
    b, s = 4, 16
    x, y = _data(cfg, b, s, seed=5)
    lr = 1e-2

    step = tf.make_train_step(comm, cfg, lr=lr, use_flash=False)
    new_params, loss = step(params, x, y)

    # serial reference: same loss/update computed on one device
    def serial_loss(p):
        from jax.sharding import PartitionSpec as P

        comm1 = smi.make_communicator(
            shape=(1, 1), axis_names=("d1", "s1"),
            devices=eight_devices[:1],
        )
        fn = jax.shard_map(
            lambda pp, xx: tf.block_shard(
                pp, xx, comm1, cfg, sp_axis="s1", use_flash=False
            ),
            mesh=comm1.mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )
        return jnp.sum((fn(p, x) - y) ** 2)

    n_total = b * s
    lref, gref = jax.value_and_grad(serial_loss)(params)
    np.testing.assert_allclose(
        float(loss), float(lref) / n_total, rtol=1e-4
    )
    for name in params:
        expect = params[name] - lr * gref[name] / n_total
        np.testing.assert_allclose(
            np.asarray(new_params[name]), np.asarray(expect),
            rtol=2e-3, atol=2e-5, err_msg=name,
        )


def test_training_reduces_loss(eight_devices):
    cfg = tf.BlockConfig(embed=32, heads=2, head_dim=128)
    comm = _mesh(eight_devices, 2, 4)
    params = tf.init_params(cfg, seed=6)
    x, y = _data(cfg, 4, 32, seed=7)
    step = tf.make_train_step(comm, cfg, lr=5e-2, use_flash=False)
    losses = []
    for _ in range(5):
        params, loss = step(params, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_train_step_bf16_compute(eight_devices):
    """Mixed precision: bf16 matmuls/attention with f32 master weights
    still trains (loss decreases) and tracks the f32 step loosely."""
    import jax.numpy as jnp

    from smi_tpu.parallel.mesh import make_communicator

    comm = make_communicator(
        shape=(2, 2), axis_names=("dp", "sp"), devices=eight_devices[:4]
    )
    cfg32 = tf.BlockConfig(embed=32, heads=2, head_dim=128)
    cfg16 = tf.BlockConfig(
        embed=32, heads=2, head_dim=128, compute_dtype="bfloat16"
    )
    params = tf.init_params(cfg32)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 16, 32).astype(np.float32))

    step32 = tf.make_train_step(comm, cfg32, use_flash=False)
    step16 = tf.make_train_step(comm, cfg16, use_flash=False)
    p32, l32 = step32(dict(params), x, x)
    p16, l16 = step16(dict(params), x, x)
    # params stay f32 master weights
    assert all(np.asarray(v).dtype == np.float32 for v in p16.values())
    np.testing.assert_allclose(float(l16), float(l32), rtol=5e-2)
    # a second bf16 step reduces the loss
    _, l16b = step16(p16, x, x)
    assert float(l16b) < float(l16)


def test_block_gqa_matches_reference(eight_devices):
    """Grouped-query attention at the model level: 4 query heads share
    2 K/V heads; the sharded block matches the repeat-KV reference."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from smi_tpu.parallel.mesh import make_communicator

    comm = make_communicator(
        shape=(1, 4), axis_names=("dp", "sp"), devices=eight_devices[:4]
    )
    cfg = tf.BlockConfig(embed=32, heads=4, head_dim=128, kv_heads=2)
    params = tf.init_params(cfg)
    assert params["wqkv"].shape == (32, (4 + 2 * 2) * 128)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(1, 32, 32).astype(np.float32))

    fn = jax.jit(
        jax.shard_map(
            lambda p, xx: tf.block_shard(p, xx, comm, cfg, use_flash=False),
            mesh=comm.mesh,
            in_specs=(P(), P("dp", "sp")),
            out_specs=P("dp", "sp"),
            check_vma=False,
        )
    )
    out = np.asarray(fn(params, x))
    ref = tf.reference_block(params, x, cfg)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_gqa_kv_heads_must_divide(eight_devices):
    with pytest.raises(ValueError, match="divide"):
        tf.init_params(tf.BlockConfig(embed=32, heads=4, kv_heads=3))


def test_stack_matches_serial_blocks(eight_devices):
    """A 3-layer stack (scan + per-block remat) equals three serial
    applications of the single block with each layer's params."""
    from jax.sharding import PartitionSpec as P

    cfg = tf.BlockConfig(embed=64, heads=2, head_dim=128)
    comm = _mesh(eight_devices, 2, 2)
    layers = 3
    stacked = tf.init_stack_params(cfg, layers, seed=5)
    x, _ = _data(cfg, 4, 32, seed=6)

    fn = jax.jit(jax.shard_map(
        lambda p, xx: tf.stack_shard(p, xx, comm, cfg, use_flash=False),
        mesh=comm.mesh,
        in_specs=(P(), P("dp", "sp")), out_specs=P("dp", "sp"),
        check_vma=False,
    ))
    out = np.asarray(fn(stacked, x))

    ref = x
    for i in range(layers):
        params_i = jax.tree_util.tree_map(lambda a, _i=i: a[_i], stacked)
        ref = tf.reference_block(params_i, ref, cfg)
    np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_stack_training_reduces_loss(eight_devices):
    """The layers>1 train step (stacked params, remat) trains: loss
    drops and every layer's parameters move."""
    cfg = tf.BlockConfig(embed=32, heads=2, head_dim=128)
    comm = _mesh(eight_devices, 2, 2)
    layers = 2
    params = tf.init_stack_params(cfg, layers, seed=7)
    x, y = _data(cfg, 4, 16, seed=8)
    step = tf.make_train_step(comm, cfg, lr=2e-3, use_flash=False,
                              layers=layers)
    p, first = step(params, x, y)
    losses = [float(first)]
    for _ in range(5):
        p, loss = step(p, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    for k in params:
        moved = np.abs(np.asarray(p[k]) - np.asarray(params[k]))
        # both layers' weights must have been updated
        assert moved[0].max() > 0 and moved[1].max() > 0, k
