"""Applications: the workloads the reference ships as ``examples/``.

Not neural models — SMI's "model zoo" is three HPC kernels exercising the
three communication patterns (SURVEY §2.7/§2.10):

- :mod:`smi_tpu.models.stencil` — 4-point Jacobi with 2-D halo exchange
  (spatial/sequence parallelism; the performance north star),
- :mod:`smi_tpu.models.gesummv` — distributed GESUMMV, operator split
  across two ranks with a streamed combine (tensor parallelism),
- :mod:`smi_tpu.models.kmeans` — data-parallel K-means with Reduce+Bcast
  collectives inside the iteration loop (data parallelism),
- :mod:`smi_tpu.models.onchip` — single-device baselines of stencil and
  GESUMMV (the reference's ``*_onchip`` variants).

Beyond reference parity, the long-context tier (first-class per the
framework goals, built on the same ring substrate as the pipelines of
SURVEY §2.10):

- :mod:`smi_tpu.models.ring_attention` — exact sequence-parallel
  attention (flash kernel tier on TPU; bf16, GQA, sliding windows,
  custom-VJP backward),
- :mod:`smi_tpu.models.transformer` — a trainable transformer block on
  a (dp, sp) mesh composing ring attention with DP gradient psums.

Each module carries a pure-numpy reference implementation used by the
tests, as the reference verifies against serial CPU code
(``stencil_smi.cpp:33-46``) and OpenBLAS (``gesummv_smi.cpp:300-301``).
"""
