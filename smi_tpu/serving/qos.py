"""QoS classes, brownout policy, and the named admission error.

The serving front-end (:mod:`smi_tpu.serving.frontend`) multiplexes
many tenants onto the channel substrate; this module is its *policy*
surface — the constants every other serving layer (and
``docs/robustness.md``, drift-guarded by ``tests/test_perf_docs.py``)
quotes:

- three priority classes, strictly ordered (``interactive`` >
  ``batch`` > ``best_effort``);
- the **brownout ceilings**: the fraction of the stream-credit pool a
  class may occupy before *that class* is shed. Ceilings are ordered
  lowest-class-lowest, which is what makes shedding
  lowest-class-first structural rather than heuristic: as occupancy
  climbs, ``best_effort`` hits its ceiling first, then ``batch``;
  ``interactive`` is refused only when the pool is fully exhausted;
- per-class **admission wait caps**: a request may queue at the
  admission edge at most this long before it is shed with a named
  error — the mechanism that keeps admission latency *bounded*
  instead of letting the pending queue become an unbounded buffer;
- per-class end-to-end **deadline budgets** (step-clock ticks),
  propagated from the request into per-chunk
  :class:`~smi_tpu.utils.watchdog.Deadline` checks.

Every rejection is a named :class:`AdmissionRejected` carrying the
tenant, the class, the queue depth at decision time, and the reason —
never a silent drop, and never after acceptance (an accepted stream
is delivered or the run fails loudly).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

#: Priority classes, highest priority first. The tuple order IS the
#: scheduling and admission order everywhere in the serving layer.
QOS_CLASSES = ("interactive", "batch", "best_effort")

#: Strict priority rank per class (lower = served first).
CLASS_PRIORITY = {c: i for i, c in enumerate(QOS_CLASSES)}

#: Brownout ceilings: class ``c`` is admitted only while pool
#: occupancy < ``ceil(ceiling * pool)``. best_effort 50%, batch 75%,
#: interactive 100% — the lowest class browns out first by
#: construction.
CLASS_POOL_CEILING = {
    "interactive": 1.0,
    "batch": 0.75,
    "best_effort": 0.5,
}

#: Admission wait caps (ticks): a pending request older than this is
#: shed with reason ``admission-timeout``. Interactive waits least —
#: it would rather fail fast than queue.
CLASS_ADMISSION_WAIT_TICKS = {
    "interactive": 12,
    "batch": 48,
    "best_effort": 96,
}

#: End-to-end deadline budgets (ticks) propagated from the request
#: into per-chunk Deadline checks. Sized to absorb a failure-detection
#: window (~60 ticks) plus a full replay to an heir — an accepted
#: stream's deadline firing is a *named* campaign failure, never a
#: silent loss.
CLASS_DEADLINE_TICKS = {
    "interactive": 400,
    "batch": 1200,
    "best_effort": 2400,
}

#: The p99 admission-latency bound (ticks) the campaigns assert for
#: the interactive class. Deliberately BELOW the interactive wait cap:
#: the cap makes latency bounded by shedding; this bound additionally
#: proves interactive requests actually jump the pending queue.
INTERACTIVE_P99_TICKS = 8


class AdmissionRejected(RuntimeError):
    """A request was refused at the admission edge — loudly.

    Carries the ``tenant``, the ``qos`` class, the ``queue_depth``
    (held stream credits + pending requests) at decision time, and
    the ``reason``:

    - ``tenant-rate`` — the tenant's token bucket is empty (per-tenant
      isolation; independent of class);
    - ``brownout:<class>`` — pool occupancy reached the class ceiling
      AND a full pool's worth of the class is already parked (the QoS
      shed path; lowest class first by ceiling order — the backpressure
      edge never buffers unboundedly);
    - ``admission-timeout`` — a parked request waited out its class's
      admission cap without a credit freeing.

    A rejection happens only BEFORE acceptance: once a stream holds a
    credit it is delivered bit-identically or the run fails with a
    named error — "accepted then lost" is the outcome the serving
    gates forbid.
    """

    def __init__(self, tenant: str, qos: str, queue_depth: int,
                 reason: str):
        super().__init__(
            f"admission rejected for tenant {tenant!r} class {qos}: "
            f"{reason} (queue depth {queue_depth})"
        )
        self.tenant = tenant
        self.qos = qos
        self.queue_depth = queue_depth
        self.reason = reason
        #: bounded flight-recorder tail attached by the gate when a
        #: recorder is wired (:mod:`smi_tpu.obs.events`) — the causal
        #: history behind the shed, riding the error itself
        self.recorder_tail: Optional[dict] = None

    def __reduce__(self):
        # exceptions pickle as cls(*args), but args holds the rendered
        # message, not the constructor fields — without this, a gate
        # whose rejection audit trail is copied (the model checker
        # forks worlds; campaign reports deep-copy cells) dies with a
        # TypeError instead of round-tripping. The third element
        # (state dict) keeps the flight-recorder tail on the copy.
        return (
            type(self),
            (self.tenant, self.qos, self.queue_depth, self.reason),
            {"recorder_tail": self.recorder_tail},
        )


def check_qos(qos: str) -> str:
    if qos not in QOS_CLASSES:
        raise ValueError(
            f"unknown QoS class {qos!r}; known: {QOS_CLASSES}"
        )
    return qos


@dataclasses.dataclass
class Request:
    """One tenant request: a stream of chunk payloads to deliver.

    ``stream_id`` is the tenant-scoped transient stream identity
    (tenant, per-tenant sequence number) — the serving analog of the
    reference's per-message transient channels. ``deadline_ticks``
    defaults to the class budget. ``base_rank`` (>= 0) overrides the
    tenant-hash routing with an explicit base destination — the MoE
    expert-dispatch path, where the stream must reach a specific
    expert's home rank; failover to heirs still rides
    ``membership.route_owner`` on top of it. ``None`` keeps the hash
    routing, byte-for-byte the pre-MoE behaviour.
    """

    tenant: str
    qos: str
    chunks: Tuple
    arrived_at: int
    stream_id: Tuple[str, int] = ("", -1)
    deadline_ticks: Optional[int] = None
    base_rank: Optional[int] = None

    def __post_init__(self):
        check_qos(self.qos)
        if not self.chunks:
            raise ValueError("a request must carry at least one chunk")
        if self.deadline_ticks is None:
            self.deadline_ticks = CLASS_DEADLINE_TICKS[self.qos]


def percentile(samples, q: float) -> Optional[float]:
    """Deterministic nearest-rank percentile (no numpy dependency in
    the pure-Python serving core). ``None`` on an empty sample set."""
    import math

    if not samples:
        return None
    ordered = sorted(samples)
    # nearest-rank: ceil(q * N), 1-indexed
    rank = max(1, math.ceil(q * len(ordered)))
    return float(ordered[rank - 1])
